"""AST lint engine: rule registry, suppressions, baseline, file walking.

The engine is deliberately small and rule-agnostic: a rule is a class
with an ``id``, a ``hint`` and a ``check(ctx)`` generator; registering it
(via :func:`register`) is all a later PR needs to add a checker (~30
lines including the rule body).  Everything cross-cutting lives here:

- per-line ``# colearn: noqa(RULE[,RULE]): reason`` suppressions (bare
  ``# colearn: noqa`` suppresses every rule on that line);
- a checked-in JSON baseline (fingerprints of accepted findings — see
  findings.Finding.fingerprint) subtracted from the report;
- dead-suppression detection (CL000): a noqa comment that suppressed
  nothing is itself a finding, so suppressions cannot rot in place;
- unreasoned-suppression detection (CL022): a live rule-listed noqa
  without a ``: reason`` suffix is itself a finding — every suppression
  must say why (concurrency suppressions should cite a witness-clean
  soak).  Blanket ``# colearn: noqa`` is exempt but CL000 still retires
  it when dead;
- ``[tool.colearn.lint]`` config from pyproject.toml (rule
  enable/disable lists, path excludes, baseline path).

The engine never imports jax or any heavyweight dependency — ``colearn
lint`` must stay a fast, CPU-only pre-test gate (scripts/lint.py).
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import json
import os
import re
import tokenize
from typing import Iterable, Iterator, Optional

from colearn_federated_learning_tpu.analysis.findings import Finding

_NOQA_RE = re.compile(
    r"#\s*colearn:\s*noqa(?:\s*\(\s*(?P<rules>[A-Z]{2}\d{3}"
    r"(?:\s*,\s*[A-Z]{2}\d{3})*)\s*\))?"
    r"(?P<reason>\s*:\s*\S.*)?"
)
_HOT_RE = re.compile(r"#\s*colearn:\s*hot\b")

DEAD_SUPPRESSION_RULE = "CL000"
UNREASONED_SUPPRESSION_RULE = "CL022"
PARSE_ERROR_RULE = "CL999"


# ---------------------------------------------------------------- context --
class FileContext:
    """Everything a rule needs about one source file, parsed once."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # part tuple of the path, e.g. ("colearn_...", "comm", "broker.py")
        self.parts = tuple(self.relpath.split("/"))
        # {lineno: comment text} — real COMMENT tokens only, so a
        # docstring that merely mentions the noqa marker cannot suppress.
        self.comments: dict = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass

    def in_dir(self, dirname: str) -> bool:
        """True when the file lives under a directory named ``dirname``
        anywhere on its repo-relative path (``comm``, ``faults``, ...)."""
        return dirname in self.parts[:-1]

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def hot_lines(self) -> set:
        """Line numbers carrying a ``# colearn: hot`` marker (CL006 scope
        extension for host-side per-round/per-step loops)."""
        return {ln for ln, text in self.comments.items()
                if _HOT_RE.search(text)}


# ----------------------------------------------------------------- rules --
class Rule:
    """Base class; subclasses set ``id``/``title``/``hint`` and implement
    ``check``."""

    id: str = ""
    title: str = ""
    hint: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=self.id, path=ctx.relpath, line=line, col=col,
                       message=message,
                       hint=self.hint if hint is None else hint,
                       line_text=ctx.line_text(line))


_REGISTRY: dict = {}


def register(cls):
    """Class decorator: add a Rule subclass to the global registry."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


def registered_rules() -> dict:
    """``{rule_id: rule_class}`` — importing analysis.rules populates it."""
    from colearn_federated_learning_tpu.analysis import rules as _rules  # noqa: F401

    return dict(_REGISTRY)


# ----------------------------------------------------------------- config --
@dataclasses.dataclass
class LintConfig:
    enable: Optional[list] = None        # None = every registered rule
    disable: tuple = ()
    exclude: tuple = ()                  # fnmatch patterns on relpath
    baseline: str = "lint_baseline.json"

    @classmethod
    def from_pyproject(cls, root: str) -> "LintConfig":
        """Read ``[tool.colearn.lint]``; silently default when the file or
        table is absent (the linter must run on a bare checkout)."""
        path = os.path.join(root, "pyproject.toml")
        if not os.path.exists(path):
            return cls()
        try:
            import tomllib  # py >= 3.11
        except ImportError:
            try:
                import tomli as tomllib
            except ImportError:
                return cls()
        with open(path, "rb") as f:
            doc = tomllib.load(f)
        table = doc.get("tool", {}).get("colearn", {}).get("lint", {})
        return cls(
            enable=table.get("enable"),
            disable=tuple(table.get("disable", ())),
            exclude=tuple(table.get("exclude", ())),
            baseline=table.get("baseline", "lint_baseline.json"),
        )

    def active_rules(self) -> list:
        rules = registered_rules()
        wanted = self.enable if self.enable is not None else sorted(rules)
        out = []
        for rid in wanted:
            if rid in self.disable:
                continue
            if rid not in rules:
                raise ValueError(
                    f"unknown lint rule {rid!r}; registered: {sorted(rules)}"
                )
            out.append(rules[rid]())
        return out

    def excluded(self, relpath: str) -> bool:
        rel = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rel, pat) for pat in self.exclude)


# --------------------------------------------------------------- baseline --
def load_baseline(path: str) -> dict:
    """``{fingerprint: accepted count}``; a missing file is an empty
    baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    entries = doc.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}

def write_baseline(path: str, findings: Iterable[Finding]) -> dict:
    entries: dict = {}
    meta: dict = {}
    for f in findings:
        fp = f.fingerprint()
        entries[fp] = entries.get(fp, 0) + 1
        meta.setdefault(fp, f"{f.rule} {f.path}: {f.line_text[:60]}")
    doc = {
        "comment": "colearn lint baseline: accepted pre-existing findings; "
                   "regenerate with `colearn lint --write-baseline`",
        "entries": entries,
        "notes": meta,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return entries


# ----------------------------------------------------------------- result --
@dataclasses.dataclass
class LintResult:
    findings: list                 # unsuppressed, un-baselined (reported)
    suppressed: int = 0            # silenced by an inline noqa marker
    baselined: int = 0             # silenced by the baseline file
    files: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_dict(self) -> dict:
        counts: dict = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "findings": [f.to_dict() for f in self.findings],
            "counts": counts,
            "files": self.files,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }


# ----------------------------------------------------------------- engine --
def _iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


class LintEngine:
    """Run the registered rules over files; apply suppressions + baseline."""

    def __init__(self, config: Optional[LintConfig] = None,
                 root: Optional[str] = None,
                 check_dead_suppressions: bool = True):
        self.root = os.path.abspath(root or os.getcwd())
        self.config = config or LintConfig.from_pyproject(self.root)
        self.rules = self.config.active_rules()
        self.check_dead_suppressions = (
            check_dead_suppressions
            and DEAD_SUPPRESSION_RULE not in self.config.disable
        )
        self.check_unreasoned_suppressions = (
            UNREASONED_SUPPRESSION_RULE not in self.config.disable
        )

    # ------------------------------------------------------------------
    def _relpath(self, path: str) -> str:
        ap = os.path.abspath(path)
        if ap.startswith(self.root + os.sep):
            return os.path.relpath(ap, self.root)
        return path

    def _suppressions(self, ctx: FileContext) -> dict:
        """``{lineno: (set(rule_ids) | None, has_reason)}`` — a None rule
        set is a blanket noqa."""
        out: dict = {}
        for lineno, text in ctx.comments.items():
            m = _NOQA_RE.search(text)
            if not m:
                continue
            rules = m.group("rules")
            out[lineno] = (
                None if rules is None else
                {r.strip() for r in rules.split(",")},
                m.group("reason") is not None,
            )
        return out

    def lint_file(self, path: str) -> tuple:
        """(kept findings, suppressed count) for one file."""
        relpath = self._relpath(path)
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            ctx = FileContext(path, relpath, source)
        except SyntaxError as e:
            return ([Finding(rule=PARSE_ERROR_RULE,
                             path=relpath.replace(os.sep, "/"),
                             line=e.lineno or 1, col=(e.offset or 1) - 1,
                             message=f"cannot parse: {e.msg}",
                             line_text=(e.text or "").strip())], 0)
        raw: list = []
        for rule in self.rules:
            raw.extend(rule.check(ctx))
        supp = self._suppressions(ctx)
        used_lines: set = set()
        kept: list = []
        suppressed = 0
        for f in raw:
            entry = supp.get(f.line)
            rules_at = "absent" if entry is None else entry[0]
            if rules_at is None or (rules_at != "absent"
                                    and f.rule in rules_at):
                suppressed += 1
                used_lines.add(f.line)
            else:
                kept.append(f)
        if self.check_dead_suppressions:
            for lineno in sorted(set(supp) - used_lines):
                kept.append(Finding(
                    rule=DEAD_SUPPRESSION_RULE, path=ctx.relpath,
                    line=lineno, col=0,
                    message="dead suppression: this `# colearn: noqa` "
                            "silences nothing",
                    hint="remove the comment (or fix the rule list in "
                         "parentheses)",
                    line_text=ctx.line_text(lineno),
                ))
        if self.check_unreasoned_suppressions:
            for lineno in sorted(used_lines):
                rules_at, has_reason = supp[lineno]
                if rules_at is None or has_reason:
                    continue
                kept.append(Finding(
                    rule=UNREASONED_SUPPRESSION_RULE, path=ctx.relpath,
                    line=lineno, col=0,
                    message="suppression without a reason: append "
                            "`: <why this is safe>` to the noqa",
                    hint="e.g. `# colearn: noqa(CL019): witness-clean "
                         "in chaos --tree-async --lock-witness`",
                    line_text=ctx.line_text(lineno),
                ))
        return kept, suppressed

    def run(self, paths: Iterable[str],
            baseline_path: Optional[str] = None) -> LintResult:
        if baseline_path is None:
            baseline_path = os.path.join(self.root, self.config.baseline)
        budget = dict(load_baseline(baseline_path))
        result = LintResult(findings=[])
        for path in _iter_py_files(paths):
            if self.config.excluded(self._relpath(path)):
                continue
            result.files += 1
            kept, suppressed = self.lint_file(path)
            result.suppressed += suppressed
            for f in kept:
                fp = f.fingerprint()
                if budget.get(fp, 0) > 0:
                    budget[fp] -= 1
                    result.baselined += 1
                else:
                    result.findings.append(f)
        result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return result
