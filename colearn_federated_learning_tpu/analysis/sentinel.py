"""SLO/regression sentinel over committed benchmark + round JSONL.

``results/*.jsonl`` records what the benches measured; until now nothing
*enforced* it — a PR could halve rounds/sec and CI would stay green.
The sentinel turns the bench trajectory into a gate: declarative SLO
rules live in ``[tool.colearn.slo]`` in pyproject.toml, each rule
selects rows from a JSONL file, aggregates one field, and bounds the
result.  ``colearn sentinel`` (and the CI step wrapping it) exits
non-zero on any violation and emits a machine-readable verdict.

Rule shape (``[[tool.colearn.slo.rules]]``)::

    id    = "fleet-1m-rounds-per-sec"      # unique, stable
    file  = "results/fleet_bench.jsonl"    # repo-root relative
    where = { bench = "fleet_round", devices = 1000000 }  # row filter
    field = "rounds_per_sec"               # numeric field to aggregate
    agg   = "min"                          # min|max|mean|sum|count
    min   = 0.01                           # floor (and/or ``max`` ceiling)
    allow_missing = false                  # missing file/rows = violation

Only order-independent aggregations are offered — verdicts MUST be
stable under reordered JSONL rows (appending re-runs or merging shards
must not flip a verdict), so there is deliberately no "last"/"first".

Rolling-window rules (``window = N`` in the table) extend the gate from
static bench rows to LIVE round history: the trailing ``window`` rows
are aggregated and compared against the ``baseline`` rows immediately
before them, as a ratio with a tolerance band — e.g. "p99 round time
over the last 5 rounds ≤ 1.5× the prior 20-round median".  Rows are
sorted by ``order_by`` (default ``round``) before windowing, so the
verdict stays reorder-stable like everything else here.

Window rule shape::

    id        = "live-round-time-tail"
    file      = "results/rounds.jsonl"
    field     = "round_time_s"
    window    = 5          # trailing rows under test
    baseline  = 20         # rows immediately before the window
    agg       = "p99"      # p50|p90|p99|median|mean|min|max over window
    baseline_agg = "median"   # same choices; default median
    max_ratio = 1.5        # window_agg / baseline_agg ceiling
    order_by  = "round"    # sort key; default "round"
"""

from __future__ import annotations

import glob
import json
import os
from typing import Optional

__all__ = [
    "SloRule",
    "WindowRule",
    "evaluate_slo",
    "load_rules",
    "load_jsonl_rows",
    "render_verdict",
    "rule_from_table",
]

_AGGS = ("min", "max", "mean", "sum", "count")
_WINDOW_AGGS = ("min", "max", "mean", "median", "p50", "p90", "p99")


def _window_agg(values: list, agg: str) -> float:
    if agg == "min":
        return min(values)
    if agg == "max":
        return max(values)
    if agg == "mean":
        return sum(values) / len(values)
    ordered = sorted(values)
    q = {"median": 0.50, "p50": 0.50, "p90": 0.90, "p99": 0.99}[agg]
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[max(0, idx)]


class SloRule:
    """One declarative bound on an aggregate of JSONL rows."""

    def __init__(self, id: str, file: str, field: str = "",
                 agg: str = "min", where: Optional[dict] = None,
                 min: Optional[float] = None, max: Optional[float] = None,
                 allow_missing: bool = False):
        if agg not in _AGGS:
            raise ValueError(
                f"slo rule {id!r}: agg {agg!r} not in {_AGGS} "
                "(only order-independent aggregations are allowed)")
        if min is None and max is None:
            raise ValueError(f"slo rule {id!r}: needs min and/or max")
        if agg != "count" and not field:
            raise ValueError(f"slo rule {id!r}: agg {agg!r} needs a field")
        self.id = id
        self.file = file
        self.field = field
        self.agg = agg
        self.where = dict(where or {})
        self.min = min
        self.max = max
        self.allow_missing = allow_missing

    @classmethod
    def from_table(cls, table: dict) -> "SloRule":
        unknown = set(table) - {"id", "file", "field", "agg", "where",
                                "min", "max", "allow_missing"}
        if unknown:
            raise ValueError(
                f"slo rule {table.get('id')!r}: unknown keys "
                f"{sorted(unknown)}")
        return cls(
            id=table["id"], file=table["file"],
            field=table.get("field", ""), agg=table.get("agg", "min"),
            where=table.get("where"), min=table.get("min"),
            max=table.get("max"),
            allow_missing=bool(table.get("allow_missing", False)),
        )

    def matches(self, row: dict) -> bool:
        return all(row.get(k) == v for k, v in self.where.items())

    # -------------------------------------------------------- evaluate --
    def evaluate(self, root: str) -> dict:
        """Verdict dict for this rule against files under ``root``.
        ``ok`` is the only field a gate needs; the rest is diagnosis."""
        out = {"id": self.id, "file": self.file, "agg": self.agg,
               "field": self.field, "min": self.min, "max": self.max,
               "ok": False, "value": None, "rows": 0, "reason": None}
        paths = sorted(glob.glob(os.path.join(root, self.file)))
        if not paths:
            if self.allow_missing:
                out.update(ok=True, reason="missing_allowed")
            else:
                out["reason"] = "file_missing"
            return out
        rows = []
        for path in paths:
            rows.extend(load_jsonl_rows(path))
        rows = [r for r in rows if self.matches(r)]
        out["rows"] = len(rows)
        if not rows:
            if self.allow_missing:
                out.update(ok=True, reason="no_rows_allowed")
            else:
                out["reason"] = "no_matching_rows"
            return out
        if self.agg == "count":
            value = float(len(rows))
        else:
            vals = [float(r[self.field]) for r in rows
                    if isinstance(r.get(self.field), (int, float))]
            if not vals:
                out["reason"] = f"field_missing:{self.field}"
                return out
            if self.agg == "min":
                value = min(vals)
            elif self.agg == "max":
                value = max(vals)
            elif self.agg == "sum":
                value = sum(vals)
            else:
                value = sum(vals) / len(vals)
        out["value"] = value
        if self.min is not None and value < self.min:
            out["reason"] = f"below_min:{value:.6g}<{self.min:.6g}"
            return out
        if self.max is not None and value > self.max:
            out["reason"] = f"above_max:{value:.6g}>{self.max:.6g}"
            return out
        out["ok"] = True
        return out


class WindowRule:
    """Rolling-window anomaly rule: aggregate of the trailing ``window``
    rows vs the ``baseline`` rows immediately before them, bounded as a
    ratio.  Rows sort by ``order_by`` before windowing, so appending
    rows out of order (merged shards, re-runs) cannot flip the verdict."""

    def __init__(self, id: str, file: str, field: str, window: int,
                 baseline: int, agg: str = "p99",
                 baseline_agg: str = "median",
                 max_ratio: Optional[float] = None,
                 min_ratio: Optional[float] = None,
                 order_by: str = "round", where: Optional[dict] = None,
                 allow_missing: bool = False):
        if agg not in _WINDOW_AGGS:
            raise ValueError(
                f"slo rule {id!r}: window agg {agg!r} not in "
                f"{_WINDOW_AGGS}")
        if baseline_agg not in _WINDOW_AGGS:
            raise ValueError(
                f"slo rule {id!r}: baseline_agg {baseline_agg!r} not in "
                f"{_WINDOW_AGGS}")
        if max_ratio is None and min_ratio is None:
            raise ValueError(
                f"slo rule {id!r}: needs max_ratio and/or min_ratio")
        if not field:
            raise ValueError(f"slo rule {id!r}: window rule needs a field")
        if int(window) < 1 or int(baseline) < 1:
            raise ValueError(
                f"slo rule {id!r}: window and baseline must be >= 1")
        self.id = id
        self.file = file
        self.field = field
        self.window = int(window)
        self.baseline = int(baseline)
        self.agg = agg
        self.baseline_agg = baseline_agg
        self.max_ratio = max_ratio
        self.min_ratio = min_ratio
        self.order_by = order_by
        self.where = dict(where or {})
        self.allow_missing = allow_missing

    @classmethod
    def from_table(cls, table: dict) -> "WindowRule":
        unknown = set(table) - {"id", "file", "field", "where", "window",
                                "baseline", "agg", "baseline_agg",
                                "max_ratio", "min_ratio", "order_by",
                                "allow_missing"}
        if unknown:
            raise ValueError(
                f"slo rule {table.get('id')!r}: unknown keys "
                f"{sorted(unknown)}")
        return cls(
            id=table["id"], file=table["file"],
            field=table.get("field", ""),
            window=table["window"],
            baseline=table.get("baseline", table["window"]),
            agg=table.get("agg", "p99"),
            baseline_agg=table.get("baseline_agg", "median"),
            max_ratio=table.get("max_ratio"),
            min_ratio=table.get("min_ratio"),
            order_by=table.get("order_by", "round"),
            where=table.get("where"),
            allow_missing=bool(table.get("allow_missing", False)),
        )

    def matches(self, row: dict) -> bool:
        return all(row.get(k) == v for k, v in self.where.items())

    # -------------------------------------------------------- evaluate --
    def evaluate(self, root: str) -> dict:
        """Verdict dict, same field contract as :meth:`SloRule.evaluate`
        (``min``/``max`` carry the ratio band) plus the window/baseline
        aggregates for diagnosis."""
        out = {"id": self.id, "file": self.file,
               "agg": f"{self.agg}[{self.window}]"
                      f"/{self.baseline_agg}[{self.baseline}]",
               "field": self.field, "min": self.min_ratio,
               "max": self.max_ratio, "ok": False, "value": None,
               "rows": 0, "reason": None,
               "window_value": None, "baseline_value": None}
        paths = sorted(glob.glob(os.path.join(root, self.file)))
        if not paths:
            if self.allow_missing:
                out.update(ok=True, reason="missing_allowed")
            else:
                out["reason"] = "file_missing"
            return out
        rows = []
        for path in paths:
            rows.extend(load_jsonl_rows(path))
        rows = [r for r in rows if self.matches(r)
                and isinstance(r.get(self.field), (int, float))
                and isinstance(r.get(self.order_by), (int, float))]
        out["rows"] = len(rows)
        need = self.window + self.baseline
        if len(rows) < need:
            # Too little history to judge — a short clean run must not
            # fail the gate unless the operator opted into strictness.
            if self.allow_missing:
                out.update(ok=True,
                           reason=f"insufficient_rows:{len(rows)}<{need}")
            else:
                out["reason"] = f"insufficient_rows:{len(rows)}<{need}"
            return out
        # Sort by the order key (ties broken by the field value, so even
        # duplicate keys can't make the verdict depend on file order).
        rows.sort(key=lambda r: (float(r[self.order_by]),
                                 float(r[self.field])))
        vals = [float(r[self.field]) for r in rows]
        trail = vals[-self.window:]
        base = vals[-(self.window + self.baseline):-self.window]
        window_value = _window_agg(trail, self.agg)
        baseline_value = _window_agg(base, self.baseline_agg)
        out["window_value"] = window_value
        out["baseline_value"] = baseline_value
        if baseline_value <= 0:
            # A non-positive baseline makes the ratio meaningless; treat
            # as unjudgeable rather than dividing through zero.
            out["reason"] = f"baseline_not_positive:{baseline_value:.6g}"
            if self.allow_missing:
                out["ok"] = True
            return out
        value = window_value / baseline_value
        out["value"] = value
        if self.max_ratio is not None and value > self.max_ratio:
            out["reason"] = (
                f"above_max_ratio:{value:.6g}>{self.max_ratio:.6g}")
            return out
        if self.min_ratio is not None and value < self.min_ratio:
            out["reason"] = (
                f"below_min_ratio:{value:.6g}<{self.min_ratio:.6g}")
            return out
        out["ok"] = True
        return out


def rule_from_table(table: dict):
    """Dispatch one ``[[tool.colearn.slo.rules]]`` table: the presence
    of ``window`` selects the rolling-window rule, everything else is a
    static :class:`SloRule` exactly as before."""
    if "window" in table:
        return WindowRule.from_table(table)
    return SloRule.from_table(table)


# ---------------------------------------------------------------- loading --
def load_jsonl_rows(path: str) -> list:
    """Decodable dict rows of a JSONL file.  A torn final line is
    tolerated (live round logs are appended by running processes); torn
    interior lines raise — that is corruption, not concurrency."""
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    rows = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break
            raise ValueError(f"corrupt JSONL at {path}:{i + 1}")
        if isinstance(doc, dict):
            rows.append(doc)
    return rows


def load_rules(root: str) -> list:
    """``[[tool.colearn.slo.rules]]`` from pyproject.toml; ``[]`` when
    the file, parser, or table is absent (sentinel must no-op cleanly on
    a bare checkout)."""
    path = os.path.join(root, "pyproject.toml")
    if not os.path.exists(path):
        return []
    try:
        import tomllib  # py >= 3.11
    except ImportError:
        try:
            import tomli as tomllib
        except ImportError:
            return []
    with open(path, "rb") as f:
        doc = tomllib.load(f)
    tables = doc.get("tool", {}).get("colearn", {}).get(
        "slo", {}).get("rules", [])
    rules = [rule_from_table(t) for t in tables]
    seen = set()
    for r in rules:
        if r.id in seen:
            raise ValueError(f"duplicate slo rule id {r.id!r}")
        seen.add(r.id)
    return rules


# --------------------------------------------------------------- verdicts --
def evaluate_slo(root: str, rules: Optional[list] = None) -> dict:
    """Evaluate every rule; the machine-readable verdict the CI gate
    consumes.  ``ok`` iff every rule passed AND at least one rule exists
    (an empty rule set passing silently would be a fake green)."""
    if rules is None:
        rules = load_rules(root)
    results = [r.evaluate(root) for r in rules]
    violations = [r for r in results if not r["ok"]]
    return {
        "schema": "colearn-slo-verdict-v1",
        "root": os.path.abspath(root),
        "rules": len(results),
        "violations": len(violations),
        "ok": bool(results) and not violations,
        "results": results,
    }


def render_verdict(verdict: dict) -> str:
    lines = []
    for res in verdict.get("results", []):
        mark = "ok " if res["ok"] else "FAIL"
        bound = []
        if res.get("min") is not None:
            bound.append(f">= {res['min']:g}")
        if res.get("max") is not None:
            bound.append(f"<= {res['max']:g}")
        value = ("-" if res.get("value") is None
                 else f"{res['value']:.6g}")
        line = (f"[{mark}] {res['id']}: {res['agg']}"
                f"({res.get('field') or 'rows'}) = {value} "
                f"(want {' and '.join(bound)}, rows={res['rows']})")
        if res.get("reason") and not res["ok"]:
            line += f" — {res['reason']}"
        lines.append(line)
    if not verdict.get("results"):
        lines.append("no SLO rules configured ([[tool.colearn.slo.rules]])")
    lines.append("")
    lines.append("sentinel verdict: "
                 + ("OK" if verdict.get("ok") else "VIOLATION"))
    return "\n".join(lines)
