"""Finding model for the AST lint engine.

A finding is one rule violation at one source location.  Its baseline
fingerprint deliberately excludes the line NUMBER (hashing the rule id,
the repo-relative path and the stripped source text instead), so a
baselined finding survives unrelated edits above it — the same contract
ruff/flake8 baselines use.
"""

from __future__ import annotations

import dataclasses
import hashlib


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str                 # "CL001"
    path: str                 # repo-relative, forward slashes
    line: int                 # 1-based
    col: int                  # 0-based
    message: str
    hint: str = ""            # fix hint shown by the human reporter
    line_text: str = ""       # stripped source of the offending line

    def fingerprint(self) -> str:
        key = f"{self.rule}:{self.path}:{self.line_text}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message, "hint": self.hint,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out
