"""Dropout-tolerant secure aggregation: the mask-recovery protocol core.

Pattern source: Bonawitz et al., "Practical Secure Aggregation for
Federated Learning on User-Held Data" (PAPERS.md, 1611.04482 — pattern
only).  The pairwise masking in privacy/secure_agg.py cancels exactly
only when EVERY cohort member's masked update reaches the aggregate; one
dropped client leaves its partners' mask halves orphaned in the sum.
This module supplies the recovery algebra the wire plane
(comm/coordinator.py + comm/worker.py) runs each secure round:

- Shamir t-of-n secret sharing over GF(2^521 − 1) (a Mersenne prime
  comfortably above the 512-bit DH exponents it must carry), so the
  coordinator can reconstruct a DEAD client's session secret — and with
  it every orphaned pairwise mask — from any ``t`` surviving
  shareholders instead of requiring every survivor to answer;
- the DOUBLE-MASK self-mask seed ``b_u`` (fresh per round): a client's
  wire update is ``delta + pairwise_masks + PRG(b_u)``, so a coordinator
  that reconstructs a client's session secret after falsely reporting it
  dropped still cannot unmask an update that actually folded — for
  folded clients the survivors reveal the ``b_u`` share, for dead
  clients the session-secret share, and the worker-side exclusivity
  ledger refuses to ever reveal both for one (client, round);
- share-transport encryption: shares travel THROUGH the untrusted
  coordinator, one ciphertext per (origin, destination) pair under a
  keystream derived from the pair's Diffie-Hellman secret
  (comm/keyexchange.py) with a direction- and round-separated context —
  the coordinator relays bytes it cannot read;
- the analytic mask-cost model backing the fleetsim k-sweep
  (scripts/bench_fleet.py): per-device PRG FLOPs and share bytes under
  the DisAgg-style group-local layering (masks span a group plus its
  aggregator, never the global cohort), demonstrating O(group +
  neighbors) per-client work with no O(cohort²) term.

Threshold convention: a client Shamir-shares into ``n = |recovery set|``
shares (its pairing partners for the round) and recovery needs
``t = max(1, ceil(secure_agg_threshold · n))`` of them.  Fewer than
``t`` surviving shares is a HARD failure — the round is discarded (the
Bonawitz convention: a sum with orphaned masks is garbage and must never
be released as an aggregate).

Honest trust statement: this defeats a passive (honest-but-curious)
coordinator and tolerates crash-faults at any protocol step.  Session
DH keys mean reconstructing a genuinely-dead client's session secret
also reveals its PAST pair keys; per-round key rotation would close
that and is out of scope here (documented in the README alongside the
existing enrollment-MITM caveat).
"""

from __future__ import annotations

import hashlib
import secrets

import numpy as np

# 13th Mersenne prime: 2^521 − 1.  Big enough for the 512-bit DH
# exponents (comm/keyexchange.py) as single shares — no limb splitting.
PRIME = (1 << 521) - 1
SECRET_BYTES = 66                  # ceil(521 / 8): one field element
_SHARE_CONTEXT = b"colearn-sharewrap-v1"
_SELF_CONTEXT = b"colearn-selfmask-v1"

# One encrypted share payload: session-secret share ‖ self-mask share.
SHARE_PAYLOAD_BYTES = 2 * SECRET_BYTES


class RecoveryError(Exception):
    """Mask recovery cannot complete (insufficient or inconsistent
    shares); the round's aggregate must be discarded."""


def random_secret() -> int:
    """A fresh per-round self-mask seed b_u, uniform in the field."""
    while True:
        b = secrets.randbits(521)
        if 0 < b < PRIME:
            return b


def threshold_count(n_shares: int, fraction: float) -> int:
    """Shares required to reconstruct: ``max(1, ceil(fraction · n))``.
    ``0`` when there is nothing to share (a solo cohort has no recovery
    set — and, symmetrically, applies no self-mask)."""
    if n_shares <= 0:
        return 0
    if not 0.0 < fraction <= 1.0:
        raise ValueError(
            f"secure_agg_threshold must be in (0, 1], got {fraction}"
        )
    return max(1, -(-int(n_shares * fraction * 1e9) // 1_000_000_000))


def split_secret(secret: int, xs: list, t: int) -> dict:
    """Shamir split: ``{x: f(x)}`` for a uniform degree-``t−1`` polynomial
    with ``f(0) = secret``.  ``xs`` must be distinct and nonzero (callers
    use ``client_id + 1``)."""
    if not 0 <= secret < PRIME:
        raise ValueError("secret out of field range")
    if t < 1 or t > len(xs):
        raise ValueError(f"threshold {t} out of range for {len(xs)} shares")
    if len(set(xs)) != len(xs) or any(x == 0 for x in xs):
        raise ValueError("share x-coordinates must be distinct and nonzero")
    coeffs = [secret] + [secrets.randbelow(PRIME) for _ in range(t - 1)]
    out = {}
    for x in xs:
        acc = 0
        for c in reversed(coeffs):         # Horner
            acc = (acc * x + c) % PRIME
        out[int(x)] = acc
    return out


def reconstruct(shares: dict, t: int) -> int:
    """Lagrange interpolation at 0 from any ``t`` of the shares.
    Raises :class:`RecoveryError` below threshold."""
    if len(shares) < t or t < 1:
        raise RecoveryError(
            f"need {t} shares to reconstruct, have {len(shares)}"
        )
    pts = sorted(shares.items())[:t]
    total = 0
    for i, (xi, yi) in enumerate(pts):
        num, den = 1, 1
        for j, (xj, _) in enumerate(pts):
            if i == j:
                continue
            num = (num * (-xj)) % PRIME
            den = (den * (xi - xj)) % PRIME
        total = (total + yi * num * pow(den, -1, PRIME)) % PRIME
    return total


# ------------------------------------------------- share transport ------
def _stream(pair_secret: bytes, origin: int, dest: int, round_idx: int,
            n: int) -> bytes:
    """Keystream for one directed (origin → dest, round) share payload.
    Direction and round are baked into the key so the two directions of a
    pair — and every round — use independent streams."""
    key = hashlib.sha256(
        _SHARE_CONTEXT + pair_secret
        + int(origin).to_bytes(8, "big") + int(dest).to_bytes(8, "big")
        + int(round_idx).to_bytes(8, "big")
    ).digest()
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + ctr.to_bytes(4, "big")).digest()
        ctr += 1
    return out[:n]


def encrypt_share(pair_secret: bytes, origin: int, dest: int,
                  round_idx: int, s_share: int, b_share: int) -> str:
    """Hex ciphertext carrying (session-secret share, self-mask share)
    from ``origin`` to ``dest``, opaque to the relaying coordinator."""
    payload = (s_share.to_bytes(SECRET_BYTES, "big")
               + b_share.to_bytes(SECRET_BYTES, "big"))
    ks = _stream(pair_secret, origin, dest, round_idx, len(payload))
    return bytes(a ^ b for a, b in zip(payload, ks)).hex()


def decrypt_share(pair_secret: bytes, origin: int, dest: int,
                  round_idx: int, ciphertext: str) -> tuple:
    """(s_share, b_share) ints from :func:`encrypt_share` output."""
    raw = bytes.fromhex(ciphertext)
    if len(raw) != SHARE_PAYLOAD_BYTES:
        raise ValueError(
            f"share payload must be {SHARE_PAYLOAD_BYTES} bytes, "
            f"got {len(raw)}"
        )
    ks = _stream(pair_secret, origin, dest, round_idx, len(raw))
    payload = bytes(a ^ b for a, b in zip(raw, ks))
    return (int.from_bytes(payload[:SECRET_BYTES], "big"),
            int.from_bytes(payload[SECRET_BYTES:], "big"))


def commitment(secret: int) -> str:
    """Binding commitment to a self-mask seed, published alongside the
    shares so the coordinator can detect a corrupted reconstruction
    (wrong shares interpolate to SOME field element; the hash won't
    match) instead of silently subtracting a garbage self-mask."""
    return hashlib.sha256(
        _SELF_CONTEXT + secret.to_bytes(SECRET_BYTES, "big")
    ).hexdigest()


def self_mask_key(secret: int) -> np.ndarray:
    """uint32[2] PRNG key-data for a client's self-mask stream.  Expanded
    via privacy/secure_agg.pairwise_mask_with_keys with sign +1 (the
    round index folds in on-device, same as the pair masks)."""
    digest = hashlib.sha256(
        _SELF_CONTEXT + b"key" + secret.to_bytes(SECRET_BYTES, "big")
    ).digest()
    return np.frombuffer(digest[:8], dtype=">u4").astype(np.uint32)


# ------------------------------------------------- cost model -----------
# Threefry-style counter PRG: ~16 integer ops per generated float32
# (conservative; the exact figure varies by backend).
PRG_FLOPS_PER_ELEM = 16


def mask_cost(cohort: int, param_count: int, neighbors: int = 0,
              group_size: int = 0) -> dict:
    """Analytic per-device masking cost under the DisAgg-style layering.

    ``group_size == 0`` is the flat cohort (masks span everyone);
    ``group_size = g`` is group-local secure aggregation on
    fed/hierarchical.py groups — each device's masks span only its group,
    so per-device work is O(group + neighbors) and the GLOBAL cost is
    linear in the cohort, never O(cohort²).

    Returns per-device mask-PRG FLOPs (+1 stream for the self-mask),
    recovery-share bytes, and the flat-cohort quadratic total for the
    same cohort so the bench row can pin the separation.
    """
    if cohort < 1 or param_count < 1:
        raise ValueError("cohort and param_count must be >= 1")
    local = min(group_size, cohort) if group_size > 0 else cohort
    degree = local - 1 if neighbors <= 0 else min(neighbors, local - 1)
    streams = degree + 1                  # pair masks + the self-mask
    flat_degree = cohort - 1 if neighbors <= 0 else min(neighbors,
                                                        cohort - 1)
    return {
        "mask_flops_per_device": float(streams * param_count
                                       * PRG_FLOPS_PER_ELEM),
        "share_bytes_per_device": float(degree * SHARE_PAYLOAD_BYTES),
        "pairs_per_device": int(degree),
        # The cost a FLAT all-cohort graph pays in total: the O(cohort²)
        # term group-local masking removes (reported for the ratio
        # column, not paid).
        "flat_pairs_total": int(cohort * flat_degree // 2),
        "grouped_pairs_total": int(cohort * degree // 2),
    }
