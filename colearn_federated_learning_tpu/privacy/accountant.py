"""Rényi-DP accounting for DP-FedAvg (SURVEY.md §5: the reference ships
clip+noise hooks with no privacy-budget statement; the rebuild states the
budget).

Model: each round is one application of the SUBSAMPLED GAUSSIAN mechanism —
a cohort of ``q·N`` clients is sampled, each update is clipped to ``C`` and
the aggregate carries central Gaussian noise ``σ·C`` (privacy/dp.py scales
per-client noise by ``1/sqrt(cohort)`` so the sum has exactly that std).

Accounting is the standard RDP recipe (Abadi et al. 2016 moments
accountant, in the RDP formulation of Mironov 2017 / Mironov-Talwar-Zhang
2019, PAPERS.md — formulas only):

- per-round RDP at integer order α for sampling rate q, noise σ:
    ε_α = 1/(α-1) · log Σ_{k=0..α} C(α,k)(1-q)^{α-k} q^k · e^{(k²-k)/2σ²}
  (at q=1 this collapses to the exact Gaussian value α/2σ²),
- RDP composes additively over rounds: T rounds cost T·ε_α,
- conversion to (ε, δ)-DP:  ε = min_α [ T·ε_α + log(1/δ)/(α-1) ].

Pure numpy in log space; nothing here touches the training path.
"""

from __future__ import annotations

import math
from typing import Iterable, Optional

import numpy as np

DEFAULT_ORDERS: tuple[int, ...] = tuple(range(2, 65)) + (128, 256, 512)


def _log_binom(n: int, k: int) -> float:
    return math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1)


def subsampled_gaussian_rdp(q: float, noise_multiplier: float,
                            order: int) -> float:
    """Per-step RDP ε_α of the sampled Gaussian mechanism at INTEGER order.

    Exact for q=1 (plain Gaussian: α/(2σ²)); for q<1 the
    Mironov-Talwar-Zhang binomial-series bound.
    """
    if order < 2 or int(order) != order:
        raise ValueError(f"integer order >= 2 required, got {order}")
    if noise_multiplier <= 0.0:
        return math.inf
    if q <= 0.0:
        return 0.0
    if q > 1.0:
        raise ValueError(f"sampling rate must be <= 1, got {q}")
    sigma2 = noise_multiplier ** 2
    if q == 1.0:
        return order / (2.0 * sigma2)
    a = int(order)
    log_terms = [
        _log_binom(a, k)
        + (a - k) * math.log1p(-q)
        + (k * math.log(q) if k else 0.0)
        + (k * k - k) / (2.0 * sigma2)
        for k in range(a + 1)
    ]
    m = max(log_terms)
    log_sum = m + math.log(sum(math.exp(t - m) for t in log_terms))
    return log_sum / (a - 1)


def rdp_to_eps_delta(total_rdp: np.ndarray, orders: np.ndarray,
                     delta: float) -> float:
    """(ε, δ) from accumulated RDP: ε = min_α [ε_α·T + log(1/δ)/(α-1)]."""
    if delta <= 0.0 or delta >= 1.0:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    eps = total_rdp + math.log(1.0 / delta) / (orders - 1.0)
    return float(np.min(eps))


class RdpAccountant:
    """Tracks cumulative (ε, δ) over federated rounds.

    One instance per experiment; call :meth:`step` after each round and read
    :meth:`epsilon`.  The per-round RDP curve is precomputed (every round
    applies the identical mechanism), so per-round cost is one vector min.
    """

    def __init__(self, noise_multiplier: float, sampling_rate: float,
                 delta: float = 1e-5,
                 orders: Optional[Iterable[int]] = None):
        self.noise_multiplier = float(noise_multiplier)
        self.sampling_rate = float(sampling_rate)
        self.delta = float(delta)
        self.orders = np.asarray(sorted(set(orders or DEFAULT_ORDERS)),
                                 dtype=np.float64)
        self._per_round = self._curve(self.sampling_rate)
        self._steps = 0
        self.total_rdp = np.zeros_like(self._per_round)

    @classmethod
    def from_config(cls, fed_config,
                    sampling_rate: float) -> Optional["RdpAccountant"]:
        """The accountant a FedConfig implies, or None when DP is off —
        the ONE place the enable condition lives (engine + coordinator)."""
        if fed_config.dp_clip > 0.0 and fed_config.dp_noise_multiplier > 0.0:
            return cls(noise_multiplier=fed_config.dp_noise_multiplier,
                       sampling_rate=sampling_rate,
                       delta=fed_config.dp_delta)
        return None

    def _curve(self, q: float,
               noise_multiplier: Optional[float] = None) -> np.ndarray:
        z = self.noise_multiplier if noise_multiplier is None else noise_multiplier
        return np.asarray([
            subsampled_gaussian_rdp(q, z, int(a)) for a in self.orders
        ])

    @property
    def steps(self) -> int:
        return self._steps

    @steps.setter
    def steps(self, value: int) -> None:
        """Reset to ``value`` rounds of the CONSTANT configured mechanism
        (checkpoint resume in the on-device engine, whose q never varies)."""
        self._steps = int(value)
        self.total_rdp = self._per_round * self._steps

    def step(self, n: int = 1, sampling_rate: Optional[float] = None,
             noise_multiplier: Optional[float] = None) -> None:
        """Record ``n`` more rounds.  ``sampling_rate`` /
        ``noise_multiplier`` override the configured mechanism for these
        rounds — the socket coordinator's cohort fraction moves as workers
        join/leave, and dropouts shrink the REALIZED central noise below
        nominal; RDP composes additively across heterogeneous rounds."""
        if sampling_rate is None and noise_multiplier is None:
            rdp = self._per_round
        else:
            q = (self.sampling_rate if sampling_rate is None
                 else min(1.0, float(sampling_rate)))
            rdp = self._curve(q, noise_multiplier)
        self.total_rdp = self.total_rdp + n * rdp
        self._steps += n

    def epsilon(self, delta: Optional[float] = None) -> float:
        """Cumulative ε at ``delta`` after the recorded steps."""
        if self._steps == 0:
            return 0.0
        if not np.isfinite(self.total_rdp).any():
            return math.inf
        return rdp_to_eps_delta(self.total_rdp, self.orders,
                                delta if delta is not None else self.delta)
