"""Differential-privacy hooks on client updates, running on-device.

BASELINE.json ``north_star`` requires "the DP-noise ... masking hooks run
on-device".  This is the standard DP-FedAvg recipe (central DP simulated at
the clients): clip each client delta to L2 norm ``clip``, then add Gaussian
noise with per-client std ``clip * noise_multiplier / sqrt(cohort)`` so the
SUM of cohort-many independent noises has std ``clip * noise_multiplier`` —
exactly the central Gaussian mechanism.  When DP is on the engine switches
to uniform (not example-count) weighting, as clipped-update aggregation
requires for a well-defined sensitivity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.utils import pytrees


def clip_by_global_norm(delta, clip: float):
    """Scale the whole pytree so its global L2 norm is at most ``clip``."""
    norm = pytrees.tree_global_norm(delta)
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return pytrees.tree_scale(delta, scale), norm


def add_gaussian_noise(delta, std, key: jax.Array):
    leaves, treedef = jax.tree.flatten(delta)
    keys = jax.random.split(key, len(leaves))
    noised = [
        leaf + std * jax.random.normal(k, leaf.shape, jnp.float32).astype(leaf.dtype)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noised)


def clip_and_noise(delta, clip, noise_multiplier: float, cohort_size: int,
                   key: jax.Array):
    """Per-client DP hook: clip to ``clip``, noise for central std
    ``clip * noise_multiplier`` after summing ``cohort_size`` clients.
    ``clip`` may be a traced scalar (adaptive clipping)."""
    delta, _ = clip_by_global_norm(delta, clip)
    if noise_multiplier > 0.0:
        std = clip * noise_multiplier / jnp.sqrt(float(max(cohort_size, 1)))
        delta = add_gaussian_noise(delta, std, key)
    return delta


def clip_and_noise_with_bit(delta, clip, noise_multiplier: float,
                            cohort_size: int, key: jax.Array):
    """Adaptive-clipping variant: also returns the quantile bit
    ``b = 1{‖Δ‖ ≤ clip}`` computed on the PRE-clip norm (Andrew et al.
    1905.03871, pattern only — the per-round geometric clip update lives in
    the engine's round epilogue)."""
    clipped, norm = clip_by_global_norm(delta, clip)
    if noise_multiplier > 0.0:
        std = clip * noise_multiplier / jnp.sqrt(float(max(cohort_size, 1)))
        clipped = add_gaussian_noise(clipped, std, key)
    return clipped, (norm <= clip).astype(jnp.float32)


def adaptive_noise_multiplier(z: float, bit_noise: float) -> float:
    """Update-noise multiplier z_Δ such that (update, bit) JOINTLY cost the
    configured total multiplier ``z``: z_Δ = (z⁻² − (2σ_b)⁻²)^(−1/2)
    (Andrew et al. — the bit query has sensitivity 1 = 2·(1/2), hence the
    2σ_b).  Requires z < 2σ_b; the RDP accountant can then keep charging
    the single-mechanism rate z per round."""
    if z <= 0.0:
        return 0.0
    if 2.0 * bit_noise <= z:
        raise ValueError(
            f"adaptive clipping needs bit_noise > z/2 (z={z}, "
            f"bit_noise={bit_noise}); raise dp_bit_noise"
        )
    return (z ** -2 - (2.0 * bit_noise) ** -2) ** -0.5


def adaptive_clip_update(clip, bit_frac, target_quantile: float,
                         clip_lr: float):
    """Geometric clip-norm step toward the target quantile:
    C ← C · exp(−η_C (b̃ − γ)).  Pure jnp — runs inside the round program,
    so the clip state stays a device scalar across rounds."""
    return clip * jnp.exp(-clip_lr * (bit_frac - target_quantile))
