"""Differential-privacy hooks on client updates, running on-device.

BASELINE.json ``north_star`` requires "the DP-noise ... masking hooks run
on-device".  This is the standard DP-FedAvg recipe (central DP simulated at
the clients): clip each client delta to L2 norm ``clip``, then add Gaussian
noise with per-client std ``clip * noise_multiplier / sqrt(cohort)`` so the
SUM of cohort-many independent noises has std ``clip * noise_multiplier`` —
exactly the central Gaussian mechanism.  When DP is on the engine switches
to uniform (not example-count) weighting, as clipped-update aggregation
requires for a well-defined sensitivity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.utils import pytrees


def clip_by_global_norm(delta, clip: float):
    """Scale the whole pytree so its global L2 norm is at most ``clip``."""
    norm = pytrees.tree_global_norm(delta)
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return pytrees.tree_scale(delta, scale), norm


def add_gaussian_noise(delta, std, key: jax.Array):
    leaves, treedef = jax.tree.flatten(delta)
    keys = jax.random.split(key, len(leaves))
    noised = [
        leaf + std * jax.random.normal(k, leaf.shape, jnp.float32).astype(leaf.dtype)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noised)


def clip_and_noise(delta, clip: float, noise_multiplier: float, cohort_size: int,
                   key: jax.Array):
    """Per-client DP hook: clip to ``clip``, noise for central std
    ``clip * noise_multiplier`` after summing ``cohort_size`` clients."""
    delta, _ = clip_by_global_norm(delta, clip)
    if noise_multiplier > 0.0:
        std = clip * noise_multiplier / jnp.sqrt(float(max(cohort_size, 1)))
        delta = add_gaussian_noise(delta, std, key)
    return delta
