"""On-device privacy hooks: DP clip+noise, secure-aggregation masking, and
RDP (ε, δ) accounting."""

from colearn_federated_learning_tpu.privacy.accountant import (  # noqa: F401
    RdpAccountant,
)
from colearn_federated_learning_tpu.privacy.dp import clip_and_noise  # noqa: F401
from colearn_federated_learning_tpu.privacy.secure_agg import pairwise_mask  # noqa: F401
