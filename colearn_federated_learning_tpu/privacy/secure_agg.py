"""Secure-aggregation pairwise masking, running on-device.

Pattern source: Bonawitz et al., "Practical Secure Aggregation for
Federated Learning on User-Held Data" (PAPERS.md, 1611.04482 — pattern
only).  Each ordered pair (i, j) of cohort members shares a symmetric PRNG
key (utils/prng.pair_mask_key); client i adds +PRG(k_ij) for every j > i
and −PRG(k_ij) for every j < i.  Summed over the cohort the masks cancel
exactly, so the aggregate equals the true sum while any single client's
submitted update is uniformly masked.

This is the honest-but-curious core of the protocol (no dropout-recovery
secret sharing); it demonstrates the masking hook the BASELINE north_star
requires.  Both members of a pair expand bit-identical float32 streams, so
cancellation is exact up to float32 summation rounding (residual ~1e-7·std
per element — negligible against typical 1e-3-scale deltas).  Cost is
O(cohort² · params) PRG work — fine for the cross-device cohorts (≤ a few
hundred) it is meant for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.utils import prng, pytrees


def _sample_tree(template, key: jax.Array, std: float = 1.0):
    # Masks are ALWAYS float32: cancellation relies on both pair members
    # expanding bit-identical streams and on summation happening at float32
    # precision (bfloat16 masks of std ~1 would quantize away ~1e-3 deltas).
    leaves, treedef = jax.tree.flatten(template)
    keys = jax.random.split(key, len(leaves))
    out = [
        std * jax.random.normal(k, leaf.shape, jnp.float32)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, out)


def pairwise_mask(template, base_key: jax.Array, client_id, cohort_ids,
                  round_idx, std: float = 1.0):
    """The mask client ``client_id`` adds to its (pre-weighted) update.

    ``cohort_ids``: (C,) int32 ids of all cohort members this round
    (including ``client_id`` itself — the self-pair contributes sign 0).
    """
    zeros = pytrees.tree_zeros_like(template)

    def body(j, acc):
        other = cohort_ids[j]
        k = prng.pair_mask_key(base_key, client_id, other, round_idx)
        sign = jnp.sign(other - client_id).astype(jnp.float32)
        noise = _sample_tree(template, k, std)
        return jax.tree.map(lambda a, n: a + sign.astype(n.dtype) * n, acc, noise)

    return jax.lax.fori_loop(0, cohort_ids.shape[0], body, zeros)


def mask_update(update, base_key: jax.Array, client_id, cohort_ids, round_idx,
                std: float = 1.0):
    """Add this client's pairwise mask to its update (before aggregation)."""
    mask = pairwise_mask(update, base_key, client_id, cohort_ids, round_idx, std)
    return pytrees.tree_add(update, mask)
