"""Secure-aggregation pairwise masking, running on-device.

Pattern source: Bonawitz et al., "Practical Secure Aggregation for
Federated Learning on User-Held Data" (PAPERS.md, 1611.04482 — pattern
only).  Each ordered pair (i, j) of cohort members shares a symmetric PRNG
key (utils/prng.pair_mask_key); client i adds +PRG(k_ij) for every j > i
and −PRG(k_ij) for every j < i.  Summed over the cohort the masks cancel
exactly, so the aggregate equals the true sum while any single client's
submitted update is uniformly masked.

This module is the masking/cancellation CORE; who can derive the pair
keys differs by plane, and that difference is the trust model:

- ENGINE plane (simulation): keys derive from the shared experiment seed
  (utils/prng.pair_mask_key).  One process holds every client anyway, so
  this only demonstrates the algebra the BASELINE north_star requires.
- WIRE plane (socket deployment): pair keys come from Diffie-Hellman
  shared secrets negotiated over the broker (comm/keyexchange.py) and
  enter through :func:`pairwise_mask_with_keys` — the coordinator holds
  public keys and masked updates only and CANNOT unmask any single
  client (tests/test_comm.py pins this).  An ACTIVE broker-controlling
  attacker could still MITM the exchange; authenticated enrollment is
  out of scope and documented.

Both members of a pair expand bit-identical float32 streams, so
cancellation is exact up to float32 summation rounding (residual ~1e-7·std
per element — negligible against typical 1e-3-scale deltas).

Two pairing graphs:

- ``neighbors=0`` (default): the complete graph — every pair shares a
  mask.  O(cohort² · params) PRG work; fine up to ~a-few-dozen cohorts.
- ``neighbors=k``: a k-regular RANDOM RING — cohort members are permuted
  by a per-round PRG (everyone derives the identical permutation from the
  shared experiment key), and each client pairs with its k nearest ring
  neighbors.  O(cohort · k · params) PRG work, so the flagship cohort=256
  configs stop paying a 256×-per-client masking bill; unmasking one
  client's update requires its k ring neighbors to collude (the
  random-graph construction of Bell et al. 2020, PAPERS.md — pattern
  only).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.utils import prng, pytrees


def _sample_tree(template, key: jax.Array, std: float = 1.0):
    # Masks are ALWAYS float32: cancellation relies on both pair members
    # expanding bit-identical streams and on summation happening at float32
    # precision (bfloat16 masks of std ~1 would quantize away ~1e-3 deltas).
    leaves, treedef = jax.tree.flatten(template)
    keys = jax.random.split(key, len(leaves))
    out = [
        std * jax.random.normal(k, leaf.shape, jnp.float32)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, out)


def ring_partner_table(base_key: jax.Array, member_ids, cohort_ids, round_idx,
                       neighbors: int):
    """Partner table for the per-round random ring, computed ONCE per round.

    All cohort members derive the IDENTICAL permutation (uniform scores
    keyed on (experiment key, round, member id) and an argsort), so the
    ring — and therefore every pair — is agreed without communication.

    ``member_ids``: (M,) the members to build rows for (a device's local
    cohort slice on a mesh); ``cohort_ids``: (C,) the full round cohort.
    Returns ``(M, neighbors)`` partner ids — exactly ``neighbors`` per
    member — or None when the cohort is too small for a ``neighbors``-
    regular ring without double-counting a pair (C <= neighbors + 1;
    callers fall back to the complete graph, which is CHEAPER there).

    ``neighbors`` must be even (ring offsets come in ± pairs): an odd
    degree cannot be realized and silently rounding would misstate the
    collusion threshold the degree promises.
    """
    if neighbors % 2 or neighbors < 2:
        raise ValueError(
            f"secure_agg_neighbors must be an even integer >= 2, got "
            f"{neighbors} (ring partners come in +/- offset pairs)"
        )
    C = cohort_ids.shape[0]
    k2 = neighbors // 2
    if k2 > (C - 1) // 2:
        return None                    # complete graph is smaller anyway
    rkey = prng.sampling_key(prng.mask_ring_key(base_key), round_idx)
    scores = jax.vmap(
        lambda i: jax.random.uniform(jax.random.fold_in(rkey, i))
    )(cohort_ids)
    ring = cohort_ids[jnp.argsort(scores)]
    pos = jnp.argmax(ring[None, :] == member_ids[:, None], axis=1)  # (M,)
    offs = jnp.concatenate([jnp.arange(1, k2 + 1), -jnp.arange(1, k2 + 1)])
    return ring[(pos[:, None] + offs[None, :]) % C]                 # (M, 2k2)


@jax.jit
def pairwise_mask(template, base_key: jax.Array, client_id, partner_ids,
                  round_idx, std: float = 1.0):
    """The mask client ``client_id`` adds to its (pre-weighted) update.

    ``partner_ids``: (P,) ids this client shares pair keys with — the whole
    cohort for complete-graph masking (the self-pair contributes sign 0),
    or this client's row of :func:`ring_partner_table`.

    Jitted AT MODULE LEVEL (as are the other mask expanders): the
    ``fori_loop`` body is a fresh closure every call, so an eager call
    re-traces and re-compiles the whole PRG expansion each time — ~seconds
    per cohort member per round, which is what blew the wire plane's round
    deadlines under the secure chaos soak.  A persistent jit cache keyed
    on (tree structure, partner count) pays one compile per shape instead.
    """
    zeros = pytrees.tree_zeros_like(template)

    def body(j, acc):
        other = partner_ids[j]
        k = prng.pair_mask_key(base_key, client_id, other, round_idx)
        sign = jnp.sign(other - client_id).astype(jnp.float32)
        noise = _sample_tree(template, k, std)
        return jax.tree.map(lambda a, n: a + sign.astype(n.dtype) * n, acc, noise)

    return jax.lax.fori_loop(0, partner_ids.shape[0], body, zeros)


@jax.jit
def mask_update(update, base_key: jax.Array, client_id, partner_ids, round_idx,
                std: float = 1.0):
    """Add this client's pairwise mask to its update (before aggregation)."""
    mask = pairwise_mask(update, base_key, client_id, partner_ids, round_idx,
                         std)
    return pytrees.tree_add(update, mask)


@jax.jit
def pairwise_mask_with_keys(template, pair_keys: jax.Array, signs: jax.Array,
                            round_idx, std: float = 1.0):
    """Pairwise mask from EXPLICIT per-pair PRNG keys — the wire-plane
    path, where pair keys come from Diffie-Hellman shared secrets
    (comm/keyexchange.py) that the coordinator cannot derive, instead of
    the shared experiment seed.

    ``pair_keys``: (P, 2) uint32 key-data rows, one per partner
    (symmetric: both pair members hold the identical row).
    ``signs``: (P,) float — +1 where this client's id is lower than the
    partner's, −1 where higher, 0 for the self-pair; the same ordering
    convention as :func:`pairwise_mask`, so summed over the cohort the
    masks cancel exactly.  The round index is folded into each key here,
    so one key exchange covers every round.
    """
    zeros = pytrees.tree_zeros_like(template)

    def body(j, acc):
        k = jax.random.fold_in(pair_keys[j], round_idx)
        noise = _sample_tree(template, k, std)
        return jax.tree.map(
            lambda a, n: a + signs[j].astype(n.dtype) * n, acc, noise
        )

    return jax.lax.fori_loop(0, pair_keys.shape[0], body, zeros)


@jax.jit
def mask_update_with_keys(update, pair_keys: jax.Array, signs: jax.Array,
                          round_idx, std: float = 1.0):
    """Explicit-key variant of :func:`mask_update` (wire plane / DH)."""
    mask = pairwise_mask_with_keys(update, pair_keys, signs, round_idx, std)
    return pytrees.tree_add(update, mask)


_SCALAR_STREAM_TAG = 0x7B17


@jax.jit
def mask_scalar(value, base_key: jax.Array, client_id, partner_ids,
                round_idx, std: float = 1.0):
    """Pairwise-mask one SCALAR side-channel value (e.g. the adaptive-
    clipping quantile bit).  Same cancellation algebra as the update
    masks — literally :func:`pairwise_mask` on a scalar template — but on
    a base key folded with a DISTINCT tag, so an observer can never
    difference a masked update leaf against the masked scalar to cancel a
    shared mask."""
    tagged = jax.random.fold_in(base_key, _SCALAR_STREAM_TAG)
    return value + pairwise_mask(
        jnp.zeros((), jnp.float32), tagged, client_id, partner_ids,
        round_idx, std,
    )


def partner_table(base_key: jax.Array, member_ids, cohort_ids, round_idx,
                  neighbors: int = 0):
    """(M, P) partner ids per member: the random ring when ``neighbors`` is
    set and the cohort supports it, else every member paired with the full
    cohort (complete graph)."""
    if neighbors > 0:
        table = ring_partner_table(base_key, member_ids, cohort_ids,
                                   round_idx, neighbors)
        if table is not None:
            return table
    return jnp.broadcast_to(
        cohort_ids[None, :], (member_ids.shape[0], cohort_ids.shape[0])
    )
