"""Multi-head attention with a pluggable core.

The projections (q/k/v/out) are ordinary ``nn.DenseGeneral`` matmuls — the
MXU work — and are IDENTICAL across cores, so the param pytree does not
depend on which core computes the softmax:

- ``dense``: single-device reference einsum (parallel/ring.py oracle).
- ``flash``: Pallas blockwise kernel (ops/attention.py) — no (L, L) matrix
  in HBM; interpret mode off-TPU.
- ``ring``:  sequence-parallel ring attention — REQUIRES being called
  inside ``shard_map`` with the sequence dim sharded over ``axis_name``
  (parallel/sp.py drives this).
- ``ulysses``: sequence-parallel all-to-all attention (heads re-sharded
  across the axis; parallel/ulysses.py) — same shard_map contract as
  ``ring``, needs ``num_heads`` divisible by the axis size.

Selected per-model via ``ModelConfig.attn_impl``.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

ATTN_IMPLS = ("dense", "flash", "ring", "ulysses")


class MultiHeadAttention(nn.Module):
    num_heads: int
    dtype: jnp.dtype = jnp.float32
    impl: str = "dense"
    axis_name: Optional[str] = None   # mesh axis (impl="ring"/"ulysses")
    causal: bool = False

    @nn.compact
    def __call__(self, x, kv_mask=None):
        """x: (B, L, D); kv_mask: optional (B, L) bool, False = padding."""
        D = x.shape[-1]
        if D % self.num_heads:
            raise ValueError(f"embed dim {D} not divisible by {self.num_heads} heads")
        head_dim = D // self.num_heads

        proj = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(self.num_heads, head_dim), dtype=self.dtype, name=name
        )
        q, k, v = proj("query")(x), proj("key")(x), proj("value")(x)

        if self.impl == "dense":
            from colearn_federated_learning_tpu.parallel.ring import dense_attention

            out = dense_attention(q, k, v, kv_mask, causal=self.causal)
        elif self.impl == "flash":
            from colearn_federated_learning_tpu.ops.attention import flash_attention

            out = flash_attention(q, k, v, kv_mask, causal=self.causal)
        elif self.impl == "ring":
            from colearn_federated_learning_tpu.parallel.ring import ring_attention

            if not self.axis_name:
                raise ValueError("impl='ring' needs axis_name (a mesh axis)")
            out = ring_attention(q, k, v, kv_mask, axis_name=self.axis_name,
                                 causal=self.causal)
        elif self.impl == "ulysses":
            from colearn_federated_learning_tpu.parallel.ulysses import (
                ulysses_attention,
            )

            if not self.axis_name:
                raise ValueError("impl='ulysses' needs axis_name (a mesh axis)")
            out = ulysses_attention(q, k, v, kv_mask,
                                    axis_name=self.axis_name,
                                    causal=self.causal)
        else:
            raise ValueError(f"unknown attn impl {self.impl!r}; use {ATTN_IMPLS}")

        return nn.DenseGeneral(features=D, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)
