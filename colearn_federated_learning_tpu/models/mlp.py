"""2-layer MLP — BASELINE config #1 ("FedAvg 2-layer MLP on MNIST").

Parity target: the reference's MNIST MLP-scale ``nn.Module`` (SURVEY.md §2
"Models: small nets ... MLP/CNN-scale"; reference source unavailable — see
SURVEY.md banner).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    num_classes: int = 10
    hidden_dim: int = 200
    depth: int = 2                      # hidden layers
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for _ in range(self.depth):
            x = nn.Dense(self.hidden_dim, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
