"""Temporal convolutional network for IoT traffic windows.

The reference's models are "small nets for anomaly detection" on IoT
network traffic (SURVEY.md §0/§2) — this is that family, TPU-first:
dilated 1-D convolutions (Bai et al. TCN pattern — receptive field grows
exponentially with depth) whose channel dims are MXU matmuls, GroupNorm
(no batch statistics — federated clients must not share normalization
state), residual blocks, masked-free static shapes.  Input: (B, T, F)
feature windows (rolling flow statistics); output: attack-family logits.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class TCNBlock(nn.Module):
    channels: int
    dilation: int
    kernel: int = 3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        """x: (B, T, C) — 'SAME' padding keeps T static across blocks."""
        h = nn.Conv(self.channels, (self.kernel,),
                    kernel_dilation=(self.dilation,), padding="SAME",
                    dtype=self.dtype)(x)
        h = nn.GroupNorm(num_groups=min(8, self.channels),
                         dtype=self.dtype)(h)
        h = nn.relu(h)
        h = nn.Conv(self.channels, (self.kernel,),
                    kernel_dilation=(self.dilation,), padding="SAME",
                    dtype=self.dtype)(h)
        h = nn.GroupNorm(num_groups=min(8, self.channels),
                         dtype=self.dtype)(h)
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1,), dtype=self.dtype)(x)
        return nn.relu(x + h)


class TCN(nn.Module):
    num_classes: int = 8
    width: int = 64
    depth: int = 4                    # dilations 1, 2, 4, ... 2^(depth-1)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for i in range(self.depth):
            x = TCNBlock(self.width, dilation=2 ** i, dtype=self.dtype)(x)
        pooled = jnp.mean(x.astype(jnp.float32), axis=1)   # (B, C)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(pooled)
