"""Small conv net — BASELINE config #2 ("FedAvg CNN on CIFAR-10").

Parity target: the reference's CNN-scale PyTorch module (SURVEY.md §2
"Models"; source unavailable — see SURVEY.md banner).  Design is TPU-first:
NHWC layout, bfloat16 compute, GroupNorm instead of BatchNorm — batch
statistics are a poor fit for federated local training (tiny per-client
batches, stats that would otherwise need cross-client sync) and GroupNorm
keeps the whole local round a pure function of (params, batch).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """Fold ``block x block`` spatial patches into channels:
    (N, H, W, C) -> (N, H/b, W/b, C·b²).  MFU lever for the stem conv —
    CIFAR's 3 input channels waste the MXU's 128-lane contraction dim,
    while 12 channels over 4x fewer positions tile it 4x better with the
    same receptive-field economics (PERF.md §1)."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, h // block, w // block, c * block * block
    )


class CNN(nn.Module):
    num_classes: int = 10
    width: int = 64
    dtype: jnp.dtype = jnp.float32
    stem: str = "conv"                # conv | space_to_depth
    norm: str = "group"               # group | none

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.stem not in ("conv", "space_to_depth"):
            raise ValueError(f"unknown stem {self.stem!r}")
        if self.norm not in ("group", "none"):
            raise ValueError(f"unknown norm {self.norm!r}")
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = space_to_depth(x, 2)
        for mult in (1, 2, 4):
            ch = self.width * mult
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            if self.norm == "group":
                x = nn.GroupNorm(num_groups=min(32, ch), dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            if self.norm == "group":
                x = nn.GroupNorm(num_groups=min(32, ch), dtype=self.dtype)(x)
            x = nn.relu(x)
            # The space_to_depth stem already halved H/W once; stop
            # pooling at 2x2 so the head still sees a spatial map.
            if x.shape[1] >= 2:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
