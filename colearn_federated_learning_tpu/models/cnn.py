"""Small conv net — BASELINE config #2 ("FedAvg CNN on CIFAR-10").

Parity target: the reference's CNN-scale PyTorch module (SURVEY.md §2
"Models"; source unavailable — see SURVEY.md banner).  Design is TPU-first:
NHWC layout, bfloat16 compute, GroupNorm instead of BatchNorm — batch
statistics are a poor fit for federated local training (tiny per-client
batches, stats that would otherwise need cross-client sync) and GroupNorm
keeps the whole local round a pure function of (params, batch).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CNN(nn.Module):
    num_classes: int = 10
    width: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for mult in (1, 2, 4):
            ch = self.width * mult
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.GroupNorm(num_groups=min(32, ch), dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.GroupNorm(num_groups=min(32, ch), dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
