"""Model zoo (flax.linen) for the five benchmark configs.

The reference's models are small PyTorch ``nn.Module`` subclasses
(SURVEY.md §2 "Models").  Here each family is a flax module built
MXU-first: channels-last conv, bfloat16 compute with float32 params, no
data-dependent Python control flow, so every client's forward/backward jits
into one fused XLA program.
"""

from colearn_federated_learning_tpu.models.registry import build_model  # noqa: F401
