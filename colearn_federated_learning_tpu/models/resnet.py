"""ResNet-18 (CIFAR variant) — BASELINE config #3 ("FedProx ResNet-18 on
CIFAR-100").

TPU-first design notes: NHWC layout, bfloat16 compute / float32 params,
GroupNorm instead of BatchNorm — federated local training with tiny
per-client batches makes batch statistics both noisy and a hidden piece of
non-param state that FedAvg would have to aggregate separately; GroupNorm
keeps the model a pure function of (params, batch), which is what lets one
``lax.scan`` express a whole local round (fed/local.py).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    channels: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.channels, (3, 3), strides=(self.stride, self.stride),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = nn.GroupNorm(num_groups=min(32, self.channels), dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=min(32, self.channels), dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.channels, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, dtype=self.dtype)(residual)
            residual = nn.GroupNorm(num_groups=min(32, self.channels),
                                    dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 100
    width: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        # CIFAR stem: 3x3, no max-pool (32x32 inputs).
        x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=min(32, self.width), dtype=self.dtype)(x)
        x = nn.relu(x)
        ch = self.width
        for stage, blocks in enumerate(self.stage_sizes):
            for b in range(blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = BasicBlock(ch, stride=stride, dtype=self.dtype)(x)
            ch *= 2
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def ResNet18(num_classes: int = 100, width: int = 64, dtype=jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, width=width,
                  dtype=dtype)
