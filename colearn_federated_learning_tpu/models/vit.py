"""ViT-B/16 — BASELINE config #5 ("Cross-silo ViT-B/16 on FEMNIST").

Pre-LN vision transformer: conv patch embedding (a single large matmul per
image on the MXU), learned position embeddings, class token, GELU MLPs.
Patch size adapts to small inputs (28x28 FEMNIST → 4x4 patches) while the
canonical 16 is used at 224 resolution; all shapes are static under jit.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from colearn_federated_learning_tpu.models.attention import MultiHeadAttention


class ViTBlock(nn.Module):
    embed_dim: int
    num_heads: int
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MultiHeadAttention(
            num_heads=self.num_heads, dtype=self.dtype, impl=self.attn_impl
        )(y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(self.embed_dim * self.mlp_ratio, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.embed_dim, dtype=self.dtype)(y)
        return x + y


class ViT(nn.Module):
    num_classes: int = 62
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    patch_size: int = 16
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "dense"
    # Rematerialize blocks under autodiff (models/bert.py ditto).
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, H, W, C = x.shape
        # Shrink the patch for small images so there are >= 4 patches/side.
        p = self.patch_size
        while p > 1 and (H // p) < 4:
            p //= 2
        x = nn.Conv(self.embed_dim, (p, p), strides=(p, p), dtype=self.dtype)(
            x.astype(self.dtype)
        )
        x = x.reshape((B, -1, self.embed_dim))                 # (B, N, D)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.embed_dim))
        x = jnp.concatenate([jnp.tile(cls.astype(self.dtype), (B, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], self.embed_dim)
        )
        x = x + pos.astype(self.dtype)
        block_cls = nn.remat(ViTBlock) if self.remat else ViTBlock
        for i in range(self.depth):
            # Explicit names pin param paths across remat (models/bert.py).
            x = block_cls(self.embed_dim, self.num_heads, dtype=self.dtype,
                          attn_impl=self.attn_impl,
                          name=f"ViTBlock_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32)(x[:, 0])
        return logits
