"""Model registry: config name → flax module + init helper."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.utils.config import ModelConfig


def _dtype(cfg: ModelConfig):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[cfg.dtype]


def build_model(cfg: ModelConfig, seq_axis_name: str | None = None):
    """Return the flax module for a ModelConfig.

    ``seq_axis_name``: mesh axis for sequence parallelism — only meaningful
    for text models with ``attn_impl="ring"``, which must then be applied
    inside ``shard_map`` with the sequence dim sharded over that axis.
    """
    dtype = _dtype(cfg)
    if seq_axis_name is not None and cfg.name not in ("bert", "moe_bert"):
        raise ValueError(
            "sequence parallelism is only supported for 'bert'/'moe_bert', "
            f"not {cfg.name!r}"
        )
    if cfg.remat and cfg.name not in ("bert", "moe_bert", "vit_b16"):
        raise ValueError(
            "remat is only implemented for the transformer families "
            f"(bert/moe_bert/vit_b16), not {cfg.name!r} — silently "
            "ignoring it would fake the memory savings"
        )
    if cfg.name == "mlp":
        from colearn_federated_learning_tpu.models.mlp import MLP

        return MLP(num_classes=cfg.num_classes, hidden_dim=cfg.hidden_dim,
                   depth=cfg.depth, dtype=dtype)
    if cfg.name == "cnn":
        from colearn_federated_learning_tpu.models.cnn import CNN

        return CNN(num_classes=cfg.num_classes, width=cfg.width, dtype=dtype,
                   stem=cfg.stem, norm=cfg.norm)
    if cfg.name == "resnet18":
        from colearn_federated_learning_tpu.models.resnet import ResNet18

        return ResNet18(num_classes=cfg.num_classes, width=cfg.width, dtype=dtype)
    if cfg.name == "bert":
        from colearn_federated_learning_tpu.models.bert import BertClassifier

        return BertClassifier(num_classes=cfg.num_classes, vocab_size=cfg.vocab_size,
                              embed_dim=cfg.width, depth=cfg.depth,
                              num_heads=cfg.num_heads, max_len=cfg.seq_len,
                              dtype=dtype, attn_impl=cfg.attn_impl,
                              seq_axis_name=seq_axis_name, remat=cfg.remat)
    if cfg.name == "moe_bert":
        from colearn_federated_learning_tpu.models.bert import BertClassifier

        # Same encoder as "bert" with MoE FFN blocks interleaved
        # (models/moe.py; expert banks shard over the model axis).
        return BertClassifier(num_classes=cfg.num_classes,
                              vocab_size=cfg.vocab_size, embed_dim=cfg.width,
                              depth=cfg.depth, num_heads=cfg.num_heads,
                              max_len=cfg.seq_len, dtype=dtype,
                              attn_impl=cfg.attn_impl,
                              seq_axis_name=seq_axis_name,
                              num_experts=cfg.num_experts, remat=cfg.remat)
    if cfg.name == "tcn":
        from colearn_federated_learning_tpu.models.tcn import TCN

        return TCN(num_classes=cfg.num_classes, width=cfg.width,
                   depth=cfg.depth, dtype=dtype)
    if cfg.name == "vit_b16":
        from colearn_federated_learning_tpu.models.vit import ViT

        return ViT(num_classes=cfg.num_classes, embed_dim=cfg.width,
                   depth=cfg.depth, num_heads=cfg.num_heads,
                   patch_size=cfg.patch_size, dtype=dtype,
                   attn_impl=cfg.attn_impl, remat=cfg.remat)
    raise KeyError(f"unknown model {cfg.name!r}")


def init_params(model, example_x, key: jax.Array):
    """Initialize float32 parameters for one example batch."""
    variables = model.init(key, example_x, train=False)
    if set(variables.keys()) != {"params"}:
        raise ValueError(
            f"model carries non-param collections {sorted(variables.keys())}; "
            "federated local training requires pure-param models "
            "(use GroupNorm/LayerNorm, not BatchNorm)"
        )
    return variables["params"]
