"""Mixture-of-Experts FFN (expert parallelism).

The reference has no MoE (SURVEY.md §2: EP absent — "models are tiny");
this layer is part of the rebuild's distributed superset and is designed
for the TPU from the start:

- **Static shapes**: routing uses the classic capacity-based one-hot
  dispatch/combine formulation (Mesh-TensorFlow / Switch Transformer
  lineage, PAPERS.md pattern only): every tensor is a fixed-size einsum
  operand, so the whole layer is jit-compatible and lands on the MXU —
  no ragged gathers, no data-dependent shapes.
- **Expert parallelism**: the expert banks are stacked ``(E, ...)`` params
  named ``experts_*``; parallel/tp.py shards their leading dim over the
  ``model`` mesh axis, and the GSPMD partitioner turns the dispatch/expert/
  combine einsums into per-shard matmuls plus the EP collectives.
- **Aux load-balance loss** (Switch: ``E · Σ_e f_e · p_e``) is ``sow``-n
  into the ``intermediates`` collection; the local trainer picks it up
  when training (fed/local.py) and it is a silent no-op everywhere else
  (flax ``sow`` does nothing when the collection is immutable).

Routing is top-2 with renormalized gates; tokens beyond an expert's
capacity ``C = ceil(top_k·N/E · capacity_factor)`` are dropped (their
block output is zero and the residual connection carries them through).
The encoder that hosts this layer is models/bert.py (``num_experts > 0``
swaps the block MLP for this module in every other block); under sequence
parallelism each sequence shard routes its LOCAL tokens with local
capacity — the standard choice, avoiding an all-to-all over the seq axis.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEFfn(nn.Module):
    """Capacity-based top-k mixture of expert FFNs over tokens."""

    embed_dim: int
    num_experts: int
    mlp_ratio: int = 4
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, token_mask=None):
        """``x``: (B, S, D); ``token_mask``: optional (B, S) bool, False =
        padding.  Masked tokens are excluded from routing entirely — they
        claim no expert capacity, produce zero layer output (the residual
        carries them), and do not enter the load-balance statistics."""
        B, S, D = x.shape
        E, K = self.num_experts, min(self.top_k, self.num_experts)
        F = D * self.mlp_ratio
        N = B * S
        C = max(1, int(-(-K * N * self.capacity_factor // E)))  # ceil

        xf = x.reshape(N, D)
        # Router in float32 for stable softmax; kept replicated (tp rules).
        logits = nn.Dense(E, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)                  # (N, E)

        gate_vals, expert_idx = jax.lax.top_k(probs, K)          # (N, K)
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9
        )

        # (N, K, E) routing one-hot — the single source for capacity
        # accounting, dispatch, and the aux statistics.  Padding tokens are
        # zeroed BEFORE the cumsum so they never occupy a capacity slot.
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)
        if token_mask is not None:
            mf = token_mask.reshape(N).astype(jnp.float32)        # (N,)
            onehot = onehot * token_mask.reshape(N, 1, 1).astype(jnp.int32)
        else:
            mf = jnp.ones((N,), jnp.float32)

        # Positions within each expert's buffer, rank-major: all rank-0
        # picks fill before any rank-1 pick, so primary routes win capacity.
        flat = onehot.transpose(1, 0, 2).reshape(K * N, E)       # rank-major
        pos_f = jnp.cumsum(flat, axis=0) - flat                  # (K*N, E)
        pos = (
            pos_f.reshape(K, N, E).transpose(1, 0, 2) * onehot
        ).sum(-1)                                                # (N, K)

        # dispatch (N, E, C): one-hot of (expert, position); over-capacity
        # tokens fall out because one_hot(pos >= C) is the zero row, and
        # masked tokens because their routing one-hot is already zero.
        # combine carries the gate weight on top.
        disp = (
            onehot.astype(self.dtype)[..., None]
            * jax.nn.one_hot(pos, C, dtype=self.dtype)[:, :, None, :]
        )                                                        # (N, K, E, C)
        combine = (disp * gate_vals[..., None, None].astype(self.dtype)).sum(1)
        disp = disp.sum(1)                                       # (N, E, C)

        up = self.param(
            "experts_up", nn.initializers.lecun_normal(), (E, D, F)
        ).astype(self.dtype)
        b_up = self.param(
            "experts_up_bias", nn.initializers.zeros, (E, F)
        ).astype(self.dtype)
        down = self.param(
            "experts_down", nn.initializers.lecun_normal(), (E, F, D)
        ).astype(self.dtype)
        b_down = self.param(
            "experts_down_bias", nn.initializers.zeros, (E, D)
        ).astype(self.dtype)

        xin = jnp.einsum("nec,nd->ecd", disp, xf.astype(self.dtype))
        h = nn.gelu(jnp.einsum("ecd,edf->ecf", xin, up) + b_up[:, None, :])
        y = jnp.einsum("ecf,efd->ecd", h, down) + b_down[:, None, :]
        out = jnp.einsum("nec,ecd->nd", combine, y)

        # Switch aux loss: E * sum_e fraction_routed_e * mean_prob_e over
        # PRIMARY routes of REAL tokens (minimized at uniform balance,
        # value 1.0).  Masked tokens are excluded from both statistics.
        denom = jnp.maximum(mf.sum(), 1.0)
        f_e = onehot[:, 0, :].astype(jnp.float32).sum(axis=0) / denom
        p_e = (probs * mf[:, None]).sum(axis=0) / denom
        self.sow("intermediates", "moe_aux", E * jnp.sum(f_e * p_e))

        return out.reshape(B, S, D)
