"""BERT-style text classifier — BASELINE config #4 ("FedAvg BERT-base on
AG-News, 50 text clients").

A from-scratch encoder (token + learned position embeddings, post-LN
transformer blocks, masked mean pooling, classification head).  Attention
and MLPs are plain ``nn.Dense``/einsum matmuls — large, batched, and
bfloat16-ready so XLA tiles them onto the MXU.  Token id 0 is padding and
is masked out of both attention and pooling.  Sequence length is static
(config.seq_len), so the whole model jits with no dynamic shapes.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.models.attention import MultiHeadAttention


class TransformerBlock(nn.Module):
    embed_dim: int
    num_heads: int
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "dense"
    attn_axis_name: Optional[str] = None
    num_experts: int = 0              # > 0: MoE FFN (models/moe.py)

    @nn.compact
    def __call__(self, x, pad_mask):
        # Post-LN (BERT-style): sublayer -> residual -> LayerNorm.
        attn = MultiHeadAttention(
            num_heads=self.num_heads, dtype=self.dtype,
            impl=self.attn_impl, axis_name=self.attn_axis_name,
        )(x, pad_mask)
        x = nn.LayerNorm(dtype=self.dtype)(x + attn)
        if self.num_experts > 0:
            from colearn_federated_learning_tpu.models.moe import MoEFfn

            h = MoEFfn(self.embed_dim, self.num_experts,
                       mlp_ratio=self.mlp_ratio, dtype=self.dtype)(
                x, token_mask=pad_mask
            )
        else:
            h = nn.Dense(self.embed_dim * self.mlp_ratio, dtype=self.dtype)(x)
            h = nn.gelu(h)
            h = nn.Dense(self.embed_dim, dtype=self.dtype)(h)
        return nn.LayerNorm(dtype=self.dtype)(x + h)


class BertClassifier(nn.Module):
    num_classes: int = 4
    vocab_size: int = 30522
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    max_len: int = 128
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "dense"
    seq_axis_name: Optional[str] = None
    # > 0 turns every other block (odd index; block 0 when depth == 1)
    # into a mixture-of-experts block — the GShard interleaving, so deep
    # models keep dense MLPs between MoE layers.
    num_experts: int = 0
    # Rematerialize each block under autodiff (activation HBM ∝ depth
    # becomes ∝ 1 at the cost of one extra forward per block).
    remat: bool = False

    @nn.compact
    def __call__(self, ids, train: bool = False):
        """``ids``: (B, L) token ids.

        Sequence parallelism: with ``seq_axis_name`` set (and
        ``attn_impl="ring"``) the module runs inside ``shard_map`` on a
        local (B, L/S) shard — position embeddings are sliced at this
        shard's GLOBAL offset, attention rings over the axis, and the
        masked-mean pooling finishes with a psum so logits come out
        replicated across the sequence axis.
        """
        B, L = ids.shape
        sp = self.seq_axis_name
        pad_mask = (ids != 0)                                  # (B, L)
        tok = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype)(ids)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, self.max_len, self.embed_dim)
        )
        if sp is not None:
            offset = jax.lax.axis_index(sp) * L
            pos_l = jax.lax.dynamic_slice_in_dim(pos, offset, L, axis=1)
        else:
            pos_l = pos[:, :L]
        x = tok + pos_l.astype(self.dtype)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        block_cls = (
            nn.remat(TransformerBlock) if self.remat else TransformerBlock
        )
        for i in range(self.depth):
            moe_here = self.num_experts > 0 and (
                i % 2 == 1 or self.depth == 1
            )
            # Explicit names pin the param paths: nn.remat's auto-prefix
            # ("CheckpointTransformerBlock_i") would otherwise fork the
            # pytree from the non-remat twin, breaking checkpoints, wire
            # payloads and the TP partition rules.
            x = block_cls(self.embed_dim, self.num_heads, dtype=self.dtype,
                          attn_impl=self.attn_impl,
                          attn_axis_name=sp,
                          num_experts=self.num_experts if moe_here else 0,
                          name=f"TransformerBlock_{i}")(
                x, pad_mask
            )
        # Masked mean pooling (no [CLS] convention in the synthetic corpus);
        # under SP the token sums finish with a psum over the sequence axis
        # whose grad convention pairs with the trainer's pmean (see
        # parallel/collectives.py).
        m = pad_mask[..., None].astype(jnp.float32)
        sum_x = (x.astype(jnp.float32) * m).sum(1)
        sum_m = m.sum(1)
        if sp is not None:
            from colearn_federated_learning_tpu.parallel.collectives import (
                psum_for_grad_pmean,
            )

            sum_x = psum_for_grad_pmean(sum_x, sp)
            sum_m = jax.lax.psum(sum_m, sp)  # mask: no grad
        pooled = sum_x / jnp.maximum(sum_m, 1.0)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32)(pooled)
        return logits
