"""Per-device fleet health ledger: the straggler-attribution data plane.

The comm planes already *count* failure (``comm.retry_total``,
``fed.clients_evicted``) but the aggregate erases WHO — and the
ROADMAP's buffered-async / CLIP-style pruning item needs exactly the
per-device record: which devices miss deadlines, how often they retry,
what their observed round latency looks like.  This module is that
record.

Durability follows ckpt/wal.py wholesale: one JSONL file per writing
process (``health_<source>.jsonl`` — coordinator, each aggregator, and
fleetsim write disjoint files, so there is no cross-process append
interleaving to reason about), ``fsync`` per flush, torn final line
tolerated on load, torn mid-file raises.  Boundedness comes from
compaction: when the event log outgrows ``max_lines`` the file is
atomically rewritten (tmp + ``os.replace``) as one snapshot line that
the next load replays before any subsequent event deltas.

Latency is kept two ways per device: an EWMA (cheap trend the eviction
heuristics can read) and a stride-thinned sample sketch (the same
deterministic thinning as registry.Histogram) for tail quantiles.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from colearn_federated_learning_tpu.telemetry import registry as _metrics

# Event-count fields a ledger line may carry, in render order.
# ``prune`` / ``pump_stall`` are the async-plane feeds (a paused pump and
# a dispatch that burned most of its timeout budget, per device) — old
# ledgers without them load as zeros via ``from_dict``'s defaults.
# ``norm_anomaly`` is the convergence-observatory feed (an update whose
# norm towers over the cohort median — a poisoned or diverging device is
# a health event, same as a straggler); it rides the same
# forward-compatible zero-default path and is deliberately NOT a rendered
# column (`colearn health` output is contract-stable).
# ``rehomed`` is the aggregator-tree failover feed: the device's in-flight
# contribution was re-sent to a sibling aggregator after its assigned one
# died.  It attributes infrastructure faults, not device behavior, so it
# carries ZERO weight in score() and (like norm_anomaly) is not a
# rendered column.
COUNT_FIELDS = ("deadline_miss", "retry", "corrupt_frame", "eviction",
                "secure_dropout", "prune", "pump_stall", "norm_anomaly",
                "rehomed")

_EWMA_ALPHA = 0.2
_MAX_SAMPLES = 256


def _quantile(samples: list, q: float) -> Optional[float]:
    if not samples:
        return None
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[max(0, idx)]


class DeviceHealth:
    """Mutable in-memory record for one device.  ``to_dict`` is the
    JSON snapshot form the ledger compacts to and ``merge`` combines
    records for the same device written by different processes."""

    def __init__(self, device_id: str):
        self.device_id = str(device_id)
        self.counts = {k: 0 for k in COUNT_FIELDS}
        self.rounds = 0
        self.last_round: Optional[int] = None
        self.lat_ewma: Optional[float] = None
        self.lat_samples: list = []
        self._stride = 1
        self._seen = 0
        self.agg: Optional[str] = None

    # ----------------------------------------------------------- update --
    def apply(self, event: dict) -> None:
        for k in COUNT_FIELDS:
            n = event.get(k)
            if n:
                self.counts[k] += int(n)
        r = event.get("round")
        if r is not None:
            r = int(r)
            if self.last_round is None or r > self.last_round:
                self.last_round = r
            self.rounds += 1
        if event.get("agg") is not None:
            self.agg = str(event["agg"])
        lat = event.get("latency_s")
        if lat is not None:
            self._observe(float(lat))

    def _observe(self, lat: float) -> None:
        self.lat_ewma = lat if self.lat_ewma is None else (
            _EWMA_ALPHA * lat + (1.0 - _EWMA_ALPHA) * self.lat_ewma)
        if self._seen % self._stride == 0:
            self.lat_samples.append(lat)
            if len(self.lat_samples) >= _MAX_SAMPLES:
                self.lat_samples = self.lat_samples[::2]
                self._stride *= 2
        self._seen += 1

    # -------------------------------------------------------- summaries --
    def score(self) -> float:
        """Offender ranking: weighted failure count.  Evictions are the
        terminal symptom, deadline misses the leading one; retries are
        the cheapest noise.  Async-plane feeds slot in between: a prune
        is a predicted dropout (nearly an eviction), a pump stall a
        near-miss of the dispatch timeout."""
        c = self.counts
        return (5.0 * c["eviction"] + 3.0 * c["deadline_miss"]
                + 3.0 * c["prune"] + 3.0 * c["norm_anomaly"]
                + 2.0 * c["corrupt_frame"] + 2.0 * c["secure_dropout"]
                + 1.0 * c["retry"] + 1.0 * c["pump_stall"])

    def to_dict(self) -> dict:
        out: dict = {"device_id": self.device_id, "rounds": self.rounds}
        out.update({k: v for k, v in self.counts.items() if v})
        if self.last_round is not None:
            out["last_round"] = self.last_round
        if self.lat_ewma is not None:
            out["lat_ewma"] = self.lat_ewma
        if self.lat_samples:
            out["lat_samples"] = [round(s, 6) for s in self.lat_samples]
        if self.agg is not None:
            out["agg"] = self.agg
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "DeviceHealth":
        dev = cls(str(d.get("device_id", "")))
        for k in COUNT_FIELDS:
            dev.counts[k] = int(d.get(k, 0))
        dev.rounds = int(d.get("rounds", 0))
        dev.last_round = d.get("last_round")
        dev.lat_ewma = d.get("lat_ewma")
        dev.lat_samples = [float(s) for s in d.get("lat_samples", [])]
        dev._seen = len(dev.lat_samples)
        if d.get("agg") is not None:
            dev.agg = str(d["agg"])
        return dev

    def merge(self, other: "DeviceHealth") -> None:
        """Fold another process's record for the same device into this
        one — counts sum, latency EWMAs average weighted by rounds seen,
        sample sketches concatenate under the same bound."""
        for k in COUNT_FIELDS:
            self.counts[k] += other.counts[k]
        if other.last_round is not None and (
                self.last_round is None
                or other.last_round > self.last_round):
            self.last_round = other.last_round
        if other.lat_ewma is not None:
            if self.lat_ewma is None:
                self.lat_ewma = other.lat_ewma
            else:
                w_a = max(1, self.rounds)
                w_b = max(1, other.rounds)
                self.lat_ewma = (
                    (w_a * self.lat_ewma + w_b * other.lat_ewma)
                    / (w_a + w_b))
        self.rounds += other.rounds
        self.lat_samples = (self.lat_samples
                            + other.lat_samples)[-_MAX_SAMPLES:]
        if other.agg is not None:
            self.agg = other.agg


class HealthLedger:
    """Bounded durable per-device ledger for ONE writing process.

    ``record`` accumulates in memory and buffers the event line;
    ``flush`` appends all buffered lines and fsyncs once — call it at
    round granularity so a SIGKILL loses at most the in-flight round.
    """

    def __init__(self, directory: str, source: str,
                 max_lines: int = 4096):
        os.makedirs(directory, exist_ok=True)
        self.source = str(source)
        self.path = os.path.join(directory, f"health_{self.source}.jsonl")
        self._max_lines = int(max_lines)
        self._f = None
        self._pending: list = []
        self._lines = 0
        self._devices: dict[str, DeviceHealth] = {}
        for entry in _load_entries(self.path):
            self._lines += 1
            self._replay(entry)

    # ----------------------------------------------------------- write --
    def record(self, device_id: str, *, round: Optional[int] = None,
               latency_s: Optional[float] = None,
               agg: Optional[str] = None, **counts) -> None:
        """Note one device observation.  ``counts`` are increments over
        COUNT_FIELDS (``retry=2``, ``eviction=1``); unknown fields
        raise so feed-site typos cannot silently drop attribution."""
        unknown = set(counts) - set(COUNT_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown health fields {sorted(unknown)!r}; "
                f"expected {COUNT_FIELDS}")
        event: dict = {"d": str(device_id)}
        if round is not None:
            event["round"] = int(round)
        if latency_s is not None:
            event["latency_s"] = float(latency_s)
        if agg is not None:
            event["agg"] = str(agg)
        event.update({k: int(v) for k, v in counts.items() if v})
        self._pending.append(event)
        self._apply_event(event)

    def flush(self) -> None:
        """Durably append every buffered event (single fsync), then
        compact if the log outgrew its bound."""
        if not self._pending:
            return
        f = self._handle()
        for event in self._pending:
            f.write(json.dumps(event, separators=(",", ":")) + "\n")
        f.flush()
        os.fsync(f.fileno())
        _metrics.get_registry().counter(
            "health.ledger_appends_total").inc(len(self._pending))
        self._lines += len(self._pending)
        self._pending.clear()
        if self._lines > self._max_lines:
            self._compact()

    def _compact(self) -> None:
        """Atomically rewrite the log as one snapshot line — the bound
        that keeps a long-lived federation's ledger O(devices), not
        O(events)."""
        snap = {"snapshot": [dev.to_dict()
                             for _, dev in sorted(self._devices.items())],
                "source": self.source}
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(snap, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self.close()
        os.replace(tmp, self.path)
        self._lines = 1
        _metrics.get_registry().counter(
            "health.ledger_compactions_total").inc()

    def _handle(self):
        if self._f is None:
            self._f = open(self.path, "a", encoding="utf-8")
        return self._f

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    # ------------------------------------------------------------ read --
    def _replay(self, entry: dict) -> None:
        if "snapshot" in entry:
            self._devices = {
                str(d.get("device_id", "")): DeviceHealth.from_dict(d)
                for d in entry["snapshot"]}
            return
        self._apply_event(entry)

    def _apply_event(self, event: dict) -> None:
        did = str(event.get("d", ""))
        if not did:
            return
        dev = self._devices.get(did)
        if dev is None:
            dev = self._devices[did] = DeviceHealth(did)
        dev.apply(event)

    def devices(self) -> dict:
        """``device_id -> DeviceHealth`` (includes un-flushed events)."""
        return dict(self._devices)


# ------------------------------------------------------------- loading --
def _load_entries(path: str) -> list:
    """Decodable JSONL entries; torn final line dropped (the flush that
    was in flight when the process died), torn mid-file raises."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    out: list = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break
            raise ValueError(f"corrupt health ledger at {path}:{i + 1}")
    return out


def load_health(directory: str) -> dict:
    """Merge every ``health_*.jsonl`` under ``directory`` (recursive —
    procsoak scatters per-role workdirs) into one
    ``device_id -> DeviceHealth`` view."""
    merged: dict[str, DeviceHealth] = {}
    if not os.path.isdir(directory):
        return merged
    for root, _, files in os.walk(directory):
        for fname in sorted(files):
            if not (fname.startswith("health_")
                    and fname.endswith(".jsonl")):
                continue
            local: dict[str, DeviceHealth] = {}
            for entry in _load_entries(os.path.join(root, fname)):
                if "snapshot" in entry:
                    local = {
                        str(d.get("device_id", "")):
                            DeviceHealth.from_dict(d)
                        for d in entry["snapshot"]}
                    continue
                did = str(entry.get("d", ""))
                if not did:
                    continue
                dev = local.get(did)
                if dev is None:
                    dev = local[did] = DeviceHealth(did)
                dev.apply(entry)
            for did, dev in local.items():
                if did in merged:
                    merged[did].merge(dev)
                else:
                    merged[did] = dev
    return merged


# --------------------------------------------------------------- feeds --
def feed_transport_retries(ledger: HealthLedger, seen: dict,
                           registry=None) -> None:
    """Attribute the transport's labeled retry counters
    (``comm.retry_total{device=...}``) to devices: record the delta since
    the last call (``seen`` carries the per-device high-water marks).
    Peers that are not devices — aggregators (``agg:N``), raw
    ``host:port`` idents — are skipped."""
    reg = registry if registry is not None else _metrics.get_registry()
    prefix = "comm.retry_total{device="
    for name, v in reg.snapshot().items():
        if not (name.startswith(prefix) and name.endswith("}")):
            continue
        did = name[len(prefix):-1]
        if ":" in did or not did:
            continue
        delta = float(v) - seen.get(did, 0.0)
        seen[did] = float(v)
        if delta > 0:
            ledger.record(did, retry=int(delta))


# ----------------------------------------------------------- reporting --
def render_health(devices: dict, top: int = 10) -> str:
    """``colearn health`` body: top offenders, fleet straggler tail,
    per-aggregator slice skew.  Pure function over :func:`load_health`
    output."""
    lines = ["colearn health — per-device fleet ledger", ""]
    if not devices:
        lines.append("no health records found")
        return "\n".join(lines)
    lines.append(f"devices tracked     {len(devices):>8}")
    lines.append("")
    ranked = sorted(devices.values(),
                    key=lambda d: (-d.score(), -(d.lat_ewma or 0.0),
                                   d.device_id))
    lines.append("top offenders (score = 5*evict + 3*miss + 3*prune "
                 "+ 2*corrupt + 2*dropout + retry + stall)")
    lines.append("  device   score  miss retry corrupt evict dropout"
                 " prune stall   lat ewma")
    for dev in ranked[:top]:
        c = dev.counts
        ewma = f"{dev.lat_ewma:.3f}s" if dev.lat_ewma is not None else "-"
        lines.append(
            f"  {dev.device_id:<8} {dev.score():>5.0f} {c['deadline_miss']:>5}"
            f" {c['retry']:>5} {c['corrupt_frame']:>7} {c['eviction']:>5}"
            f" {c['secure_dropout']:>7} {c['prune']:>5} {c['pump_stall']:>5}"
            f" {ewma:>10}")
    all_samples: list = []
    for dev in devices.values():
        all_samples.extend(dev.lat_samples)
    if all_samples:
        lines.append("")
        lines.append(
            "straggler tail      "
            f"p50 {_quantile(all_samples, 0.50):.3f}s   "
            f"p90 {_quantile(all_samples, 0.90):.3f}s   "
            f"p99 {_quantile(all_samples, 0.99):.3f}s")
    by_agg: dict[str, list] = {}
    for dev in devices.values():
        if dev.agg is not None and dev.lat_samples:
            by_agg.setdefault(dev.agg, []).extend(dev.lat_samples)
    if len(by_agg) > 1:
        lines.append("")
        lines.append("per-aggregator slice skew")
        means = {}
        for agg_id in sorted(by_agg):
            samples = by_agg[agg_id]
            means[agg_id] = sum(samples) / len(samples)
            lines.append(
                f"  agg {agg_id:<4} mean {means[agg_id]:.3f}s"
                f"   p90 {_quantile(samples, 0.90):.3f}s"
                f"   n {len(samples)}")
        lo = min(means.values())
        if lo > 0:
            lines.append(f"  skew (max/min mean) {max(means.values()) / lo:.2f}x")
    return "\n".join(lines)


def export_gauges(devices: dict, registry=None, top: int = 16) -> None:
    """Surface the ledger as labeled gauges so the Prometheus endpoint
    shows attribution without a file read.  Bounded to the ``top`` worst
    devices — a 10k-device fleet must not mint 10k gauge children."""
    reg = registry if registry is not None else _metrics.get_registry()
    reg.gauge("health.devices_tracked").set(len(devices))
    ranked = sorted(devices.values(),
                    key=lambda d: (-d.score(), -(d.lat_ewma or 0.0),
                                   d.device_id))
    for dev in ranked[:top]:
        labels = {"device": dev.device_id}
        reg.gauge("health.device_score", labels=labels).set(dev.score())
        if dev.lat_ewma is not None:
            reg.gauge("health.device_latency_ewma_s",
                      labels=labels).set(dev.lat_ewma)


def health_record_keys(devices: dict) -> dict:
    """Round-record summary (``health_*`` keys) — stamped only when the
    plane is enabled, so default records stay byte-identical."""
    out = {"health_devices": len(devices)}
    all_samples: list = []
    worst, worst_score = None, 0.0
    for dev in devices.values():
        all_samples.extend(dev.lat_samples)
        s = dev.score()
        if s > worst_score:
            worst, worst_score = dev.device_id, s
    p99 = _quantile(all_samples, 0.99)
    if p99 is not None:
        out["health_lat_p99_s"] = round(p99, 6)
    if worst is not None:
        out["health_worst_device"] = worst
        out["health_worst_score"] = worst_score
    return out
