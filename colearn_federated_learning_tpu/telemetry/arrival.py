"""Seeded-EWMA arrival-rate estimation for the buffered-async plane.

The async coordinator folds a buffer of K updates whenever K arrive; the
fleet simulator does the same on a virtual clock.  Both planes previously
*reacted* to arrivals without measuring them, which left ROADMAP's
"adaptive buffer size K driven by the observed arrival rate" unbuildable:
there was no observed arrival rate.  This module is that observation.

Design points:

- **Clock-agnostic.** ``observe(device_id, now=t)`` takes the caller's
  timestamp in the caller's units — wall seconds for the coordinator,
  virtual sim-minutes for fleetsim — and every rate it reports is in
  arrivals per that same unit.  Nothing here reads a clock, which keeps
  fleetsim runs deterministic and tests hermetic.
- **Seeded EWMA.** The estimator smooths *inter-arrival gaps*, not
  counts-per-tick, so it needs no bucketing interval.  The first gap a
  stream sees seeds the EWMA directly instead of decaying up from zero —
  a zero-initialised EWMA under-reports rate for ~1/alpha observations,
  which is exactly the warm-up window an auto-K controller must not
  spend mis-sized.
- **Fleet + per-device.** The fleet stream drives buffer sizing; the
  per-device streams feed straggler attribution (a device whose arrival
  rate collapses is stalling before it ever trips a deadline).

``recommend_buffer`` is the control half: given a target fold cadence it
returns the K that would fold at that cadence under the current fleet
rate (K = rate x target interval, clamped to the caller's bounds).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class _EwmaRate:
    """EWMA over inter-arrival gaps for one stream.  ``rate`` is
    1/gap — arrivals per time unit — or 0.0 before two observations."""

    __slots__ = ("alpha", "last_t", "gap", "count")

    def __init__(self, alpha: float):
        self.alpha = alpha
        self.last_t: Optional[float] = None
        self.gap: Optional[float] = None
        self.count = 0

    def observe(self, now: float) -> None:
        self.count += 1
        if self.last_t is not None:
            g = max(now - self.last_t, 1e-9)
            # First gap seeds the EWMA; later gaps blend in.
            self.gap = g if self.gap is None else (
                self.alpha * g + (1.0 - self.alpha) * self.gap)
        self.last_t = now

    @property
    def rate(self) -> float:
        return 1.0 / self.gap if self.gap else 0.0


class ArrivalEstimator:
    """Fleet-wide and per-device arrival-rate estimator.

    Thread-safe: the coordinator's dispatcher pumps observe from many
    threads while ``run_aggregation`` reads the fleet rate.
    """

    def __init__(self, alpha: float = 0.3):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self._fleet = _EwmaRate(alpha)
        self._devices: Dict[str, _EwmaRate] = {}
        self._lock = threading.Lock()

    def observe(self, device_id: Optional[str] = None, *,
                now: float) -> None:
        """Record one arrival at time ``now`` (caller's clock + units)."""
        with self._lock:
            self._fleet.observe(now)
            if device_id is not None:
                dev = self._devices.get(device_id)
                if dev is None:
                    dev = self._devices[device_id] = _EwmaRate(self.alpha)
                dev.observe(now)

    @property
    def count(self) -> int:
        with self._lock:
            return self._fleet.count

    def rate(self) -> float:
        """Fleet arrivals per time unit (0.0 until two arrivals)."""
        with self._lock:
            return self._fleet.rate

    def device_rate(self, device_id: str) -> float:
        with self._lock:
            dev = self._devices.get(device_id)
            return dev.rate if dev is not None else 0.0

    def device_rates(self) -> Dict[str, float]:
        with self._lock:
            return {d: e.rate for d, e in self._devices.items()}

    def group_rate(self, device_ids) -> float:
        """Summed per-device rate over one slice of the fleet — the
        arrival rate an aggregator owning exactly ``device_ids`` would
        see.  Devices the estimator has not warmed up on contribute 0.0
        (same cold semantics as :meth:`device_rate`)."""
        with self._lock:
            return sum(
                self._devices[str(d)].rate for d in device_ids
                if str(d) in self._devices)

    def recommend_buffer(self, target_interval: float, *, lo: int = 1,
                         hi: int = 1 << 30,
                         current: Optional[int] = None) -> int:
        """K that folds once per ``target_interval`` at the current fleet
        rate, clamped to [lo, hi].  Falls back to ``current`` (or ``lo``)
        while the estimator is still cold."""
        r = self.rate()
        if r <= 0.0:
            k = current if current is not None else lo
        else:
            k = int(round(r * target_interval))
        return max(lo, min(hi, k))

    def export_gauges(self, reg, name: str, *, top: int = 8) -> None:
        """Set the fleet gauge ``name`` and per-device children
        ``name{device=...}`` for the ``top`` fastest devices.  Labeled
        gauges do not roll up in the registry, so the fleet value is a
        separately-set unlabeled gauge."""
        with self._lock:
            fleet = self._fleet.rate
            rates = {d: e.rate for d, e in self._devices.items()}
        # Callers pass a catalog-declared literal (the coordinator's
        # async.arrival_rate_per_s); this helper just fans it out.
        reg.gauge(name).set(fleet)  # colearn: noqa(CL005): callers pass a catalog-declared literal
        for dev, r in sorted(rates.items(), key=lambda kv: -kv[1])[:top]:
            reg.gauge(  # colearn: noqa(CL005): same catalog-declared name, fanned out per device
                name, labels={"device": str(dev)}).set(r)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rate": self._fleet.rate,
                "count": self._fleet.count,
                "devices": {d: e.rate for d, e in self._devices.items()},
            }
