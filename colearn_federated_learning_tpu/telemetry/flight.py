"""Crash flight recorder: bounded in-memory forensics, dumped on death.

The mp chaos soak (faults/procsoak.py) kills real processes with
SIGKILL — which no handler can catch.  So survivability cannot hinge on
an exit hook: the recorder keeps bounded ring buffers of recent
activity (events, metric snapshot, tails of attached tracers) and a
background **heartbeat thread** rewrites ``flight_<pid>.json``
atomically every few seconds.  When the process dies — SIGKILL, OOM,
power-off — the last heartbeat dump IS the black box, at most one
heartbeat stale.  The catchable ends of a process (SIGTERM, uncaught
exception, watchdog-declared stall) additionally trigger an immediate
dump with the trigger and traceback recorded.

``colearn postmortem`` merges a directory of flight dumps with the
PR 5 round-WAL to answer the operator question directly: what was the
last committed round, and what was each process doing when it died?

All writes are atomic (tmp + ``os.replace``): a dump file either parses
or does not exist — procsoak asserts exactly this per killed pid.
"""

from __future__ import annotations

import faulthandler
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Optional

from colearn_federated_learning_tpu.telemetry.registry import get_registry

__all__ = [
    "FlightRecorder",
    "get_flight_recorder",
    "install_flight_recorder",
    "load_flight_dumps",
    "postmortem_report",
    "render_postmortem",
]

_SPAN_TAIL = 256          # most-recent spans kept per attached tracer
_EVENT_RING = 512         # most-recent recorded events


class FlightRecorder:
    """Black box for one process.

    ``record(kind, **fields)`` appends to the event ring (comm events,
    round marks, lifecycle).  ``mark_progress()`` feeds the watchdog —
    if ``watchdog_s`` passes without a mark after the first one, the
    heartbeat thread dumps once with ``trigger="watchdog_stall"``.
    ``attach_tracer`` registers span sources whose recent tails are
    embedded in every dump.
    """

    def __init__(self, directory: str, role: str = "main",
                 heartbeat_s: float = 5.0,
                 watchdog_s: Optional[float] = None):
        self.directory = directory
        self.role = role
        self.heartbeat_s = heartbeat_s
        self.watchdog_s = watchdog_s
        self.path = os.path.join(directory, f"flight_{os.getpid()}.json")
        self._events: deque = deque(maxlen=_EVENT_RING)
        self._tracers: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_progress: Optional[float] = None
        self._stall_dumped = False
        self._prev_sigterm = None
        self._prev_excepthook = None
        self.dumps = 0
        os.makedirs(directory, exist_ok=True)

    # -- feeding the box ------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        self._events.append(
            {"ts": time.time(), "kind": kind, **fields})

    def mark_progress(self) -> None:
        self._last_progress = time.monotonic()
        self._stall_dumped = False

    def attach_tracer(self, tracer) -> None:
        with self._lock:
            if tracer not in self._tracers:
                self._tracers.append(tracer)

    # -- dumping --------------------------------------------------------
    def _payload(self, trigger: str, exc: Optional[str] = None) -> dict:
        with self._lock:
            tracers = list(self._tracers)
        spans = []
        for tr in tracers:
            try:
                tail = tr.snapshot()[-_SPAN_TAIL:]
            except Exception:
                continue
            spans.extend(sp.to_dict() for sp in tail)
        doc = {
            "schema": "colearn-flight-v1",
            "pid": os.getpid(),
            "role": self.role,
            "trigger": trigger,
            "ts": time.time(),
            "argv": list(sys.argv),
            "events": list(self._events),
            "metrics": get_registry().snapshot(),
            "spans": spans,
        }
        if exc is not None:
            doc["exception"] = exc
        return doc

    def dump(self, trigger: str, exc: Optional[str] = None) -> str:
        """Atomically (re)write the flight file; returns its path.
        Never raises — the recorder must not be the second failure."""
        try:
            doc = self._payload(trigger, exc)
            tmp = f"{self.path}.tmp.{threading.get_ident()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, separators=(",", ":"), default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self.dumps += 1
            get_registry().counter("flight.dumps_total").inc()
        except Exception:
            pass
        return self.path

    # -- lifecycle ------------------------------------------------------
    def install(self) -> "FlightRecorder":
        """Write the initial dump, hook SIGTERM + sys.excepthook, start
        the heartbeat/watchdog thread, and enable faulthandler (hard
        faults at least leave a native traceback on stderr)."""
        self.dump("install")
        try:
            faulthandler.enable()
        except (RuntimeError, AttributeError, ValueError):
            pass                       # no usable stderr (daemonized)
        if threading.current_thread() is threading.main_thread():
            try:
                self._prev_sigterm = signal.signal(
                    signal.SIGTERM, self._on_sigterm)
            except (ValueError, OSError):
                pass
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._on_exception
        self._thread = threading.Thread(
            target=self._heartbeat_loop, name="flight-recorder",
            daemon=True)
        self._thread.start()
        return self

    def _on_sigterm(self, signum, frame) -> None:
        self.dump("sigterm")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _on_exception(self, etype, value, tb) -> None:
        exc = "".join(traceback.format_exception(etype, value, tb))
        self.dump("fatal_exception", exc=exc)
        hook = self._prev_excepthook or sys.__excepthook__
        hook(etype, value, tb)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            trigger = "heartbeat"
            if (self.watchdog_s is not None
                    and self._last_progress is not None
                    and not self._stall_dumped
                    and time.monotonic() - self._last_progress
                    > self.watchdog_s):
                trigger = "watchdog_stall"
                self._stall_dumped = True
            self.dump(trigger)

    def close(self, final_trigger: str = "shutdown") -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        self.dump(final_trigger)


_recorder: Optional[FlightRecorder] = None


def install_flight_recorder(directory: str, role: str = "main",
                            heartbeat_s: float = 5.0,
                            watchdog_s: Optional[float] = None,
                            ) -> FlightRecorder:
    """Install the process-wide recorder (idempotent per process: a
    second call returns the existing one — worker and engine planes may
    both ask)."""
    global _recorder
    if _recorder is None:
        _recorder = FlightRecorder(
            directory, role=role, heartbeat_s=heartbeat_s,
            watchdog_s=watchdog_s).install()
    return _recorder


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _recorder


# ------------------------------------------------------------ postmortem --
def load_flight_dumps(directory: str) -> list:
    """Parse every ``flight_*.json`` under ``directory`` (recursive),
    sorted by dump timestamp.  Unparseable files are reported as
    ``{"error": ..., "path": ...}`` stubs rather than skipped — a
    corrupt black box is itself a finding."""
    dumps = []
    for root, _dirs, files in os.walk(directory):
        for fn in sorted(files):
            if not (fn.startswith("flight_") and fn.endswith(".json")):
                continue
            path = os.path.join(root, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
                doc["_path"] = path
                dumps.append(doc)
            except (OSError, ValueError) as e:
                dumps.append({"schema": "colearn-flight-v1",
                              "error": str(e), "_path": path})
    dumps.sort(key=lambda d: d.get("ts", 0.0))
    return dumps


def postmortem_report(dumps: list, wal_entries: Optional[list] = None,
                      checkpoint_step: Optional[int] = None) -> dict:
    """Merge flight dumps with round-WAL entries into the operator
    answer: last committed round, rounds in flight at death, and per-pid
    what each process was doing (trigger, last events, open spans).

    WAL entries carry no committed flag — commitment is positional
    (ckpt/wal.py: entries past the latest checkpoint step are
    uncommitted).  With ``checkpoint_step`` the split is exact; without
    it, every logged round counts as committed and "in flight" means
    rounds the flight dumps saw PAST the last WAL entry — work that died
    before its WAL append."""
    logged = [e.get("round") for e in (wal_entries or [])
              if e.get("round") is not None]
    if checkpoint_step is not None:
        committed = logged[:checkpoint_step]
        in_flight = logged[checkpoint_step:]
    else:
        committed, in_flight = logged, []
    processes = []
    for d in dumps:
        if "error" in d:
            processes.append({"path": d.get("_path"),
                              "error": d["error"]})
            continue
        spans = d.get("spans", [])
        events = d.get("events", [])
        metrics = d.get("metrics", {})
        rounds_seen = sorted({e.get("round") for e in events
                              if e.get("round") is not None})
        if (checkpoint_step is None and rounds_seen and committed
                and rounds_seen[-1] > committed[-1]):
            for r in rounds_seen:
                if r > committed[-1] and r not in in_flight:
                    in_flight.append(r)
        processes.append({
            "pid": d.get("pid"),
            "role": d.get("role"),
            "trigger": d.get("trigger"),
            "ts": d.get("ts"),
            "exception": d.get("exception"),
            "last_round_seen": rounds_seen[-1] if rounds_seen else None,
            "last_events": events[-5:],
            "last_spans": [s.get("name") for s in spans[-8:]],
            "metrics_of_note": {
                k: v for k, v in metrics.items()
                if isinstance(v, (int, float)) and v
                and any(k.startswith(p) for p in
                        ("fed.", "comm.", "fault.", "flight.",
                         "telemetry."))},
        })
    return {
        "schema": "colearn-postmortem-v1",
        "last_committed_round": committed[-1] if committed else None,
        "committed_rounds": len(committed),
        "rounds_in_flight": sorted(in_flight),
        "process_count": len(processes),
        "crash_triggers": sorted({p.get("trigger") for p in processes
                                  if p.get("trigger")}),
        "processes": processes,
    }


def render_postmortem(report: dict) -> str:
    """Human-readable rendering of :func:`postmortem_report`."""
    lines = ["colearn postmortem", ""]
    lines.append(f"last committed round : "
                 f"{report.get('last_committed_round')}")
    lines.append(f"committed rounds     : {report.get('committed_rounds')}")
    ifl = report.get("rounds_in_flight") or []
    lines.append(f"rounds in flight     : "
                 f"{', '.join(map(str, ifl)) if ifl else '-'}")
    lines.append("")
    for p in report.get("processes", []):
        if "error" in p:
            lines.append(f"  [unparseable] {p.get('path')}: {p['error']}")
            continue
        lines.append(f"  pid {p.get('pid')} ({p.get('role')}) "
                     f"— trigger={p.get('trigger')} "
                     f"last_round={p.get('last_round_seen')}")
        if p.get("exception"):
            first = p["exception"].strip().splitlines()[-1]
            lines.append(f"      exception: {first}")
        if p.get("last_spans"):
            lines.append(
                "      recent spans: " + ", ".join(p["last_spans"]))
    return "\n".join(lines)
