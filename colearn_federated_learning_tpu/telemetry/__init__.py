"""End-to-end round telemetry (see tracer/registry/export/lifecycle).

Public surface:

- :class:`Tracer` / :func:`get_tracer` — nested spans, monotonic timing,
  cross-process trace propagation (``current_context`` + ``adopt``);
- :class:`MetricsRegistry` / :func:`get_registry` — process-wide
  counters, gauges, quantile histograms;
- :mod:`.export` — Chrome-trace/Perfetto JSON writer/loader and the
  ``colearn trace-summary`` text breakdown;
- :class:`RoundTelemetry` — the per-round lifecycle driver shared by the
  span tracer window and the jax profiler window;
- :mod:`.runtime` — XLA introspection (:class:`CompileTracker` recompile
  detection, AOT cost analysis, HBM gauges) and live export (Prometheus
  endpoint, JSONL event stream, ``colearn top`` renderer);
- :mod:`.flight` — crash flight recorder (heartbeat ring-buffer dumps,
  ``colearn postmortem`` merge with the round WAL);
- :mod:`.health` — durable per-device health ledger (straggler
  attribution, latency sketches, ``colearn health`` renderer);
- :mod:`.arrival` — seeded-EWMA arrival-rate estimation (fleet +
  per-device) feeding the async observatory and ``--async-buffer auto``;
- :mod:`.convergence` — the learning-health plane: per-round update-norm
  / cosine / trend signals from the aggregate, per-cohort drift
  attribution, and the ``colearn converge`` report.
"""

from colearn_federated_learning_tpu.telemetry.tracer import (  # noqa: F401
    Span,
    SpanContext,
    Tracer,
    get_tracer,
    new_id,
)
from colearn_federated_learning_tpu.telemetry.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from colearn_federated_learning_tpu.telemetry.export import (  # noqa: F401
    default_trace_path,
    load_trace,
    spans_to_chrome,
    summarize_trace,
    trace_spans,
    write_trace,
    write_tracer,
)
from colearn_federated_learning_tpu.telemetry.lifecycle import (  # noqa: F401
    RoundTelemetry,
)
from colearn_federated_learning_tpu.telemetry.runtime import (  # noqa: F401
    CompileTracker,
    EventLog,
    MetricsExporter,
    compiled_cost,
    prometheus_text,
    sample_device_memory,
)
from colearn_federated_learning_tpu.telemetry.health import (  # noqa: F401
    DeviceHealth,
    HealthLedger,
    export_gauges,
    feed_transport_retries,
    health_record_keys,
    load_health,
    render_health,
)
from colearn_federated_learning_tpu.telemetry.arrival import (  # noqa: F401
    ArrivalEstimator,
)
from colearn_federated_learning_tpu.telemetry.convergence import (  # noqa: F401,E501
    ConvergenceObservatory,
    cohort_skew,
    device_skew,
    render_convergence_report,
)
from colearn_federated_learning_tpu.telemetry.flight import (  # noqa: F401
    FlightRecorder,
    get_flight_recorder,
    install_flight_recorder,
    load_flight_dumps,
    postmortem_report,
    render_postmortem,
)
