"""Trace export: Chrome-trace/Perfetto JSON + human-readable summary.

The on-disk format is the Chrome Trace Event JSON object form —
``{"traceEvents": [...]}`` with complete (``"ph": "X"``) events — which
both ``chrome://tracing`` and https://ui.perfetto.dev open directly.
Span identity (trace/span/parent ids) rides in each event's ``args`` so
a loaded trace round-trips back into span dicts, and a ``metrics`` key
carries the :class:`~..telemetry.registry.MetricsRegistry` snapshot.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from colearn_federated_learning_tpu.telemetry.tracer import Span, Tracer

TRACE_VERSION = 1


def spans_to_chrome(spans: list[Span]) -> list[dict]:
    """Span records → Chrome complete events (+ process_name metadata).

    Each distinct span ``process`` label becomes a pid row so coordinator
    and worker timelines render as separate tracks of ONE stitched trace.
    """
    pids: dict[str, int] = {}
    events: list[dict] = []
    for sp in spans:
        label = sp.process or "main"
        if label not in pids:
            pids[label] = len(pids) + 1
            events.append({
                "name": "process_name", "ph": "M", "pid": pids[label],
                "tid": 0, "args": {"name": label},
            })
        events.append({
            "name": sp.name,
            "cat": "colearn",
            "ph": "X",
            "ts": sp.t_wall * 1e6,                 # micros on the wall clock
            "dur": sp.duration_s * 1e6,
            "pid": pids[label],
            "tid": 0,
            "args": {
                **sp.attrs,
                "trace_id": sp.trace_id,
                "span_id": sp.span_id,
                "parent_id": sp.parent_id,
            },
        })
    return events


def write_trace(path: str, spans: list[Span],
                metrics: Optional[dict] = None,
                dropped_spans: int = 0) -> str:
    """Write the Chrome-trace JSON file; returns ``path``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    doc = {
        "traceEvents": spans_to_chrome(spans),
        "displayTimeUnit": "ms",
        "otherData": {
            "format_version": TRACE_VERSION,
            "num_spans": len(spans),
            "dropped_spans": dropped_spans,
        },
    }
    if metrics:
        doc["otherData"]["metrics"] = metrics
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)                  # readers never see a torn file
    return path


def load_trace(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome-trace JSON (no traceEvents)")
    return doc


def trace_spans(doc: dict) -> list[Span]:
    """Reconstruct span records from a loaded trace (the JSON round-trip
    inverse of :func:`spans_to_chrome`)."""
    names = {
        ev["pid"]: ev["args"]["name"]
        for ev in doc["traceEvents"]
        if ev.get("ph") == "M" and ev.get("name") == "process_name"
    }
    spans = []
    for ev in doc["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args", {}))
        spans.append(Span.from_dict({
            "name": ev["name"],
            "trace_id": args.pop("trace_id", ""),
            "span_id": args.pop("span_id", ""),
            "parent_id": args.pop("parent_id", None),
            "process": names.get(ev["pid"], str(ev.get("pid", ""))),
            "t_wall": ev["ts"] / 1e6,
            "duration_s": ev.get("dur", 0.0) / 1e6,
            "attrs": args,
        }))
    return spans


def default_trace_path(trace_dir: str, name: str) -> str:
    return os.path.join(trace_dir, f"{name}_trace.json")


def write_tracer(trace_dir: str, name: str, tracer: Tracer,
                 metrics: Optional[dict] = None) -> str:
    return write_trace(default_trace_path(trace_dir, name),
                       tracer.snapshot(), metrics=metrics,
                       dropped_spans=tracer.dropped)


# ---------------------------------------------------------------- summary ----
def summarize_trace(doc: dict, root: str = "round") -> str:
    """Per-phase time breakdown of a trace, as printable text.

    Phases aggregate by span name; the denominator for the percentage
    column is the total time under ``root`` spans when any exist (so
    phase percentages read as "share of round wall time"), otherwise the
    overall traced extent.

    Fleetsim sweep traces are understood natively: with the default root
    and no ``round`` spans present, the root falls back to
    ``fleet_round``, and the per-chunk ``train_chunk`` children get a
    dispatch-rate line (chunks/s and clients/s at the chunk size carried
    in the ``train_chunks`` span attrs) instead of rendering as one
    opaque block.
    """
    spans = trace_spans(doc)
    if not spans:
        return "(empty trace)"
    by_name: dict[str, list[Span]] = {}
    for sp in spans:
        by_name.setdefault(sp.name, []).append(sp)
    if root == "round" and "round" not in by_name and (
            "fleet_round" in by_name):
        root = "fleet_round"
    if root == "round" and "round" not in by_name and (
            "async.aggregate" in by_name):
        # Buffered-async traces have no sync rounds; percentages read as
        # "share of aggregation wall time" instead.
        root = "async.aggregate"
    roots = by_name.get(root, [])
    if roots:
        denom = sum(sp.duration_s for sp in roots)
        denom_label = f"{len(roots)} {root} span(s)"
    else:
        t0 = min(sp.t_wall for sp in spans)
        t1 = max(sp.t_wall + sp.duration_s for sp in spans)
        denom = t1 - t0
        denom_label = "traced extent"
    denom = max(denom, 1e-12)
    procs = sorted({sp.process for sp in spans})
    lines = [
        f"trace: {len(spans)} spans over {len(procs)} process(es): "
        + ", ".join(procs),
        f"denominator: {denom:.6f} s ({denom_label})",
        "",
        f"{'phase':<28}{'count':>7}{'total_s':>12}{'mean_ms':>12}"
        f"{'max_ms':>12}{'pct':>8}",
    ]
    rows = []
    for phase, group in by_name.items():
        total = sum(sp.duration_s for sp in group)
        durs = [sp.duration_s for sp in group]
        rows.append((total, phase, len(group),
                     total / len(group) * 1e3, max(durs) * 1e3))
    for total, phase, n, mean_ms, max_ms in sorted(rows, reverse=True):
        lines.append(
            f"{phase:<28}{n:>7}{total:>12.4f}{mean_ms:>12.3f}"
            f"{max_ms:>12.3f}{100.0 * total / denom:>7.1f}%"
        )
    # Coverage: share of root-span time accounted for by their direct
    # children — the acceptance number for "spans cover the round".
    if roots:
        root_ids = {sp.span_id for sp in roots}
        child_t = sum(sp.duration_s for sp in spans
                      if sp.parent_id in root_ids)
        lines.append("")
        lines.append(
            f"phase coverage of {root} time: "
            f"{100.0 * min(1.0, child_t / denom):.1f}%"
        )
    # Fleetsim chunked-vmap sweep: dispatch-rate stats for the chunk loop.
    chunks = by_name.get("train_chunk", [])
    if chunks:
        chunk_t = max(sum(sp.duration_s for sp in chunks), 1e-12)
        # Total clients through the loop: the wrapper span carries the
        # per-round cohort in its attrs.
        cohort = sum(int(sp.attrs.get("cohort") or 0)
                     for sp in by_name.get("train_chunks", []))
        lines.append("")
        lines.append(
            f"fleetsim sweep: {len(chunks)} chunk dispatch(es), "
            f"{len(chunks) / chunk_t:.1f} chunks/s "
            f"(mean {chunk_t / len(chunks) * 1e3:.3f} ms/chunk)")
        if cohort:
            lines.append(
                f"fleetsim sweep: {cohort} client(s) at "
                f"{cohort / chunk_t:.0f} clients/s through the chunk loop")
    # Buffered-async runs: the observatory's version-lineage spans.  Each
    # fold_update is parented on its update's dispatch_train context, so
    # "stitched" counts how many folds joined a dispatch→train trace.
    aggs = by_name.get("async.aggregate", [])
    folds = by_name.get("fold_update", [])
    if aggs or folds:
        lines.append("")
        if aggs:
            agg_t = max(sum(sp.duration_s for sp in aggs), 1e-12)
            k_mean = (sum(int(sp.attrs.get("buffer_size") or 0)
                          for sp in aggs) / len(aggs))
            lines.append(
                f"async plane: {len(aggs)} aggregation(s) at "
                f"{len(aggs) / agg_t:.2f} folds/s (K mean {k_mean:.1f})")
        if folds:
            folded = [sp for sp in folds
                      if sp.attrs.get("outcome") == "folded"]
            stitched = sum(1 for sp in folds if sp.parent_id)
            lines.append(
                f"async lineage: {len(folded)} update(s) folded, "
                f"{len(folds) - len(folded)} discarded; "
                f"{stitched}/{len(folds)} stitched to dispatch spans")
            taus = sorted(float(sp.attrs.get("tau") or 0.0)
                          for sp in folded)
            if taus:
                def _q(p: float) -> float:
                    return taus[min(len(taus) - 1, int(p * len(taus)))]

                waits = [float(sp.attrs.get("buffer_wait_s") or 0.0)
                         for sp in folded]
                lines.append(
                    f"async staleness: p50 {_q(0.50):.0f}   "
                    f"p90 {_q(0.90):.0f}   p99 {_q(0.99):.0f}   "
                    f"mean buffer wait "
                    f"{sum(waits) / len(waits) * 1e3:.1f} ms")
    # Convergence observatory: aggregate/apply/server_update spans carry
    # conv_* attrs only when the run folded updates under --learn-observe.
    conv = [sp for spans in by_name.values() for sp in spans
            if sp.attrs.get("conv_update_norm") is not None]
    if conv:
        conv.sort(key=lambda sp: sp.t_wall)
        norms = [float(sp.attrs["conv_update_norm"]) for sp in conv]
        trends = [str(sp.attrs.get("conv_trend") or "") for sp in conv]
        census: dict[str, int] = {}
        for t in trends:
            if t:
                census[t] = census.get(t, 0) + 1
        census_s = " ".join(f"{k}={census[k]}" for k in sorted(census))
        lines.append("")
        lines.append(
            f"learning: {len(conv)} observed fold(s), update norm "
            f"{norms[0]:.3e} -> {norms[-1]:.3e} (max {max(norms):.3e})"
            + (f"; trend {census_s}" if census_s else ""))
    metrics = doc.get("otherData", {}).get("metrics")
    if metrics:
        lines.append("")
        lines.append("metrics:")
        for k in sorted(metrics):
            lines.append(f"  {k}: {json.dumps(metrics[k], sort_keys=True)}")
    return "\n".join(lines)
