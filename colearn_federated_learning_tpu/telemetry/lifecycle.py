"""Round-scoped telemetry lifecycle: span-trace window + jax profiler.

One object owns BOTH per-round observability mechanisms so they share a
lifecycle (open before the round, settle after it, flush on close, even
on an exception mid-round):

- the span tracer window: with ``RunConfig.trace_dir`` set, spans are
  recorded and written as Chrome-trace JSON; ``trace_rounds`` > 0 limits
  recording to the first N rounds the lifecycle sees (0 = all rounds);
- the ``jax.profiler`` window (``RunConfig.profile_dir``): the existing
  :class:`~..utils.profiling.RoundProfiler`, folded in unchanged.

``engine.fit`` drives ``before_round``/``after_round``/``end_round``/
``close``; the
coordinators use the tracer half only (their round loop has no jax
device program to profile on the server side).
"""

from __future__ import annotations

from typing import Optional

from colearn_federated_learning_tpu.telemetry import export, registry
from colearn_federated_learning_tpu.telemetry.tracer import Tracer
from colearn_federated_learning_tpu.utils.profiling import RoundProfiler


class RoundTelemetry:
    """Drive the trace window and the jax profiler window together."""

    def __init__(self, run_config, tracer: Tracer):
        self.tracer = tracer
        self.trace_dir: Optional[str] = getattr(run_config, "trace_dir", None)
        self.trace_rounds: int = getattr(run_config, "trace_rounds", 0) or 0
        self.run_name: str = getattr(run_config, "name", "default")
        self.profiler = RoundProfiler(getattr(run_config, "profile_dir", None))
        self._first_round: Optional[int] = None
        self._written: Optional[str] = None
        tracer.enabled = bool(self.trace_dir)

    @property
    def profiling(self) -> bool:
        """A jax trace window is open — the engine inserts its round
        barrier only while this (or span tracing) is on."""
        return self.profiler.active

    @property
    def tracing(self) -> bool:
        return self.tracer.enabled

    @property
    def trace_path(self) -> Optional[str]:
        """Where the Chrome-trace JSON lands (None without a trace_dir).
        Valid before the file exists — the CLI reports it up front."""
        if not self.trace_dir:
            return None
        return export.default_trace_path(self.trace_dir, self.run_name)

    def before_round(self, round_idx: int) -> None:
        self.profiler.before_round(round_idx)
        if not self.trace_dir:
            return
        if self._first_round is None:
            self._first_round = round_idx
        if self.trace_rounds:
            in_window = round_idx - self._first_round < self.trace_rounds
            self.tracer.enabled = in_window

    def after_round(self, round_idx: int) -> None:
        """Profiler half — call while the round's device work is settled,
        still inside the round span."""
        self.profiler.after_round(round_idx)

    def end_round(self, round_idx: int) -> None:
        """Trace-window half — call AFTER the round span has closed, so
        an early flush includes the final traced round."""
        if (self.trace_dir and self.trace_rounds
                and self._first_round is not None
                and round_idx - self._first_round == self.trace_rounds - 1):
            # The window just closed: flush now, so a long run yields its
            # trace file without waiting for the final round.
            self.write()

    def write(self) -> Optional[str]:
        if not self.trace_dir:
            return None
        self._written = export.write_tracer(
            self.trace_dir, self.run_name, self.tracer,
            metrics=registry.get_registry().snapshot(),
        )
        return self._written

    def close(self) -> Optional[str]:
        """Settle both windows.  Safe under mid-round exceptions — the
        process-global jax profiler must never be left running, and
        whatever spans were recorded still reach disk."""
        self.profiler.close()
        if self.trace_dir and (self._written is None or self.tracer.enabled):
            self.write()
        return self._written
