"""Process-wide metrics registry: counters, gauges, histograms.

Spans answer *where the time went* inside one round; the registry holds
the cumulative process counters a production federation is tuned by —
bytes on the wire, dropped clients, dispatch retries, host-to-device
transfer time — with quantile summaries for the distributions.  All
instruments are thread-safe (the comm planes increment from fan-out and
dispatcher threads) and dependency-free.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Union

from colearn_federated_learning_tpu.analysis import metric_catalog

Number = Union[int, float]

# Opt-in guard for ad-hoc scripts: with COLEARN_METRICS_STRICT=1, a name
# missing from analysis/metric_catalog.py raises at first touch.  The
# default stays permissive (tests register scratch instruments); the
# CL005 lint enforces the catalog on the codebase itself either way.
_STRICT = os.environ.get("COLEARN_METRICS_STRICT", "") not in ("", "0")


def labeled_name(name: str, labels: dict) -> str:
    """Canonical key for a labeled instrument: ``name{k=v,...}`` with
    keys sorted, so the same label set always maps to the same child."""
    items = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{items}}}"


class Counter:
    """Monotonically increasing value (bytes sent, retries, drops)."""

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: Number = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease by {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class _ChildCounter(Counter):
    """Labeled child (``comm.retry_total{device=3}``): every increment
    rolls up into the unlabeled parent, so aggregate readers (the soak
    gate's counter deltas, coordinator round records) keep working while
    snapshots additionally show per-label attribution."""

    def __init__(self, name: str, parent: Counter):
        super().__init__(name)
        self._parent = parent

    def inc(self, n: Number = 1) -> None:
        super().inc(n)
        self._parent.inc(n)


class Gauge:
    """Last-observed value (current cohort size, h2d transfer seconds)."""

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None

    def set(self, v: Number) -> None:
        self.value = float(v)


class _ChildGauge(Gauge):
    """Labeled gauge child (``comm.agg_heartbeat_age_s{agg=0}``).  Unlike
    counters there is no meaningful aggregate roll-up — a gauge is
    last-observed, and "last across labels" is noise — so the parent is
    left untouched and exists only to reserve the family name/kind."""




class Histogram:
    """Streaming distribution summary with bounded memory.

    Running count/sum/min/max are exact; quantiles come from a bounded
    sample buffer.  When the buffer fills, it is thinned by keeping every
    other sample and the admission stride doubles — a deterministic
    sketch (no RNG) whose bias is acceptable for the p50/p90/p99 this
    registry reports.
    """

    def __init__(self, name: str, max_samples: int = 8192):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._samples: list[float] = []
        self._max_samples = max_samples
        self._stride = 1
        self._lock = threading.Lock()

    def observe(self, v: Number) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if (self.count - 1) % self._stride == 0:
                self._samples.append(v)
                if len(self._samples) >= self._max_samples:
                    self._samples = self._samples[::2]
                    self._stride *= 2

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            samples = sorted(self._samples)
        if not samples:
            return None
        idx = min(len(samples) - 1, int(q * len(samples)))
        return samples[max(0, idx)]

    def summary(self) -> dict:
        out = {"count": self.count, "sum": self.sum}
        if self.count:
            out.update(
                mean=self.sum / self.count, min=self.min, max=self.max,
                p50=self.quantile(0.50), p90=self.quantile(0.90),
                p99=self.quantile(0.99),
            )
        return out


class _ChildHistogram(Histogram):
    """Labeled histogram child (``comm.agg_fold_time_s{agg=0}``): every
    observation also lands in the unlabeled parent, so aggregate readers
    (render_top's latency lines, SLO gates over the family) keep working
    while the exposition additionally shows per-label quantiles."""

    def __init__(self, name: str, parent: Histogram,
                 max_samples: int = 8192):
        super().__init__(name, max_samples=max_samples)
        self._parent = parent

    def observe(self, v: Number) -> None:
        super().observe(v)
        self._parent.observe(v)


class MetricsRegistry:
    """Named instruments, created on first touch (prometheus-client
    idiom without the dependency).  Asking for an existing name with a
    different instrument kind raises — silent type confusion would
    corrupt both series."""

    def __init__(self):
        self._instruments: dict = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                if _STRICT and not metric_catalog.is_known(name):
                    raise ValueError(
                        f"metric {name!r} is not declared in "
                        "analysis/metric_catalog.py "
                        "(COLEARN_METRICS_STRICT=1)"
                    )
                inst = self._instruments[name] = cls(name, **kw)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} is a {type(inst).__name__}, "
                    f"not a {cls.__name__}"
                )
            return inst

    def counter(self, name: str,
                labels: Optional[dict] = None) -> Counter:
        """Without ``labels``, the (aggregate) counter.  With ``labels``,
        the child registered under ``name{k=v,...}`` whose increments
        also roll up into the aggregate (see _ChildCounter)."""
        parent = self._get(name, Counter)
        if not labels:
            return parent
        full = labeled_name(name, labels)
        with self._lock:
            inst = self._instruments.get(full)
            if inst is None:
                inst = self._instruments[full] = _ChildCounter(full, parent)
            elif not isinstance(inst, Counter):
                raise TypeError(
                    f"metric {full!r} is a {type(inst).__name__}, "
                    "not a Counter"
                )
            return inst

    def gauge(self, name: str, labels: Optional[dict] = None) -> Gauge:
        """Without ``labels``, the plain gauge.  With ``labels``, the
        child registered under ``name{k=v,...}``; no aggregate roll-up
        (a last-observed value has no meaningful sum across labels)."""
        parent = self._get(name, Gauge)
        if not labels:
            return parent
        full = labeled_name(name, labels)
        with self._lock:
            inst = self._instruments.get(full)
            if inst is None:
                inst = self._instruments[full] = _ChildGauge(full)
            elif not isinstance(inst, Gauge):
                raise TypeError(
                    f"metric {full!r} is a {type(inst).__name__}, "
                    "not a Gauge"
                )
            return inst

    def histogram(self, name: str, labels: Optional[dict] = None,
                  max_samples: int = 8192) -> Histogram:
        """Without ``labels``, the (aggregate) histogram.  With
        ``labels``, the child registered under ``name{k=v,...}`` whose
        observations also roll up into the aggregate (_ChildHistogram),
        mirroring the labeled-counter contract."""
        parent = self._get(name, Histogram, max_samples=max_samples)
        if not labels:
            return parent
        full = labeled_name(name, labels)
        with self._lock:
            inst = self._instruments.get(full)
            if inst is None:
                inst = self._instruments[full] = _ChildHistogram(
                    full, parent, max_samples=max_samples)
            elif not isinstance(inst, Histogram):
                raise TypeError(
                    f"metric {full!r} is a {type(inst).__name__}, "
                    "not a Histogram"
                )
            return inst

    def snapshot(self) -> dict:
        """Flat JSON-safe dump: counters/gauges map to their value,
        histograms to their summary dict."""
        with self._lock:
            items = list(self._instruments.items())
        out = {}
        for name, inst in items:
            if isinstance(inst, Counter):
                out[name] = inst.value
            elif isinstance(inst, Gauge):
                out[name] = inst.value
            else:
                out[name] = inst.summary()
        return out

    def typed_snapshot(self) -> dict:
        """Like :meth:`snapshot` but each value is ``(kind, value)`` with
        kind in {counter, gauge, histogram} — exposition formats (the
        Prometheus endpoint's ``# TYPE`` lines) need the instrument kind,
        which the flat snapshot erases."""
        with self._lock:
            items = list(self._instruments.items())
        out = {}
        for name, inst in items:
            if isinstance(inst, Counter):
                out[name] = ("counter", inst.value)
            elif isinstance(inst, Gauge):
                out[name] = ("gauge", inst.value)
            else:
                out[name] = ("histogram", inst.summary())
        return out

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every layer increments into; tests that
    need isolation construct their own MetricsRegistry."""
    return _default_registry
