"""Process-wide metrics registry: counters, gauges, histograms.

Spans answer *where the time went* inside one round; the registry holds
the cumulative process counters a production federation is tuned by —
bytes on the wire, dropped clients, dispatch retries, host-to-device
transfer time — with quantile summaries for the distributions.  All
instruments are thread-safe (the comm planes increment from fan-out and
dispatcher threads) and dependency-free.
"""

from __future__ import annotations

import threading
from typing import Optional, Union

Number = Union[int, float]


class Counter:
    """Monotonically increasing value (bytes sent, retries, drops)."""

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: Number = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease by {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-observed value (current cohort size, h2d transfer seconds)."""

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None

    def set(self, v: Number) -> None:
        self.value = float(v)


class Histogram:
    """Streaming distribution summary with bounded memory.

    Running count/sum/min/max are exact; quantiles come from a bounded
    sample buffer.  When the buffer fills, it is thinned by keeping every
    other sample and the admission stride doubles — a deterministic
    sketch (no RNG) whose bias is acceptable for the p50/p90/p99 this
    registry reports.
    """

    def __init__(self, name: str, max_samples: int = 8192):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._samples: list[float] = []
        self._max_samples = max_samples
        self._stride = 1
        self._lock = threading.Lock()

    def observe(self, v: Number) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if (self.count - 1) % self._stride == 0:
                self._samples.append(v)
                if len(self._samples) >= self._max_samples:
                    self._samples = self._samples[::2]
                    self._stride *= 2

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            samples = sorted(self._samples)
        if not samples:
            return None
        idx = min(len(samples) - 1, int(q * len(samples)))
        return samples[max(0, idx)]

    def summary(self) -> dict:
        out = {"count": self.count, "sum": self.sum}
        if self.count:
            out.update(
                mean=self.sum / self.count, min=self.min, max=self.max,
                p50=self.quantile(0.50), p90=self.quantile(0.90),
                p99=self.quantile(0.99),
            )
        return out


class MetricsRegistry:
    """Named instruments, created on first touch (prometheus-client
    idiom without the dependency).  Asking for an existing name with a
    different instrument kind raises — silent type confusion would
    corrupt both series."""

    def __init__(self):
        self._instruments: dict = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, **kw)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} is a {type(inst).__name__}, "
                    f"not a {cls.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, max_samples: int = 8192) -> Histogram:
        return self._get(name, Histogram, max_samples=max_samples)

    def snapshot(self) -> dict:
        """Flat JSON-safe dump: counters/gauges map to their value,
        histograms to their summary dict."""
        with self._lock:
            items = list(self._instruments.items())
        out = {}
        for name, inst in items:
            if isinstance(inst, Counter):
                out[name] = inst.value
            elif isinstance(inst, Gauge):
                out[name] = inst.value
            else:
                out[name] = inst.summary()
        return out

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every layer increments into; tests that
    need isolation construct their own MetricsRegistry."""
    return _default_registry
