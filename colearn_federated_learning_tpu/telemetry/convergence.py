"""Convergence observatory: learning-health signals from the aggregate.

Every prior observability plane (spans, metrics, flight recorder, health
ledger) watches the *machinery*; this one watches the *model*.  Per round
it derives, from the already-materialized mean update — pure pytree math,
jit-safe, zero extra communication:

- global update norm and the effective server step it induces
  (``server_lr * ||delta||``);
- cosine similarity to the previous round's update (progress points the
  same way round over round; oscillation flips sign);
- an EWMA'd update-norm trend classified into ``warmup`` / ``progress``
  / ``plateau`` / ``oscillation`` / ``divergence``.

The same constraint secure aggregation imposes (Bonawitz et al.: the
server only ever opens the aggregate) shapes the API: everything above
needs ONLY the aggregate.  Per-device/per-cohort skew attribution
(:func:`device_skew`, :func:`cohort_skew`) is reserved for planes where
individual updates are legitimately visible — secure_agg off, or fleetsim
where updates are simulation-local.

All tree math goes through ``jax.tree`` leaves, so LoRA factor trees
(``{path: {"lora_a": A, "lora_b": B}}``) fold natively, exactly like the
StreamingFolder does — no densify, no special-casing.

Feature-gated everywhere: ``--learn-observe`` stamps ``conv_*`` record
keys and ``learn.*`` metrics; default round records stay byte-identical
(pinned by tests on the sync, async, and fleetsim planes).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Iterable, Optional

TREND_WARMUP = "warmup"
TREND_PROGRESS = "progress"
TREND_PLATEAU = "plateau"
TREND_OSCILLATION = "oscillation"
TREND_DIVERGENCE = "divergence"
TRENDS = (TREND_WARMUP, TREND_PROGRESS, TREND_PLATEAU,
          TREND_OSCILLATION, TREND_DIVERGENCE)


# ------------------------------------------------------------- tree math --
def tree_norm(tree) -> float:
    """Global L2 norm over every leaf (dense pytrees and LoRA factor
    trees alike).  Host float — call once per round, never per step."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(tree)
    if not leaves:
        return 0.0
    return float(jnp.sqrt(sum(jnp.vdot(x, x).real for x in leaves)))


def tree_cosine(a, b) -> Optional[float]:
    """Cosine similarity between two pytrees with identical structure;
    ``None`` (undefined, NOT NaN) when either side has zero norm."""
    import jax
    import jax.numpy as jnp

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    dot = float(sum(jnp.vdot(x, y).real for x, y in zip(la, lb))) \
        if la else 0.0
    na, nb = tree_norm(a), tree_norm(b)
    if na <= 0.0 or nb <= 0.0:
        return None
    return max(-1.0, min(1.0, dot / (na * nb)))


# ---------------------------------------------------------- observatory --
@dataclasses.dataclass
class ConvergenceObservatory:
    """Stateful per-plane learning-health tracker.

    ``observe(mean_delta, lr=...)`` returns the round's ``conv_*``
    signal dict (record-ready scalars/strings) or ``None`` for a no-op
    round (quorum skip / unmask failure): state is untouched, so the
    trend picks up where it left off.
    """

    ewma_alpha: float = 0.3          # update-norm EWMA smoothing
    divergence_ratio: float = 2.0    # norm > ratio * ewma -> divergence
    plateau_band: float = 0.1        # |norm/ewma - 1| <= band -> plateau
    oscillation_cos: float = -0.2    # cos(prev) below this -> oscillation
    warmup_rounds: int = 2           # observations before classifying
    keep_prev: bool = True           # retain prev update for cosine

    _prev_update: Any = dataclasses.field(default=None, repr=False)
    _ewma: Optional[float] = None
    _seen: int = 0

    def observe(self, mean_delta, *, lr: float = 1.0) -> Optional[dict]:
        if mean_delta is None:
            return None
        norm = tree_norm(mean_delta)
        if not math.isfinite(norm):
            # A non-finite aggregate is the strongest divergence signal
            # there is; classify it directly rather than poisoning the
            # EWMA with inf/NaN.
            self._seen += 1
            self._prev_update = None
            return {"conv_update_norm": norm,
                    "conv_step_size": norm * float(lr),
                    "conv_norm_ewma": float(self._ewma or 0.0),
                    "conv_trend": TREND_DIVERGENCE}
        cos = (tree_cosine(mean_delta, self._prev_update)
               if self._prev_update is not None else None)
        trend = self._classify(norm, cos)
        prev_ewma = self._ewma
        self._ewma = (norm if prev_ewma is None
                      else self.ewma_alpha * norm
                      + (1.0 - self.ewma_alpha) * prev_ewma)
        self._seen += 1
        if self.keep_prev:
            self._prev_update = mean_delta
        sig = {
            "conv_update_norm": round(norm, 8),
            "conv_step_size": round(norm * float(lr), 8),
            "conv_norm_ewma": round(self._ewma, 8),
            "conv_trend": trend,
        }
        if cos is not None:
            # Key only present once a previous update exists AND both
            # norms are nonzero — first round stays cosine-free by
            # construction (undefined, not NaN).
            sig["conv_cos_prev"] = round(cos, 6)
        return sig

    def _classify(self, norm: float, cos: Optional[float]) -> str:
        if self._seen < self.warmup_rounds or self._ewma is None:
            return TREND_WARMUP
        if norm > self.divergence_ratio * max(self._ewma, 1e-30):
            return TREND_DIVERGENCE
        if cos is not None and cos < self.oscillation_cos:
            return TREND_OSCILLATION
        if abs(norm / max(self._ewma, 1e-30) - 1.0) <= self.plateau_band:
            return TREND_PLATEAU
        return TREND_PROGRESS

    # -- metric export (learn.* — declared in analysis/metric_catalog.py)
    def export_metrics(self, reg, sig: dict) -> None:
        reg.gauge("learn.update_norm").set(sig["conv_update_norm"])
        reg.gauge("learn.update_norm_ewma").set(sig["conv_norm_ewma"])
        reg.gauge("learn.step_size").set(sig["conv_step_size"])
        if "conv_cos_prev" in sig:
            reg.gauge("learn.cos_prev").set(sig["conv_cos_prev"])
        reg.histogram("learn.update_norm_dist").observe(
            sig["conv_update_norm"])
        reg.counter(
            f"learn.trend_total{{trend={sig['conv_trend']}}}").inc()
        if "conv_cohort_skew" in sig:
            reg.gauge("learn.cohort_skew").set(sig["conv_cohort_skew"])


# ------------------------------------------------- per-device attribution --
def device_skew(norms: Iterable[float], *,
                anomaly_ratio: float = 3.0) -> dict:
    """Summarize per-device update norms: median, p90, and the indices of
    anomalously-large updates (norm > ``anomaly_ratio`` x median — a
    poisoned or diverging device is a health event, same as a straggler).

    Only meaningful where individual updates are visible (secure_agg off,
    or fleetsim).  Returns ``{"median": ..., "p90": ..., "anomalies":
    [idx, ...]}``; empty input -> zeros and no anomalies.
    """
    xs = sorted(float(n) for n in norms)
    if not xs:
        return {"median": 0.0, "p90": 0.0, "anomalies": []}
    def q(p):
        i = min(len(xs) - 1, max(0, int(round(p * (len(xs) - 1)))))
        return xs[i]
    med = q(0.5)
    thresh = anomaly_ratio * max(med, 1e-30)
    anomalies = [i for i, n in enumerate(float(n) for n in norms)
                 if n > thresh]
    return {"median": med, "p90": q(0.9), "anomalies": anomalies}


def cohort_skew(class_sums, class_weights, aggregate) -> dict:
    """Attribute drift to cohorts: cosine of each cohort's weighted-mean
    update (centroid) to the global aggregate.

    ``class_sums`` is a pytree whose leaves carry a leading cohort axis
    (per-cohort weighted delta sums); ``class_weights`` the matching
    ``(num_cohorts,)`` weight vector.  Skew is ``1 - min_cos`` over
    populated cohorts — 0 when every cohort pushes the same way (IID),
    approaching/exceeding 1 as a seeded non-IID cluster pulls against
    the aggregate.  Returns record-ready ``conv_cohort_*`` floats.
    """
    import jax
    import numpy as np

    w = np.asarray(class_weights, dtype=np.float64)
    coses = []
    for c in range(w.shape[0]):
        if w[c] <= 0.0:
            continue
        centroid = jax.tree.map(lambda x: x[c] / w[c], class_sums)
        cos = tree_cosine(centroid, aggregate)
        if cos is not None:
            coses.append(cos)
    if not coses:
        return {"conv_cohort_skew": 0.0, "conv_cohort_cos_min": 1.0}
    return {"conv_cohort_skew": round(1.0 - min(coses), 6),
            "conv_cohort_cos_min": round(min(coses), 6)}


# ------------------------------------------------------------- reporting --
def convergence_records(records: Iterable[dict]) -> list:
    """The sub-sequence of round records carrying learning signals,
    ordered by round when a round key is present."""
    out = [r for r in records if "conv_update_norm" in r]
    key = "round" if all("round" in r for r in out) else None
    if key:
        out.sort(key=lambda r: r[key])
    return out


def render_convergence_report(records: Iterable[dict]) -> str:
    """Round-over-round learning report for ``colearn converge`` from any
    committed JSONL (results dirs, event streams): per-round norm / step
    / EWMA / cosine / trend, then a trend census and the first round each
    non-progress trend appeared."""
    recs = convergence_records(records)
    if not recs:
        return ("no learning signals found "
                "(run with --learn-observe to stamp conv_* keys)")
    lines = ["round  update_norm     step_size       ewma        "
             "cos_prev  trend"]
    for r in recs:
        cos = r.get("conv_cos_prev")
        lines.append(
            "%5s  %-14.6g  %-14.6g  %-10.5g  %-8s  %s" % (
                r.get("round", "-"),
                r["conv_update_norm"],
                r.get("conv_step_size", float("nan")),
                r.get("conv_norm_ewma", float("nan")),
                ("%.4f" % cos) if cos is not None else "-",
                r.get("conv_trend", "-")))
    census: dict = {}
    first: dict = {}
    for r in recs:
        t = r.get("conv_trend", "-")
        census[t] = census.get(t, 0) + 1
        first.setdefault(t, r.get("round", "-"))
    lines.append("")
    lines.append("trends: " + "  ".join(
        f"{t}={census[t]}" for t in TRENDS if t in census))
    for t in (TREND_DIVERGENCE, TREND_OSCILLATION, TREND_PLATEAU):
        if t in first:
            lines.append(f"first {t}: round {first[t]}")
    norms = [r["conv_update_norm"] for r in recs]
    lines.append("update_norm: first=%.6g last=%.6g max=%.6g" % (
        norms[0], norms[-1], max(norms)))
    if any("conv_cohort_skew" in r for r in recs):
        skews = [r["conv_cohort_skew"] for r in recs
                 if "conv_cohort_skew" in r]
        lines.append("cohort_skew: mean=%.4f max=%.4f" % (
            sum(skews) / len(skews), max(skews)))
    return "\n".join(lines)
