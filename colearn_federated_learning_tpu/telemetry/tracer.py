"""Dependency-free span tracer for federated rounds.

The reference logs accuracy with prints/CSV and has no tracing (PAPER.md
§5); the only timing signal in the rebuild so far was whole-round wall
time plus a 2-round ``jax.profiler`` window.  This tracer answers *where*
a round spends its time: nested spans with monotonic-clock durations and
wall-clock anchors, cheap enough to leave on in production paths.

Design points:

- ``tracer.span("aggregate", round=3)`` is a context manager; nesting is
  tracked per thread, so spans opened inside a fan-out worker thread do
  not accidentally parent onto the coordinator's round span.
- The context manager ALWAYS yields a timed :class:`Span` — even when the
  tracer is disabled — so hot paths can read ``sp.duration_s`` for
  metrics (JSONL phase fields) without a second clock read; only the
  *recording* into the in-memory buffer is gated on ``enabled``.
- Spans carry ``(trace_id, span_id, parent_id)``; ``current_context()``
  exports the active identity for wire propagation and ``span(parent=…)``
  adopts a remote parent, which is how a worker's local-train span
  stitches under the coordinator's round span across processes.
- Cross-process stitching is completed by ``Span.to_dict`` /
  ``Tracer.adopt``: a worker ships its finished spans back in the reply
  metadata and the coordinator adopts them into its own buffer.

Wall-clock (``time.time``) anchors position spans on a shared timeline
across processes on one machine; durations always come from
``time.perf_counter`` so individual spans are immune to clock steps.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterator, Optional

SpanContext = tuple[str, str]            # (trace_id, span_id)

_id_counter = itertools.count(1)
_id_lock = threading.Lock()


def new_id() -> str:
    """Process-unique 64-bit-style hex id (pid-salted so ids minted by a
    coordinator and an in-process loopback worker never collide)."""
    with _id_lock:
        n = next(_id_counter)
    return f"{os.getpid() & 0xFFFF:04x}{n & 0xFFFFFFFFFFFF:012x}"


@dataclass
class Span:
    """One timed operation.  ``t_wall`` anchors the span on the shared
    wall-clock timeline; ``duration_s`` is monotonic-clock elapsed."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    process: str = "main"
    t_wall: float = 0.0                  # epoch seconds at start
    attrs: dict = field(default_factory=dict)
    _t0: float = 0.0                     # perf_counter at start
    _t1: Optional[float] = None          # perf_counter at end

    @property
    def ended(self) -> bool:
        return self._t1 is not None

    @property
    def duration_s(self) -> float:
        return (self._t1 if self._t1 is not None else time.perf_counter()) - self._t0

    @property
    def context(self) -> SpanContext:
        return (self.trace_id, self.span_id)

    def to_dict(self) -> dict:
        """JSON-safe wire form (worker reply metadata / trace files)."""
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "process": self.process,
            "t_wall": self.t_wall,
            "duration_s": self.duration_s,
            "attrs": dict(self.attrs),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        sp = cls(
            name=d["name"], trace_id=d["trace_id"], span_id=d["span_id"],
            parent_id=d.get("parent_id"), process=d.get("process", "main"),
            t_wall=float(d.get("t_wall", 0.0)), attrs=dict(d.get("attrs", {})),
        )
        sp._t0 = 0.0
        sp._t1 = float(d.get("duration_s", 0.0))
        return sp


class Tracer:
    """Per-component span recorder (engine, coordinator, one per worker).

    ``enabled`` gates recording only — ``span()`` always times.  The
    buffer is bounded by ``max_spans``; once full, new spans are dropped
    and counted in ``dropped`` (a trace that silently swallows its own
    overflow would misreport coverage).
    """

    def __init__(self, process: str = "main", enabled: bool = True,
                 max_spans: int = 100_000):
        self.process = process
        self.enabled = enabled
        self.max_spans = max_spans
        self.spans: list[Span] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._local = threading.local()

    # -- per-thread span stack -----------------------------------------
    def _stack(self) -> list[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_context(self) -> Optional[SpanContext]:
        """(trace_id, span_id) of this thread's innermost open span —
        the identity to inject into outbound messages."""
        stack = self._stack()
        return stack[-1].context if stack else None

    @contextmanager
    def span(self, name: str, parent: Optional[SpanContext] = None,
             **attrs) -> Iterator[Span]:
        """Open a span.  ``parent`` overrides the thread-local nesting
        with an explicit (possibly remote) parent context."""
        stack = self._stack()
        if parent is not None:
            trace_id, parent_id = parent
        elif stack:
            trace_id, parent_id = stack[-1].trace_id, stack[-1].span_id
        else:
            trace_id, parent_id = new_id(), None
        sp = Span(name=name, trace_id=trace_id, span_id=new_id(),
                  parent_id=parent_id, process=self.process,
                  t_wall=time.time(), attrs=attrs)
        sp._t0 = time.perf_counter()
        stack.append(sp)
        try:
            yield sp
        finally:
            sp._t1 = time.perf_counter()
            stack.pop()
            self._record(sp)

    def _record(self, sp: Span) -> None:
        sink = getattr(self._local, "capture", None)
        if sink is not None:
            sink.append(sp)
        if not self.enabled:
            return
        with self._lock:
            if len(self.spans) < self.max_spans:
                self.spans.append(sp)
            else:
                self.dropped += 1

    @contextmanager
    def capture(self) -> Iterator[list[Span]]:
        """Additionally collect every span FINISHED on this thread while
        active — how a worker gathers the spans of one request to ship
        them back to the coordinator, without draining the shared
        buffer under concurrent requests."""
        prev = getattr(self._local, "capture", None)
        captured: list[Span] = []
        self._local.capture = captured
        try:
            yield captured
        finally:
            self._local.capture = prev

    # -- cross-process stitching ---------------------------------------
    def adopt(self, span_dicts: list, process: Optional[str] = None) -> int:
        """Ingest remote spans (``Span.to_dict`` forms) into this buffer;
        returns how many were adopted.  Malformed entries are skipped —
        a peer must not be able to kill the coordinator's trace."""
        adopted = 0
        for d in span_dicts or []:
            try:
                sp = Span.from_dict(d)
            except (KeyError, TypeError, ValueError):
                continue
            if process is not None:
                sp.process = process
            with self._lock:
                if len(self.spans) < self.max_spans:
                    self.spans.append(sp)
                    adopted += 1
                else:
                    self.dropped += 1
        return adopted

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self.spans)

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()
            self.dropped = 0


_default_tracer = Tracer(process="main")


def get_tracer() -> Tracer:
    """Process-wide default tracer (components that want isolation — the
    engine, each worker — hold their own instance instead)."""
    return _default_tracer
