"""XLA/JAX runtime introspection + live metric export.

The span tracer answers *where a round spent its time*; this module
answers the production questions the spans cannot:

- **Is the program recompiling?**  :class:`CompileTracker` wraps a
  jitted callable and fingerprints every call's abstract signature
  (treedef + leaf shape/dtype).  The first distinct signature is the
  expected compile (``telemetry.compile_total{fn=...}``); every later
  NEW signature is a recompile, counted with an attributed reason —
  ``telemetry.recompile_total{fn=...,reason=shape|dtype|structure}`` —
  so "the coordinator silently recompiles every round" is a visible
  counter, and fleetsim's one-compile-per-sweep claim is a tested
  invariant instead of a docstring.
- **What does one round cost?**  :func:`compiled_cost` runs XLA's own
  ``cost_analysis`` on the AOT-compiled executable (cached per
  signature, so asking twice is free) — the automated replacement for
  the manual lower/compile procedure PERF.md used to prescribe.
- **Is HBM creeping toward OOM?**  :func:`sample_device_memory` turns
  ``device.memory_stats()`` into live gauges
  (``runtime.hbm_bytes_in_use`` / ``..._limit`` / ``..._peak``).
- **How do I watch it?**  :func:`prometheus_text` renders a registry
  snapshot in Prometheus text exposition format; :class:`MetricsExporter`
  serves it from a stdlib HTTP thread (``/metrics``, plus the raw JSON
  snapshot at ``/snapshot.json`` that ``colearn top`` consumes); and
  :class:`EventLog` appends machine-readable JSONL events (round
  records, lifecycle marks) for the push-based half.

Everything here is dependency-free host-side code: no prometheus
client, no agent, no thread unless an exporter is explicitly started.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Optional

from colearn_federated_learning_tpu.telemetry.registry import (
    MetricsRegistry,
    get_registry,
)

__all__ = [
    "CompileTracker",
    "EventLog",
    "MetricsExporter",
    "compiled_cost",
    "prometheus_text",
    "sample_device_memory",
]


# ------------------------------------------------------------ signatures --
def _leaf_abstract(leaf) -> tuple:
    """(shape, dtype) for array-likes; (type-name, value-ignored) for
    host scalars — a Python int changing VALUE must not read as a
    recompile (weak-typed scalars usually re-trace only on type)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    return ((), type(leaf).__name__)


def abstract_signature(args: tuple, kwargs: dict) -> tuple:
    """Hashable abstraction of a call: (treedef repr, leaf abstracts).
    Two calls with the same signature hit the same jit-cache entry;
    a differing signature is (at least) a cache miss."""
    import jax

    leaves, treedef = jax.tree.flatten((args, kwargs))
    return (str(treedef), tuple(_leaf_abstract(l) for l in leaves))


def _recompile_reason(prev_sigs, sig) -> str:
    """Attribute WHY a new signature missed the cache, against the most
    recently seen signature: structure (treedef) > dtype > shape."""
    if not prev_sigs:
        return "shape"
    treedef, leaves = sig
    p_treedef, p_leaves = prev_sigs[-1]
    if treedef != p_treedef or len(leaves) != len(p_leaves):
        return "structure"
    if any(l[1] != p[1] for l, p in zip(leaves, p_leaves)):
        return "dtype"
    return "shape"


class CompileTracker:
    """Transparent wrapper around a (jitted) callable that counts the
    distinct call signatures it has seen.

    ``tracker(...)`` forwards to the wrapped fn; attribute access
    (``.lower``, ``.trace`` …) passes through, so code holding the
    tracker can keep using the jit AOT surface.  ``compiles`` is the
    number of distinct signatures — the executable count a correct
    static-shape pipeline holds at exactly 1 per sweep shape.
    """

    def __init__(self, fn, name: str,
                 registry: Optional[MetricsRegistry] = None):
        self._fn = fn
        self.name = name
        self._registry = registry
        self._sigs: list = []
        self._sig_set: set = set()
        self._cost_cache: dict = {}
        self._lock = threading.Lock()

    # -- introspection --------------------------------------------------
    @property
    def compiles(self) -> int:
        return len(self._sigs)

    @property
    def recompiles(self) -> int:
        return max(0, len(self._sigs) - 1)

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None else (
            get_registry())

    def _note(self, sig) -> None:
        with self._lock:
            if sig in self._sig_set:
                return
            reason = None
            if self._sigs:
                reason = _recompile_reason(self._sigs, sig)
            self._sig_set.add(sig)
            self._sigs.append(sig)
        reg = self._reg()
        reg.counter("telemetry.compile_total",
                    labels={"fn": self.name}).inc()
        if reason is not None:
            reg.counter("telemetry.recompile_total",
                        labels={"fn": self.name, "reason": reason}).inc()

    # -- call surface ---------------------------------------------------
    def __call__(self, *args, **kwargs):
        self._note(abstract_signature(args, kwargs))
        return self._fn(*args, **kwargs)

    def __getattr__(self, attr):
        return getattr(self._fn, attr)

    # -- cost analysis --------------------------------------------------
    def cost_analysis(self, *args, **kwargs) -> dict:
        """XLA ``cost_analysis`` of the executable for THIS signature
        (AOT lower+compile; cached per signature so repeated asks are
        free).  Returns ``{}`` when the wrapped fn has no ``lower``."""
        sig = abstract_signature(args, kwargs)
        with self._lock:
            cached = self._cost_cache.get(sig)
        if cached is not None:
            return dict(cached)
        cost = compiled_cost(self._fn, *args, **kwargs)
        with self._lock:
            self._cost_cache[sig] = cost
        return dict(cost)


def compiled_cost(fn, *args, **kwargs) -> dict:
    """Lower + AOT-compile ``fn`` for these operands and return XLA's
    ``cost_analysis`` dict plus ``compile_s``.  ``{}``-valued keys when
    the backend reports nothing (CPU often does).  NOTE: XLA counts a
    while/scan body ONCE — callers whose FLOPs live in a scan must scale
    by the trip count themselves (fed/engine.round_cost_analysis does)."""
    if not hasattr(fn, "lower"):
        return {}
    t0 = time.perf_counter()
    compiled = fn.lower(*args, **kwargs).compile()
    compile_s = time.perf_counter() - t0
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else (cost or {})
    out = {k: float(v) for k, v in cost.items()
           if isinstance(v, (int, float))}
    out["compile_s"] = compile_s
    return out


# ------------------------------------------------------------ HBM gauges --
def sample_device_memory(
        registry: Optional[MetricsRegistry] = None) -> dict:
    """Sample ``device.memory_stats()`` of the first local device into
    live gauges; returns the raw stats dict (``{}`` when the backend —
    CPU, typically — reports none).  Cheap host call, safe every round."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except (RuntimeError, IndexError, NotImplementedError):
        stats = {}
    if stats:
        reg = registry if registry is not None else get_registry()
        if "bytes_in_use" in stats:
            reg.gauge("runtime.hbm_bytes_in_use").set(
                stats["bytes_in_use"])
        if "bytes_limit" in stats:
            reg.gauge("runtime.hbm_bytes_limit").set(stats["bytes_limit"])
        if "peak_bytes_in_use" in stats:
            reg.gauge("runtime.hbm_peak_bytes_in_use").set(
                stats["peak_bytes_in_use"])
    return stats


# -------------------------------------------------------- Prometheus text --
_LABELED_RE = re.compile(r"^(?P<base>[^{]+)\{(?P<labels>.*)\}$")
_INVALID_CHARS = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "colearn_" + _INVALID_CHARS.sub("_", name)


def _prom_labels(label_str: str) -> str:
    pairs = []
    for item in label_str.split(","):
        if not item:
            continue
        k, _, v = item.partition("=")
        v = v.replace("\\", "\\\\").replace('"', '\\"')
        pairs.append(f'{k}="{v}"')
    return "{" + ",".join(pairs) + "}"


def prometheus_text(typed_snapshot: dict) -> str:
    """Render a :meth:`MetricsRegistry.typed_snapshot` in the Prometheus
    text exposition format (version 0.0.4).

    Counters/gauges become single samples; histograms become Prometheus
    summaries (``_count``/``_sum`` + ``{quantile=...}`` lines).  Labeled
    children (``name{k=v}``) share their parent's metric family.  Gauges
    never set stay out of the exposition entirely.
    """
    families: dict = {}
    for name, (kind, value) in sorted(typed_snapshot.items()):
        m = _LABELED_RE.match(name)
        base, labels = (m.group("base"), m.group("labels")) if m else (
            name, None)
        families.setdefault(base, {"kind": kind, "samples": []})
        families[base]["samples"].append((labels, value))
    lines = []
    for base in sorted(families):
        kind = families[base]["kind"]
        pname = _prom_name(base)
        if kind == "histogram":
            lines.append(f"# TYPE {pname} summary")
            for labels, summary in families[base]["samples"]:
                # A labeled child merges its labels into each quantile
                # line and suffixes _count/_sum, sharing the family of
                # the unlabeled aggregate parent.
                extra = ""
                if labels is not None:
                    extra = _prom_labels(labels)[1:-1]  # inner k="v" pairs
                for q, key in (("0.5", "p50"), ("0.9", "p90"),
                               ("0.99", "p99")):
                    if summary.get(key) is not None:
                        qlabels = f'quantile="{q}"' + (
                            f",{extra}" if extra else "")
                        lines.append(
                            f'{pname}{{{qlabels}}} '
                            f'{summary[key]:.10g}')
                suffix = "{" + extra + "}" if extra else ""
                lines.append(f"{pname}_count{suffix} {summary['count']}")
                lines.append(
                    f"{pname}_sum{suffix} {summary['sum']:.10g}")
            continue
        samples = [(labels, value)
                   for labels, value in families[base]["samples"]
                   if value is not None]    # gauges never set are skipped
        if not samples:
            continue                  # no samples, no family header
        lines.append(f"# TYPE {pname} {kind}")
        for labels, value in samples:
            suffix = _prom_labels(labels) if labels is not None else ""
            lines.append(f"{pname}{suffix} {float(value):.10g}")
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------- exporter --
class MetricsExporter:
    """Pull-based exporter: a daemon HTTP thread serving the process
    registry.  ``GET /metrics`` → Prometheus text; ``GET /snapshot.json``
    → the raw registry snapshot (what ``colearn top`` renders).

    ``port=0`` binds an ephemeral port (read it back from ``.port`` —
    the CLI announces it on stderr so harnesses can find it).
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None):
        self._registry = registry
        self._host = host
        self._want_port = port
        self._server = None
        self._thread = None

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None else (
            get_registry())

    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    def start(self) -> "MetricsExporter":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802  (stdlib handler name)
                reg = exporter._reg()
                if self.path.startswith("/metrics"):
                    body = prometheus_text(reg.typed_snapshot()).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/snapshot.json"):
                    body = json.dumps(reg.snapshot()).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                reg.counter("export.scrapes_total").inc()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *log_args):
                pass                   # scrapes must not spam stderr

        self._server = ThreadingHTTPServer((self._host, self._want_port),
                                           Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="metrics-exporter",
            daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None

    def __enter__(self):
        return self.start() if self._server is None else self

    def __exit__(self, *exc):
        self.close()


# -------------------------------------------------------------- EventLog --
class EventLog:
    """Push-based JSONL event stream: one JSON object per line, flushed
    per write so a tail (or a post-crash reader) always sees complete
    recent events.  Events carry ``ts`` (epoch) and ``event`` (type)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def emit(self, event: str, **payload) -> None:
        doc = {"ts": time.time(), "event": event, **payload}
        line = json.dumps(doc, separators=(",", ":"), default=str) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)
            self._f.flush()
        self._reg_count()

    def _reg_count(self) -> None:
        get_registry().counter("export.events_written_total").inc()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# ---------------------------------------------------------- `colearn top` --
def render_top(snapshot: dict, prev: Optional[dict] = None,
               interval_s: float = 0.0) -> str:
    """Terminal dashboard body from a registry snapshot (pure function —
    the CLI loops it; tests call it directly).  ``prev`` + ``interval_s``
    turn cumulative counters into per-second rates."""

    def val(name, default=0.0):
        v = snapshot.get(name)
        return default if v is None or isinstance(v, dict) else float(v)

    def rate(name):
        if not prev or interval_s <= 0:
            return None
        return (val(name) - float(prev.get(name) or 0.0)) / interval_s

    lines = ["colearn top — live federation metrics", ""]
    rounds = (val("fed.rounds_total") or val("engine.rounds_total")
              or val("fleetsim.rounds_total"))
    rps = (rate("fed.rounds_total") or rate("engine.rounds_total")
           or rate("fleetsim.rounds_total"))
    lines.append(f"rounds total        {rounds:>12.0f}"
                 + (f"   ({rps:.3f}/s)" if rps is not None else ""))
    rt = snapshot.get("fed.round_time_s") or snapshot.get(
        "engine.round_time_s") or snapshot.get("fleetsim.round_time_s")
    if isinstance(rt, dict) and rt.get("count"):
        lines.append(
            f"round time          p50 {rt.get('p50', 0.0):.3f}s   "
            f"p90 {rt.get('p90', 0.0):.3f}s   max {rt.get('max', 0.0):.3f}s")
    lines.append("")
    lines.append("cohort health")
    for label, name in (("  clients dropped  ", "fed.clients_dropped"),
                        ("  clients evicted  ", "fed.clients_evicted"),
                        ("  quorum skips     ", "fed.rounds_skipped_quorum"),
                        ("  resumes          ", "fed.rounds_resumed_total")):
        lines.append(f"{label}{val(name):>12.0f}")
    lines.append("")
    lines.append("faults / retries")
    for label, name in (("  retries          ", "comm.retry_total"),
                        ("  corrupt frames   ", "comm.corrupt_frames_total"),
                        ("  faults injected  ", "fault.injected_total"),
                        ("  reconnect fails  ",
                         "comm.reconnect_failures_total")):
        lines.append(f"{label}{val(name):>12.0f}")
    # Aggregator tier: shown only when a tree is (or was) enrolled —
    # per-agg rows come from the coordinator-side labeled children
    # (heartbeat age gauge, slice-size gauge, partials-folded counter).
    agg_rows: dict[str, dict] = {}
    for name, v in snapshot.items():
        m = _LABELED_RE.match(name)
        if not m or v is None or isinstance(v, dict):
            continue
        base, labels = m.group("base"), m.group("labels")
        field = {"comm.agg_heartbeat_age_s": "hb_age",
                 "comm.agg_slice_devices": "slice",
                 "comm.agg_partials_folded_total": "partials"}.get(base)
        if field is None:
            continue
        agg = dict(item.partition("=")[::2] for item in labels.split(","))
        agg_id = agg.get("agg")
        if agg_id is None:
            continue
        agg_rows.setdefault(agg_id, {})[field] = float(v)
    failovers = val("comm.agg_failovers_total")
    expired = val("comm.agg_heartbeat_expired_total")
    if agg_rows or failovers or expired:
        lines.append("")
        lines.append("aggregator tier")
        for agg_id in sorted(agg_rows):
            row = agg_rows[agg_id]
            lines.append(
                f"  agg {agg_id:<4} hb age {row.get('hb_age', 0.0):>7.2f}s"
                f"   slice {row.get('slice', 0.0):>4.0f}"
                f"   partials {row.get('partials', 0.0):>6.0f}")
        lines.append(f"  failovers        {failovers:>12.0f}")
        lines.append(f"  heartbeats expired{expired:>11.0f}")
    # Async plane (the staleness observatory): shown only when the
    # buffered-async coordinator — or fleetsim's async mode — exported
    # something; flat sync snapshots keep the classic layout.
    async_aggs = (val("async.aggregations_total")
                  or val("fleetsim.async_aggregations_total"))
    stale = (snapshot.get("async.staleness")
             or snapshot.get("fleetsim.async_staleness"))
    if not (isinstance(stale, dict) and stale.get("count")):
        stale = None
    if async_aggs or stale:
        lines.append("")
        lines.append("async plane")
        aps = (rate("async.aggregations_total")
               or rate("fleetsim.async_aggregations_total"))
        lines.append(f"  aggregations     {async_aggs:>12.0f}"
                     + (f"   ({aps:.3f}/s)" if aps is not None else ""))
        buf_k = (val("async.buffer_target")
                 or val("fleetsim.async_buffer_size"))
        if buf_k:
            lines.append(f"  buffer K         {buf_k:>12.0f}")
        arr_s = val("async.arrival_rate_per_s")
        if arr_s:
            lines.append(f"  arrival rate     {arr_s:>12.3f}/s")
        arr_min = val("fleetsim.async_arrival_rate_per_min")
        if arr_min:
            lines.append(f"  arrival rate     {arr_min:>12.3f}/min")
        discards = (val("async.updates_discarded_stale")
                    or val("fleetsim.async_updates_discarded_total"))
        lines.append(f"  stale discards   {discards:>12.0f}")
        if stale:
            lines.append(
                f"  staleness        p50 {stale.get('p50', 0.0):.1f}   "
                f"p90 {stale.get('p90', 0.0):.1f}   "
                f"p99 {stale.get('p99', 0.0):.1f}")
        mass_f = (val("async.contribution_mass{outcome=folded}")
                  or val("fleetsim.async_contribution_mass"
                         "{outcome=folded}"))
        mass_d = (val("async.contribution_mass{outcome=discarded}")
                  or val("fleetsim.async_contribution_mass"
                         "{outcome=discarded}"))
        if mass_f or mass_d:
            lines.append(f"  mass folded      {mass_f:>12.2f}"
                         f"   discarded {mass_d:.2f}")
        pump_rows = [
            f"{st} {val(f'async.pumps{{state={st}}}'):.0f}"
            for st in ("wait", "train", "retry", "pruned", "evicted")
            if snapshot.get(f"async.pumps{{state={st}}}") is not None]
        if pump_rows:
            lines.append("  pumps            " + "   ".join(pump_rows))
    # Learning plane (the convergence observatory): shown only when a
    # --learn-observe run exported learn.* gauges; default snapshots
    # keep the classic layout.
    upd_norm = snapshot.get("learn.update_norm")
    if upd_norm is not None and not isinstance(upd_norm, dict):
        lines.append("")
        lines.append("learning")
        lines.append(f"  update norm      {float(upd_norm):>12.6f}")
        ewma = val("learn.update_norm_ewma")
        if ewma:
            lines.append(f"  norm ewma        {ewma:>12.6f}")
        step = val("learn.step_size")
        if step:
            lines.append(f"  step size        {step:>12.6f}")
        cos = snapshot.get("learn.cos_prev")
        if cos is not None and not isinstance(cos, dict):
            lines.append(f"  cos(prev update) {float(cos):>12.4f}")
        skew = snapshot.get("learn.cohort_skew")
        if skew is not None and not isinstance(skew, dict):
            lines.append(f"  cohort skew      {float(skew):>12.4f}")
        trend_rows = [
            f"{t} {val(f'learn.trend_total{{trend={t}}}'):.0f}"
            for t in ("warmup", "progress", "plateau", "oscillation",
                      "divergence")
            if snapshot.get(f"learn.trend_total{{trend={t}}}") is not None]
        if trend_rows:
            lines.append("  trends           " + "   ".join(trend_rows))
    compiles = val("telemetry.compile_total")
    recompiles = val("telemetry.recompile_total")
    if compiles or recompiles:
        lines.append("")
        lines.append(f"xla compiles        {compiles:>12.0f}   "
                     f"recompiles {recompiles:.0f}")
    hbm = snapshot.get("runtime.hbm_bytes_in_use")
    if hbm is not None and not isinstance(hbm, dict):
        limit = snapshot.get("runtime.hbm_bytes_limit") or 0.0
        pct = f" ({100.0 * hbm / limit:.1f}%)" if limit else ""
        lines.append("")
        lines.append(f"hbm in use          {hbm / 2**30:>11.3f}G{pct}")
    return "\n".join(lines)
