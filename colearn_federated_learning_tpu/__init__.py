"""colearn_federated_learning_tpu — a TPU-native federated-learning framework.

A from-scratch rebuild of the capabilities of
``aferaudo/CoLearn_Federated_Learning`` (PySyft/PyTorch/MQTT federated
learning for IoT edge networks) re-designed TPU-first on JAX/XLA:

- every federated round executes on-device: clients are simulated by
  ``jax.vmap`` (single chip) or laid out along a ``jax.sharding.Mesh``
  "clients" axis via ``shard_map`` (multi chip),
- local SGD is a single jit-compiled ``lax.scan`` per client per round,
- FedAvg/FedProx aggregation lowers to ``jax.lax.psum`` over ICI instead of
  host-side tensor copies,
- DP-noise and secure-aggregation masking hooks run on-device,
- the MQTT/websocket control plane of the reference is replaced by
  in-process orchestration (fed/engine.py owns enrollment-equivalent
  client placement; a cross-process TCP control plane lives in ``comm/``
  once that subsystem lands).

NOTE ON PROVENANCE: the read-only reference checkout at /root/reference was
empty during both the survey and build sessions (see SURVEY.md status
banner), so reference parity claims cite SURVEY.md sections and
BASELINE.json keys rather than reference file:line.
"""

__version__ = "0.1.0"

from colearn_federated_learning_tpu.utils import config as config  # noqa: F401
