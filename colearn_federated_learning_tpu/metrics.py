"""Structured metrics: JSONL records + throughput counters + TensorBoard.

The reference logs accuracy-per-round with prints/CSV (SURVEY.md §5
"Metrics/logging").  The rebuild emits structured JSONL — one record per
federated round — computes the BASELINE.json headline counters
(``rounds_per_sec``, ``client_samples_per_sec_per_chip``, ``acc@round``),
and optionally mirrors scalar metrics to TensorBoard event files
(``tensorboard_dir``; lazy import, no-op if the writer is unavailable).
"""

from __future__ import annotations

import json
import os
import time
from typing import IO, Optional


class MetricsLogger:
    """Append-only JSONL round log with throughput summarization.

    Every record gets ``ts`` (wall clock) and the experiment ``name``;
    ``summary()`` folds the stream into the headline throughput numbers.
    """

    def __init__(self, path: Optional[str] = None, name: str = "default",
                 stream: Optional[IO] = None,
                 tensorboard_dir: Optional[str] = None):
        if path is not None and stream is not None:
            raise ValueError(
                "pass either path or stream, not both (a path-opened file "
                "would silently shadow the stream)"
            )
        self.name = name
        self.path = path
        self._fh: Optional[IO] = stream
        self._owns_fh = False
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
            self._owns_fh = True
        self._tb = None
        if tensorboard_dir:
            try:
                from flax.metrics import tensorboard as _tb

                self._tb = _tb.SummaryWriter(tensorboard_dir)
            except Exception:
                self._tb = None
        self.records: list[dict] = []
        self._t_start = time.perf_counter()

    def log(self, record: dict) -> dict:
        rec = dict(record)
        rec.setdefault("name", self.name)
        rec.setdefault("ts", time.time())
        self.records.append(rec)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
        if self._tb is not None and "round" in rec:
            step = int(rec["round"])
            for k, v in rec.items():
                if isinstance(v, (int, float)) and k not in ("round", "ts"):
                    self._tb.scalar(k, v, step)
        return rec

    def summary(self, samples_per_round: float = 0.0, n_chips: int = 1) -> dict:
        rounds = [r for r in self.records if "round" in r]
        elapsed = time.perf_counter() - self._t_start
        out = {
            "name": self.name,
            "rounds": len(rounds),
            "elapsed_s": elapsed,
        }
        timed = [r["round_time_s"] for r in rounds if "round_time_s" in r]
        if timed:
            out["rounds_per_sec"] = len(timed) / sum(timed)
            if samples_per_round:
                out["client_samples_per_sec_per_chip"] = (
                    out["rounds_per_sec"] * samples_per_round / max(n_chips, 1)
                )
        accs = [(r["round"], r["eval_acc"]) for r in rounds if "eval_acc" in r]
        if accs:
            out["final_acc"] = accs[-1][1]
            out["best_acc"] = max(a for _, a in accs)
            out["acc_at_round"] = dict(accs)
        return out

    def flush(self) -> None:
        """Push buffered records to their sinks without closing anything —
        long runs call this to make the JSONL/TensorBoard tail readable
        mid-flight."""
        if self._fh is not None:
            try:
                self._fh.flush()
            except (OSError, ValueError):
                pass                     # sink already closed by its owner
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        """Flush and release OWNED sinks.  An externally-provided stream is
        flushed but NEVER closed — its lifetime belongs to the caller (e.g.
        a test's StringIO, or stdout)."""
        self.flush()
        if self._fh is not None:
            if self._owns_fh:
                self._fh.close()
            self._fh = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
