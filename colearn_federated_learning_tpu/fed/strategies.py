"""Server-side aggregation strategies.

The reference's server step is FedAvg — a host-side weighted mean of client
state_dicts (SURVEY.md §2 "fed_avg(weights, sizes)", §3a).  Here the server
consumes the already-aggregated mean DELTA (computed on-device, possibly via
psum across the mesh) and applies a server optimizer:

- fedavg / fedprox : w ← w + server_lr · Δ̄   (server_lr=1 reproduces the
  classic weighted-parameter-mean exactly; FedProx differs only in the
  client loss, fed/local.py)
- fednova          : same server step, but the engine normalizes each
  client delta by its effective local-step coefficient and rescales the
  mean (Wang et al., "Tackling the Objective Inconsistency Problem" —
  pattern only; fed/engine.py) so heterogeneous step counts, e.g. under
  straggler budgets, stop biasing the objective
- fedadam / fedyogi: adaptive server optimizers (Reddi et al., "Adaptive
  Federated Optimization" — capability superset of the reference)
- scaffold        : control-variate correction (Karimireddy et al.) — the
  server additionally maintains the global variate c, updated by the
  participation-weighted mean of client variate deltas; the per-client
  variates live in the engine (stacked over the client mesh axis)

All states are pytrees; the whole update jits and shards with the params.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.utils import pytrees
from colearn_federated_learning_tpu.utils.config import FedConfig


class ServerState(NamedTuple):
    params: Any
    opt_m: Optional[Any]      # first moment (fedadam/fedyogi) or None
    opt_v: Optional[Any]      # second moment or None
    control: Optional[Any]    # global control variate c (scaffold) or None
    round_idx: jnp.ndarray    # () int32


SCHEDULES = ("constant", "cosine", "warmup_cosine")


def lr_scale_for_round(cfg: FedConfig, round_idx) -> jnp.ndarray:
    """In-graph client-lr factor for ``round_idx`` (traced or plain int).

    The per-step optimizer is built once with ``cfg.lr``; every update it
    emits is scaled by this factor (fed/local.py), which for SGD(+momentum)
    and Adam alike equals running the round at ``lr · scale``.  Schedules:

    - constant: returns ``None`` so the scaling branch compiles away
      entirely (a live ×1.0 operand would cost per-step elementwise work
      XLA cannot fold).
    - cosine: half-cosine from 1 to ``lr_min_fraction`` over the config's
      ``rounds`` horizon.
    - warmup_cosine: linear ramp over ``warmup_rounds`` (round r trains at
      (r+1)/warmup — never 0), then the cosine leg over the remainder.

    Chaos overlay: ``lr_spike_round >= 0`` multiplies the factor by
    ``lr_spike_multiplier`` for exactly that round — the injected fault
    the convergence observatory's divergence sentinel must catch
    (scripts/learn_smoke.py).  The gate is config-static, so default
    graphs are untouched.
    """
    if cfg.lr_schedule not in SCHEDULES:
        raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}; "
                         f"use one of {SCHEDULES}")
    spiked = cfg.lr_spike_round >= 0 and cfg.lr_spike_multiplier != 1.0
    if cfg.lr_schedule == "constant":
        if not spiked:
            return None
        r = jnp.asarray(round_idx, jnp.float32)
        return jnp.where(r == jnp.float32(cfg.lr_spike_round),
                         jnp.float32(cfg.lr_spike_multiplier),
                         jnp.float32(1.0))
    r = jnp.asarray(round_idx, jnp.float32)
    floor = jnp.float32(cfg.lr_min_fraction)
    warm = float(cfg.warmup_rounds if cfg.lr_schedule == "warmup_cosine"
                 else 0)
    horizon = jnp.maximum(jnp.float32(cfg.rounds) - warm, 1.0)
    prog = jnp.clip((r - warm) / horizon, 0.0, 1.0)
    cos = floor + (1.0 - floor) * 0.5 * (1.0 + jnp.cos(jnp.pi * prog))
    if warm > 0:
        cos = jnp.where(r < warm, jnp.minimum((r + 1.0) / warm, 1.0), cos)
    if spiked:
        cos = cos * jnp.where(r == jnp.float32(cfg.lr_spike_round),
                              jnp.float32(cfg.lr_spike_multiplier),
                              jnp.float32(1.0))
    return cos


def init_server_state(params, cfg: FedConfig) -> ServerState:
    adaptive = cfg.strategy in ("fedadam", "fedyogi")
    zeros = pytrees.tree_zeros_like(params)
    return ServerState(
        params=params,
        opt_m=zeros if adaptive else None,
        opt_v=zeros if adaptive else None,
        control=zeros if cfg.strategy == "scaffold" else None,
        round_idx=jnp.zeros((), jnp.int32),
    )


def server_update(
    state: ServerState,
    mean_delta,
    cfg: FedConfig,
    mean_delta_c=None,
    participation: Optional[jnp.ndarray] = None,
) -> ServerState:
    """Apply one server step to the aggregated mean delta.

    ``mean_delta_c`` / ``participation`` (|S|/N) are scaffold-only: the
    global variate moves by ``participation · mean_delta_c``.
    """
    if cfg.strategy in ("fedavg", "fedprox", "scaffold", "fednova"):
        new_params = jax.tree.map(
            lambda w, d: w + cfg.server_lr * d.astype(w.dtype),
            state.params, mean_delta,
        )
        control = state.control
        if cfg.strategy == "scaffold" and mean_delta_c is not None:
            frac = 1.0 if participation is None else participation
            control = jax.tree.map(
                lambda c, dc: c + frac * dc.astype(c.dtype),
                control, mean_delta_c,
            )
        return ServerState(new_params, None, None, control,
                           state.round_idx + 1)

    if cfg.strategy in ("fedadam", "fedyogi"):
        b1, b2, eps = cfg.server_beta1, cfg.server_beta2, cfg.server_eps
        m = jax.tree.map(lambda m_, d: b1 * m_ + (1 - b1) * d, state.opt_m, mean_delta)
        if cfg.strategy == "fedadam":
            v = jax.tree.map(
                lambda v_, d: b2 * v_ + (1 - b2) * jnp.square(d), state.opt_v, mean_delta
            )
        else:  # fedyogi
            v = jax.tree.map(
                lambda v_, d: v_ - (1 - b2) * jnp.square(d) * jnp.sign(v_ - jnp.square(d)),
                state.opt_v, mean_delta,
            )
        new_params = jax.tree.map(
            lambda w, m_, v_: w + (cfg.server_lr * m_ / (jnp.sqrt(v_) + eps)).astype(w.dtype),
            state.params, m, v,
        )
        return ServerState(new_params, m, v, None, state.round_idx + 1)

    raise ValueError(f"unknown strategy {cfg.strategy!r}")
