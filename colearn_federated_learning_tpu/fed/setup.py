"""Config → runtime pieces shared by the in-process engine and the
cross-silo offline path.

The same ExperimentConfig must produce the SAME partition, step budget and
local trainer whether clients are simulated on-device (fed/engine.py) or
run as decoupled silos against model files (fed/offline.py) — otherwise a
silo trains differently from its simulated twin.  Both paths call these
helpers instead of re-deriving the pieces.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from colearn_federated_learning_tpu.data import partition as partition_lib
from colearn_federated_learning_tpu.fed import local as local_lib
from colearn_federated_learning_tpu.utils.config import ExperimentConfig


def local_model_config(model_cfg):
    """Model config as seen by a SINGLE process (no mesh): ring/ulysses
    attention need a shard_map sequence axis, so SP configs fall back to
    the dense core — the param pytree is identical across cores, so
    checkpoints and wire payloads stay compatible (models/attention.py)."""
    import dataclasses

    if model_cfg.attn_impl in ("ring", "ulysses"):
        return dataclasses.replace(model_cfg, attn_impl="dense")
    return model_cfg


def partition_for_config(
    config: ExperimentConfig, labels: np.ndarray
) -> list[np.ndarray]:
    """Per-client index lists for ``config.data``
    (iid | dirichlet | pathological)."""
    c = config.data
    if c.partition == "dirichlet":
        return partition_lib.dirichlet_partition(
            labels, c.num_clients, c.dirichlet_alpha, seed=config.run.seed
        )
    if c.partition == "pathological":
        # McMahan-style sort-and-deal 2-shard split (the literature-anchor
        # protocol, scripts/validate_literature.py).
        return partition_lib.pathological_partition(
            labels, c.num_clients, seed=config.run.seed
        )
    if c.partition != "iid":
        # A typo must not silently train on an IID split — for the
        # literature protocol that would "validate" the non-IID anchor
        # against the wrong partition with plausible-looking numbers.
        raise ValueError(
            f"unknown data.partition {c.partition!r}; "
            "use iid | dirichlet | pathological"
        )
    return partition_lib.iid_partition(
        len(labels), c.num_clients, seed=config.run.seed
    )


def num_steps_for_config(config: ExperimentConfig, capacity: int) -> int:
    """Static per-round local step budget: explicit ``local_steps`` or
    ``local_epochs * ceil(capacity / batch_size)``."""
    c = config.fed
    if c.local_steps > 0:
        return c.local_steps
    steps_per_epoch = max(1, int(np.ceil(capacity / c.batch_size)))
    return c.local_epochs * steps_per_epoch


def local_trainer_for_config(
    config: ExperimentConfig,
    apply_fn: Callable,
    capacity: int,
    grad_sync_axes: tuple[str, ...] = (),
    lora_dense_ok: bool = False,
) -> tuple[Callable, int]:
    """(local_update fn, num_steps) for one client round under ``config``.

    ``grad_sync_axes``: sequence-parallel mesh axes (fed/local.py).
    ``lora_dense_ok``: fleetsim prices LoRA factor frames but keeps its
    vmapped training dynamics dense by design (fleetsim/sim.py) — only
    it may build this dense trainer under ``lora_rank > 0``."""
    c = config.fed
    if c.lora_rank < 0:
        raise ValueError(f"lora_rank must be >= 0, got {c.lora_rank}")
    if c.lora_rank > 0 and not lora_dense_ok:
        # Adapter federation lives on the socket plane (comm/worker.py ->
        # lora_trainer_for_config); an in-process consumer reaching the
        # dense trainer with lora on would silently train the full model.
        raise ValueError(
            "lora_rank > 0 requires the socket federation plane "
            "(coordinate/worker); this in-process trainer would ignore "
            "the adapters and train dense"
        )
    if c.strategy == "scaffold" and c.local_optimizer != "sgd":
        raise ValueError(
            "scaffold's option-II variate refresh assumes plain SGD steps; "
            f"local_optimizer={c.local_optimizer!r} is unsupported"
        )
    if c.strategy == "fednova" and c.local_optimizer != "sgd":
        raise ValueError(
            "fednova's step coefficient a_i models SGD(+momentum) "
            f"dynamics; local_optimizer={c.local_optimizer!r} does not "
            "follow that geometric series and would be mis-normalized"
        )
    if c.strategy == "scaffold" and c.momentum != 0.0:
        # Option-II refresh c_i' = c_i - c + (w_g - w_l)/(K*lr) equals the
        # mean corrected gradient ONLY under vanilla SGD; momentum silently
        # biases the variates (and the default config carries momentum=0.9).
        raise ValueError(
            "scaffold requires momentum=0.0: the option-II control-variate "
            f"refresh is biased under momentum (got momentum={c.momentum})"
        )
    num_steps = num_steps_for_config(config, capacity)
    optimizer = local_lib.make_optimizer(c.lr, c.momentum, c.local_optimizer)
    is_moe = config.model.name.startswith("moe")
    update_fn = local_lib.make_local_update(
        apply_fn,
        optimizer,
        num_steps=num_steps,
        batch_size=c.batch_size,
        prox_mu=c.prox_mu if c.strategy == "fedprox" else 0.0,
        min_steps_fraction=c.straggler_min_fraction,
        grad_sync_axes=grad_sync_axes,
        scaffold=c.strategy == "scaffold",
        lr=c.lr,
        aux_loss_weight=config.model.moe_aux_weight if is_moe else 0.0,
    )
    return update_fn, num_steps


def lora_trainer_for_config(
    config: ExperimentConfig,
    apply_fn: Callable,
    capacity: int,
) -> tuple[Callable, int]:
    """(lora_update fn, num_steps) — factor-only twin of
    :func:`local_trainer_for_config`, built when ``fed.lora_rank > 0``.
    The strategy restriction (fedavg/fedprox only) is enforced by
    ``validate_robustness``; the trainer mirrors the dense step budget
    and optimizer so a lora run and its dense twin walk the same
    schedule."""
    c = config.fed
    num_steps = num_steps_for_config(config, capacity)
    optimizer = local_lib.make_optimizer(c.lr, c.momentum, c.local_optimizer)
    is_moe = config.model.name.startswith("moe")
    update_fn = local_lib.make_lora_local_update(
        apply_fn,
        optimizer,
        num_steps=num_steps,
        batch_size=c.batch_size,
        rank=c.lora_rank,
        alpha=c.lora_alpha,
        prox_mu=c.prox_mu if c.strategy == "fedprox" else 0.0,
        min_steps_fraction=c.straggler_min_fraction,
        aux_loss_weight=config.model.moe_aux_weight if is_moe else 0.0,
    )
    return update_fn, num_steps


# Tag folded into the experiment key for the A-factor init stream —
# disjoint from every prng.py tag so factor randomness never collides
# with data/local/dp key derivations.
_LORA_INIT_TAG = 0x10AA


def init_lora_factors(config: ExperimentConfig, params: Any) -> Any:
    """Seed-deterministic factor tree for ``params`` under ``config`` —
    the ONE derivation shared by coordinator, workers and tests, so every
    participant reconstructs the identical A basis from the config alone
    (B is zero everywhere; round 0 is bit-for-bit the base model)."""
    import jax

    from colearn_federated_learning_tpu.fed import lora
    from colearn_federated_learning_tpu.utils import prng

    key = jax.random.fold_in(
        prng.experiment_key(config.run.seed), _LORA_INIT_TAG)
    return lora.init_factors(
        params, config.fed.lora_rank, key=key,
        model_name=config.model.name)


def require_stateless_strategy(config: ExperimentConfig, where: str) -> None:
    """File/socket participants keep no cross-round client state, so the
    stateful SCAFFOLD strategy only runs in the on-device engine; FedNova
    is engine-only too — the wire/file folding is a plain weighted mean,
    which is exactly the step-count inconsistency FedNova corrects."""
    if config.fed.strategy == "scaffold":
        raise NotImplementedError(
            f"{where} does not support 'scaffold' (per-client control "
            "variates are engine-resident); use the on-device simulation "
            "or a stateless strategy"
        )
    if config.fed.strategy == "fednova":
        raise NotImplementedError(
            f"{where} does not support 'fednova' (its normalized "
            "aggregation is engine-resident); use the on-device "
            "simulation or fedavg/fedprox"
        )


def require_mean_aggregator(config: ExperimentConfig, where: str) -> None:
    """The file/socket aggregation planes fold updates incrementally
    (comm/aggregation.py, fed/offline.py) — coordinate-wise order
    statistics need ALL updates at once, so robust aggregators are
    engine-only.  Silently averaging when the config asks for 'median'
    would defeat the whole point; be loud instead."""
    if config.fed.aggregator != "mean":
        raise NotImplementedError(
            f"{where} does not support aggregator="
            f"{config.fed.aggregator!r} (robust aggregation is "
            "engine-only); use the on-device simulation or aggregator="
            "'mean'"
        )


def init_global_params(config: ExperimentConfig) -> Any:
    """Seed-deterministic global model init (shared by the file-based and
    socket-based federation entrypoints, so every participant derives the
    IDENTICAL starting point from the config alone)."""
    import jax.numpy as jnp

    from colearn_federated_learning_tpu.data import registry as data_registry
    from colearn_federated_learning_tpu.models import registry as model_registry
    from colearn_federated_learning_tpu.utils import prng

    ds = data_registry.get_dataset(config.data.dataset, seed=config.run.seed,
                                   max_train=4 * config.fed.batch_size,
                                   max_test=1)
    model = model_registry.build_model(local_model_config(config.model))
    x = jnp.asarray(ds.x_train[: config.fed.batch_size])
    return model_registry.init_params(
        model, x, prng.init_key(prng.experiment_key(config.run.seed))
    )


def dp_effective_cohort(config: ExperimentConfig) -> int:
    """The cohort size the per-client DP noise is calibrated against
    (``σ·C/√B`` per update so the SUM of B updates carries std ``σ·C``).
    The ONE definition shared by the noise hook (finalize_client_delta)
    and every accountant that must match it (sync + async coordinators) —
    divergence would silently mis-report ε."""
    return max(config.fed.cohort_size or config.data.num_clients, 1)


def finalize_client_delta(
    config: ExperimentConfig, result, client_id: int, round_idx: int
) -> tuple[Any, float]:
    """Apply the config's on-update privacy hooks to one client's
    ``LocalResult`` and return ``(delta, aggregation_weight)`` — identical
    across the on-device engine's conventions: DP clipping+noise switches
    FedAvg to uniform weighting."""
    from colearn_federated_learning_tpu.privacy import dp as dp_lib
    from colearn_federated_learning_tpu.utils import prng

    delta = result.delta
    weight = float(result.num_examples)
    c = config.fed
    if c.dp_adaptive_clip:
        raise NotImplementedError(
            "dp_adaptive_clip is engine-only: the clip norm is cross-round "
            "server state the stateless file/socket participants don't "
            "carry; use the on-device simulation or a fixed dp_clip"
        )
    if c.dp_clip > 0.0:
        key = prng.experiment_key(config.run.seed)
        delta = dp_lib.clip_and_noise(
            delta, c.dp_clip, c.dp_noise_multiplier,
            dp_effective_cohort(config),
            prng.dp_key(key, client_id, round_idx),
        )
        weight = 1.0
    return delta, weight
