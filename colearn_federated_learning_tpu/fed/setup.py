"""Config → runtime pieces shared by the in-process engine and the
cross-silo offline path.

The same ExperimentConfig must produce the SAME partition, step budget and
local trainer whether clients are simulated on-device (fed/engine.py) or
run as decoupled silos against model files (fed/offline.py) — otherwise a
silo trains differently from its simulated twin.  Both paths call these
helpers instead of re-deriving the pieces.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from colearn_federated_learning_tpu.data import partition as partition_lib
from colearn_federated_learning_tpu.fed import local as local_lib
from colearn_federated_learning_tpu.utils.config import ExperimentConfig


def partition_for_config(
    config: ExperimentConfig, labels: np.ndarray
) -> list[np.ndarray]:
    """Per-client index lists for ``config.data`` (iid | dirichlet)."""
    c = config.data
    if c.partition == "dirichlet":
        return partition_lib.dirichlet_partition(
            labels, c.num_clients, c.dirichlet_alpha, seed=config.run.seed
        )
    return partition_lib.iid_partition(
        len(labels), c.num_clients, seed=config.run.seed
    )


def num_steps_for_config(config: ExperimentConfig, capacity: int) -> int:
    """Static per-round local step budget: explicit ``local_steps`` or
    ``local_epochs * ceil(capacity / batch_size)``."""
    c = config.fed
    if c.local_steps > 0:
        return c.local_steps
    steps_per_epoch = max(1, int(np.ceil(capacity / c.batch_size)))
    return c.local_epochs * steps_per_epoch


def local_trainer_for_config(
    config: ExperimentConfig,
    apply_fn: Callable,
    capacity: int,
    grad_sync_axes: tuple[str, ...] = (),
) -> tuple[Callable, int]:
    """(local_update fn, num_steps) for one client round under ``config``.

    ``grad_sync_axes``: sequence-parallel mesh axes (fed/local.py)."""
    c = config.fed
    num_steps = num_steps_for_config(config, capacity)
    optimizer = local_lib.make_optimizer(c.lr, c.momentum)
    update_fn = local_lib.make_local_update(
        apply_fn,
        optimizer,
        num_steps=num_steps,
        batch_size=c.batch_size,
        prox_mu=c.prox_mu if c.strategy == "fedprox" else 0.0,
        min_steps_fraction=c.straggler_min_fraction,
        grad_sync_axes=grad_sync_axes,
    )
    return update_fn, num_steps
