"""Update compression for the cross-process planes.

The reference ships full-precision state_dicts over websockets; at the
edge, update size is the round bottleneck.  The rebuild compresses
DELTAS (not params — deltas are small-range and quantize well) on both
wire directions: ``FedConfig.compress`` is the UPLINK codec (worker
replies, comm/worker.py, and offline update files), ``compress_down``
the DOWNLINK codec (coordinator broadcast, comm/downlink.py).  Both ends
carry error feedback: the downlink encoder tracks a reconstruction base
(PR 4), and the uplink worker carries the compression residual across
rounds via :func:`feedback_compress` (``FedConfig.compress_feedback``).

- ``int8``: per-leaf symmetric linear quantization — float32 payloads
  shrink ~4x, each leaf replaced by ``{"q": int8[...], "s": scale}``.
  Quantization error per round is O(scale/127); FedAvg's averaging
  further shrinks it by the cohort size.
- ``topk``: per-leaf magnitude sparsification — only the largest
  ``topk_fraction`` of entries survive, shipped as ``{"i": int32 indices,
  "v": float32 values, "n": size}`` (8 bytes/kept entry → ~10x at the
  default 5% density).  The standard sparsification baseline (Aji &
  Heafield 2017 pattern, PAPERS.md — pattern only); biased on its own,
  but uplink error feedback re-injects what sparsification dropped, so
  density becomes a bytes/latency knob instead of a bias cap.  Topk
  frames are also the SPARSE-NATIVE fold format: the coordinator's
  StreamingFolder stages ``(indices, values)`` via
  :func:`topk_leaf_arrays` and scatter-adds at finalize — O(k) host work
  per contribution, never densifying on the hot path
  (comm/aggregation.py).
- ``topk8``: the quantized-sparse hybrid — a topk frame whose values are
  int8 with a per-leaf dequant scale (``{"i", "v": int8[k], "n", "s"}``,
  5 bytes/kept entry vs topk's 8).  Decoded inside
  :func:`topk_leaf_arrays`, so it rides the same sparse-native O(k)
  StreamingFolder fold; error feedback composes and re-injects the
  quantization error along with the sparsification drop.
- ``none``: passthrough.

The on-device engine never compresses — its aggregation is a psum, no
serialization involved.
"""

from __future__ import annotations

from typing import Any

import numpy as np

SCHEMES = ("none", "int8", "topk", "topk8")
_Q, _S = "q", "s"
_I, _V, _N = "i", "v", "n"
TOPK_FRACTION = 0.05
TOPK_SCHEMES = ("topk", "topk8")


def _is_qleaf(node: Any) -> bool:
    return isinstance(node, dict) and set(node) == {_Q, _S}


def _is_kleaf(node: Any) -> bool:
    return isinstance(node, dict) and set(node) == {_I, _V, _N}


def _is_k8leaf(node: Any) -> bool:
    # topk8 hybrid frame: topk indices with int8-quantized values and
    # the per-leaf dequant scale riding along.
    return isinstance(node, dict) and set(node) == {_I, _V, _N, _S}


def topk_leaf_arrays(node: Any) -> tuple[np.ndarray, np.ndarray, int]:
    """Split one topk/topk8 wire leaf into ``(indices, float32 values,
    size)``.

    The sparse-native consumers' accessor: comm/aggregation.py stages
    these without ever materializing the dense leaf.  ``size`` is the
    flat element count of the original leaf.  A topk8 leaf is DECODED
    here — int8 values times the per-leaf scale — so the sparse fold
    stage is the one decode site for both frame flavors."""
    if _is_k8leaf(node):
        n = int(np.asarray(node[_N]).ravel()[0])
        vals = (np.asarray(node[_V], np.float32)
                * np.float32(np.asarray(node[_S]).ravel()[0]))
        return np.asarray(node[_I]), vals, n
    if not _is_kleaf(node):
        raise TypeError(f"unexpected node {type(node).__name__} in topk tree")
    # _N may arrive off the wire as a 1-element array (see decompress).
    n = int(np.asarray(node[_N]).ravel()[0])
    return np.asarray(node[_I]), np.asarray(node[_V], np.float32), n


def topk_leaf_raw(node: Any) -> tuple[np.ndarray, np.ndarray, np.float32, int]:
    """Split one topk/topk8 wire leaf into ``(indices, RAW values, scale,
    size)`` — the device-fold accessor (ops/fold_kernel.py): a topk8 leaf
    keeps its int8 values UNdecoded so the dequant multiply happens inside
    the fused fold kernel, in the same ``(value * scale) * weight`` order
    :func:`topk_leaf_arrays` + the host stage would compute.  A plain topk
    leaf returns its float32 values with ``scale = 1.0`` (an exact
    identity multiply for every finite float32)."""
    if _is_k8leaf(node):
        n = int(np.asarray(node[_N]).ravel()[0])
        return (np.asarray(node[_I]), np.asarray(node[_V], np.int8),
                np.float32(np.asarray(node[_S]).ravel()[0]), n)
    if not _is_kleaf(node):
        raise TypeError(f"unexpected node {type(node).__name__} in topk tree")
    n = int(np.asarray(node[_N]).ravel()[0])
    return (np.asarray(node[_I]), np.asarray(node[_V], np.float32),
            np.float32(1.0), n)


def compress_delta(
    delta: Any, scheme: str, *, topk_fraction: float | None = None
) -> tuple[Any, dict]:
    """Returns (wire_tree, meta_fields) — a nested dict the CLW1/npz
    codecs serialize directly.

    ``topk_fraction`` overrides the default keep density for the topk
    scheme (``FedConfig.topk_fraction`` threads through here); ignored
    by the other schemes."""
    import jax

    if scheme == "none":
        return delta, {"compress": "none"}
    if scheme == "int8":
        def q(leaf):
            arr = np.asarray(leaf, dtype=np.float32)
            scale = float(np.max(np.abs(arr))) / 127.0 if arr.size else 0.0
            if scale == 0.0:
                qa = np.zeros(arr.shape, np.int8)
            else:
                qa = np.clip(np.rint(arr / scale), -127, 127).astype(np.int8)
            return {_Q: qa, _S: np.float32(scale)}

        return jax.tree.map(q, delta), {"compress": "int8"}
    if scheme in TOPK_SCHEMES:
        from colearn_federated_learning_tpu import native

        frac = TOPK_FRACTION if topk_fraction is None else float(topk_fraction)
        quantize = scheme == "topk8"

        def k_of(leaf):
            flat = np.asarray(leaf, np.float32).ravel()
            # Keep at least one entry so tiny biases/scalars survive.
            k = max(1, int(np.ceil(flat.size * frac)))
            # Thread-parallel selection when the C++ library is present
            # (native/src/topk.cpp); numpy argpartition otherwise.
            idx, val = native.topk_abs(flat, k)
            if not quantize:
                return {_I: idx, _V: val, _N: np.int64(flat.size)}
            # Hybrid frame: int8 values inside the topk frame — 5
            # bytes/kept entry instead of 8.  Survivors are the
            # LARGEST-magnitude entries, so the symmetric scale wastes
            # no range on near-zeros the selector already dropped.
            scale = float(np.max(np.abs(val))) / 127.0 if val.size else 0.0
            if scale == 0.0:
                q = np.zeros(val.shape, np.int8)
            else:
                q = np.clip(np.rint(val / scale), -127, 127).astype(np.int8)
            return {_I: idx, _V: q, _N: np.int64(flat.size),
                    _S: np.float32(scale)}

        return jax.tree.map(k_of, delta), {"compress": scheme}
    raise ValueError(f"unknown compression {scheme!r} (use {SCHEMES})")


def decompress_delta(wire_tree: Any, meta: dict, shapes: Any = None) -> Any:
    """Inverse of :func:`compress_delta`; rebuilds the float delta.

    ``shapes``: matching pytree of ARRAYS (e.g. the global params) —
    required to un-flatten ``topk`` leaves back to their original shapes;
    int8 leaves carry their shape themselves.
    """
    scheme = meta.get("compress", "none")
    if scheme == "none":
        return wire_tree
    if scheme == "int8":
        def walk(node):
            if _is_qleaf(node):
                return np.asarray(node[_Q], np.float32) * np.float32(node[_S])
            if isinstance(node, dict):
                return {k: walk(v) for k, v in node.items()}
            raise TypeError(
                f"unexpected node {type(node).__name__} in int8 tree"
            )

        return walk(wire_tree)
    if scheme in TOPK_SCHEMES:
        import jax

        if shapes is None:
            raise ValueError("topk decompression needs the `shapes` pytree")

        def unk(node, ref):
            # topk_leaf_arrays decodes both frame flavors (topk8 values
            # dequantize through the per-leaf scale).
            idx, vals, n = topk_leaf_arrays(node)
            flat = np.zeros(n, np.float32)
            flat[idx] = vals
            return flat.reshape(np.asarray(ref).shape)

        # Walk the REFERENCE tree's structure and stop at ITS leaf
        # positions (flatten_up_to), so the kleaf dicts — and any container
        # types compress_delta's tree.map recursed through — round-trip.
        treedef = jax.tree.structure(shapes)
        refs = jax.tree.leaves(shapes)
        nodes = treedef.flatten_up_to(wire_tree)
        return jax.tree.unflatten(
            treedef, [unk(n, r) for n, r in zip(nodes, refs)]
        )
    raise ValueError(f"unknown compression {scheme!r}")


def feedback_compress(
    delta: Any,
    residual: Any,
    scheme: str,
    *,
    topk_fraction: float | None = None,
) -> tuple[Any, dict, Any]:
    """Error-feedback compression (EF-SGD pattern): fold the carried
    ``residual`` into ``delta``, compress the compensated tree, and
    return what the codec dropped as the next round's residual.

    Returns ``(wire_tree, meta_fields, new_residual)``.  The residual is
    a host-numpy float32 pytree (``None`` for a lossless scheme, and
    accepted as ``None`` on the first round / after a resync reset).
    The caller carries it across rounds; symmetric to the downlink
    encoder's reconstruction-base feedback (comm/downlink.py)."""
    import jax

    delta = jax.tree.map(lambda l: np.asarray(l, np.float32), delta)
    if residual is not None:
        delta = jax.tree.map(np.add, delta, residual)
    wire, meta = compress_delta(delta, scheme, topk_fraction=topk_fraction)
    if scheme == "none":
        return wire, meta, None
    recon = decompress_delta(wire, meta, shapes=delta)
    new_residual = jax.tree.map(np.subtract, delta, recon)
    return wire, meta, new_residual
