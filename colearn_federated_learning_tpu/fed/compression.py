"""Update compression for the cross-process planes.

The reference ships full-precision state_dicts over websockets; at the
edge, update size is the round bottleneck.  The rebuild compresses client
DELTAS (not params — deltas are small-range and quantize well):

- ``int8``: per-leaf symmetric linear quantization — float32 payloads
  shrink ~4x, each leaf replaced by ``{"q": int8[...], "s": scale}``.
  Quantization error per round is O(scale/127); FedAvg's averaging
  further shrinks it by the cohort size.
- ``none``: passthrough.

Only the WIRE/FILE planes compress (comm/worker.py replies, offline update
files).  The on-device engine never needs to — its aggregation is a psum,
no serialization involved.  Config: ``FedConfig.compress``.
"""

from __future__ import annotations

from typing import Any

import numpy as np

SCHEMES = ("none", "int8")
_Q, _S = "q", "s"


def _is_qleaf(node: Any) -> bool:
    return isinstance(node, dict) and set(node) == {_Q, _S}


def compress_delta(delta: Any, scheme: str) -> tuple[Any, dict]:
    """Returns (wire_tree, meta_fields) — a nested dict the CLW1/npz
    codecs serialize directly."""
    if scheme == "none":
        return delta, {"compress": "none"}
    if scheme != "int8":
        raise ValueError(f"unknown compression {scheme!r} (use {SCHEMES})")

    def q(leaf):
        arr = np.asarray(leaf, dtype=np.float32)
        scale = float(np.max(np.abs(arr))) / 127.0 if arr.size else 0.0
        if scale == 0.0:
            qa = np.zeros(arr.shape, np.int8)
        else:
            qa = np.clip(np.rint(arr / scale), -127, 127).astype(np.int8)
        return {_Q: qa, _S: np.float32(scale)}

    import jax

    return jax.tree.map(q, delta), {"compress": "int8"}


def decompress_delta(wire_tree: Any, meta: dict) -> Any:
    """Inverse of :func:`compress_delta`; rebuilds the float delta."""
    scheme = meta.get("compress", "none")
    if scheme == "none":
        return wire_tree
    if scheme != "int8":
        raise ValueError(f"unknown compression {scheme!r}")

    def walk(node):
        if _is_qleaf(node):
            return np.asarray(node[_Q], np.float32) * np.float32(node[_S])
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        raise TypeError(f"unexpected node {type(node).__name__} in int8 tree")

    return walk(wire_tree)
