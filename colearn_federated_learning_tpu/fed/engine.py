"""Federated round orchestration, fully on-device.

This replaces the reference's coordinator process (SURVEY.md §3a: MQTT
enrollment → websocket broadcast → per-worker PyTorch epochs → host-side
``fed_avg``) with a single jit-compiled round function:

- single chip: clients are a ``vmap`` axis,
- multi chip:  clients are a ``shard_map`` axis over a ``jax.sharding.Mesh``
  and the weighted average lowers to ``jax.lax.psum`` over ICI
  (BASELINE.json ``north_star``).

One call = one federated round: cohort sampling → broadcast (implicit: the
global params are an operand) → local SGD per client → privacy hooks →
weighted aggregation → server update.  Shapes are static across rounds, so
the program compiles once.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.data.sharding import (
    ClientShards,
    pack_client_shards,
    pad_clients_to_multiple,
)
from colearn_federated_learning_tpu.fed import programs
from colearn_federated_learning_tpu.fed.programs import rank_cohort
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.fed.evaluation import (
    detection_report,
    make_confusion_eval_fn,
    make_eval_fn,
)
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.privacy import dp as dp_lib
from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.utils import prng
from colearn_federated_learning_tpu.utils import config as config_lib
from colearn_federated_learning_tpu.utils.config import ExperimentConfig


def _resolve_devices(backend: str) -> list:
    """Device list for --backend=auto|cpu|tpu (auto prefers accelerators).

    ``auto`` degrades to the CPU backend when the default backend fails to
    initialize (a flaky TPU plugin must not kill a CPU-capable run);
    ``tpu`` stays strict and surfaces the error."""
    if backend == "auto":
        try:
            return jax.devices()
        except Exception:
            return jax.devices("cpu")
    devices = jax.devices()
    if backend == "cpu":
        devices = [d for d in devices if d.platform == "cpu"] or jax.devices("cpu")
    elif backend == "tpu":
        tpu = [d for d in devices if d.platform not in ("cpu",)]
        if not tpu:
            raise RuntimeError("--backend=tpu requested but no accelerator present")
        devices = tpu
    else:
        raise ValueError(f"unknown backend {backend!r} (use auto|cpu|tpu)")
    return devices


class FederatedLearner:
    """End-to-end federated experiment: data, model, round loop, eval.

    ``mesh``: optional ``jax.sharding.Mesh``.  The ``config.run.mesh_axis``
    (clients) axis is required; a ``seq`` axis adds ring-attention sequence
    parallelism, and a ``model`` axis adds GSPMD tensor/expert parallelism
    (parallel/tp.py) — any combination up to the 3-D
    (clients, seq, model) mesh.  Client state shards over the client axis
    and aggregation runs as psum over it.  When None, everything runs on
    one device via vmap.
    """

    @classmethod
    def from_config(
        cls,
        config: ExperimentConfig,
        dataset: Optional[data_registry.Dataset] = None,
    ) -> "FederatedLearner":
        """Build a learner honoring ``config.run.backend`` (the CLI's
        ``--backend=tpu|cpu|auto``, BASELINE.json ``north_star``): resolve
        devices and lay clients over a 1-D mesh — or, with
        ``attn_impl="ring"``, a 2-D (clients, seq) mesh, or, with
        ``run.tp_size > 1``, a 2-D (clients, model) tensor-parallel
        mesh."""
        from colearn_federated_learning_tpu.parallel.mesh import make_mesh

        devices = _resolve_devices(config.run.backend)
        r = config.run
        if config.model.attn_impl in ("ring", "ulysses") and r.tp_size > 1:
            raise ValueError(
                "from_config cannot auto-lay a 3-D (clients, seq, model) "
                "mesh; build it with parallel.mesh.make_mesh and pass "
                "mesh= explicitly"
            )
        mesh = None
        if r.tp_size > 1 and len(devices) % r.tp_size != 0:
            # Non-divisible device counts would otherwise surface as an
            # opaque reshape error inside make_mesh((-1, tp_size)).  The
            # degradation is observable: a warning for interactive runs
            # AND a labeled counter for dashboards/soaks — a fleet that
            # silently runs replicated at tp_size=1 is a perf SLO bug.
            import warnings

            telemetry.get_registry().counter(
                "fed.mesh_fallback_total",
                labels={"reason": "indivisible_devices"}).inc()
            warnings.warn(
                f"tp_size={r.tp_size} needs a device count that is a "
                f"multiple of it, have {len(devices)}; running without "
                f"tensor parallelism",
                stacklevel=2,
            )
        if len(devices) > 1:
            if config.model.attn_impl in ("ring", "ulysses"):
                mesh = make_mesh((r.mesh_axis, r.seq_axis), devices=devices)
            elif r.tp_size > 1 and len(devices) % r.tp_size == 0:
                mesh = make_mesh((r.mesh_axis, r.tp_axis), (-1, r.tp_size),
                                 devices=devices)
            else:
                mesh = Mesh(np.array(devices), (r.mesh_axis,))
        return cls(config, dataset=dataset, mesh=mesh)

    def __init__(
        self,
        config: ExperimentConfig,
        dataset: Optional[data_registry.Dataset] = None,
        mesh: Optional[Mesh] = None,
        partitions: Optional[list] = None,
    ):
        """``partitions``: optional explicit per-client index lists into the
        dataset's train split, overriding ``config.data.partition`` —
        callers that already know exactly who owns which rows (clustered
        FL preserving member shards) inject them here."""
        self.config = config
        self.mesh = mesh
        c = config
        config_lib.validate_experiment(c)

        # --- mesh axes ------------------------------------------------
        # 1-D mesh: clients only.  2-D (attn_impl="ring"): + an inner ``seq``
        # axis (sequence parallelism; parallel/ring.py).  A ``model`` axis
        # (parallel/tp.py) adds tensor/expert parallelism: it is left to the
        # AUTOMATIC partitioner (shard_map axis_names excludes it), params
        # are sharded over it by the TP rules, and XLA inserts the TP
        # collectives inside each client's local step.
        self.client_axis = c.run.mesh_axis
        self.seq_axis = c.run.seq_axis
        self.tp_axis = c.run.tp_axis
        if mesh is not None:
            if self.client_axis not in mesh.shape:
                raise ValueError(
                    f"mesh axes {tuple(mesh.shape)} lack the client axis "
                    f"{self.client_axis!r}"
                )
            self.clients_size = mesh.shape[self.client_axis]
            self.seq_size = mesh.shape.get(self.seq_axis, 1)
            self.tp_size = mesh.shape.get(self.tp_axis, 1)
            extra = set(mesh.shape) - {
                self.client_axis, self.seq_axis, self.tp_axis
            }
            if extra:
                raise ValueError(f"unsupported mesh axes {sorted(extra)}")
        else:
            self.clients_size = 1
            self.seq_size = 1
            self.tp_size = 1
        self.sp = self.seq_size > 1
        if self.sp and c.model.attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"a {self.seq_size}-way {self.seq_axis!r} mesh axis requires "
                "model.attn_impl='ring' or 'ulysses'"
            )
        if (c.model.attn_impl in ("ring", "ulysses") and mesh is not None
                and not self.sp):
            raise ValueError(
                f"attn_impl={c.model.attn_impl!r} on a mesh requires a "
                f"{self.seq_axis!r} axis of size > 1"
            )

        # --- data -----------------------------------------------------
        self.dataset = dataset or data_registry.get_dataset(
            c.data.dataset, seed=c.run.seed
        )
        labels = np.asarray(self.dataset.y_train)
        parts = (partitions if partitions is not None
                 else setup_lib.partition_for_config(c, labels))
        shards = pack_client_shards(
            np.asarray(self.dataset.x_train), labels, parts,
            capacity=c.data.max_examples_per_client,
        )
        self.real_num_clients = shards.num_clients   # pre-ghost-padding
        if self.sp:
            seq_len = shards.x.shape[-1]
            if shards.x.ndim != 3:
                raise ValueError(
                    "sequence parallelism needs (tokens,)-shaped examples, "
                    f"got example shape {shards.x.shape[2:]}"
                )
            if seq_len % self.seq_size:
                raise ValueError(
                    f"seq_len {seq_len} is not divisible by the "
                    f"{self.seq_size}-way {self.seq_axis!r} axis"
                )
            if (c.model.attn_impl == "ulysses"
                    and c.model.num_heads % self.seq_size):
                # Fail eagerly like the seq_len check above — the kernel's
                # own guard would only fire deep inside the first trace.
                raise ValueError(
                    f"attn_impl='ulysses' needs num_heads "
                    f"({c.model.num_heads}) divisible by the "
                    f"{self.seq_size}-way {self.seq_axis!r} axis; use "
                    "attn_impl='ring'"
                )
        if mesh is not None:
            shards = pad_clients_to_multiple(shards, self.clients_size)
            # Interleave so real clients spread evenly across devices (ghost
            # padding would otherwise pile onto the last devices and starve
            # their per-device cohorts).  ``client_ids[slot]`` is the
            # ORIGINAL client identity of each array slot; all PRNG is keyed
            # on it, keeping results placement-independent.
            D = self.clients_size
            L = shards.num_clients // D
            order = np.array(
                [j * D + d for d in range(D) for j in range(L)], dtype=np.int32
            )
            shards = ClientShards(
                x=shards.x[order], y=shards.y[order], counts=shards.counts[order]
            )
            self.client_ids = order
        else:
            self.client_ids = np.arange(shards.num_clients, dtype=np.int32)
        self.shards = shards
        self.num_clients = shards.num_clients

        # --- model ----------------------------------------------------
        # Under SP the trained module runs on sequence SHARDS inside
        # shard_map; its dense-attention twin (identical param pytree) is
        # used for init and full-sequence evaluation outside the mesh.
        train_model_cfg = (
            c.model if self.sp else setup_lib.local_model_config(c.model)
        )
        self.model = model_registry.build_model(
            train_model_cfg, seq_axis_name=self.seq_axis if self.sp else None
        )
        if self.sp:
            self.eval_model = model_registry.build_model(
                setup_lib.local_model_config(c.model)
            )
        else:
            self.eval_model = self.model
        example_x = jnp.asarray(shards.x[0, : c.fed.batch_size])
        ikey = prng.init_key(prng.experiment_key(c.run.seed))
        self.params = model_registry.init_params(self.eval_model, example_x, ikey)
        if self.tp_size > 1:
            # Tensor parallelism: shard the wide param dims over the model
            # axis (parallel/tp.py rules); ``init_server_state``'s
            # zeros_like leaves inherit the shardings, so the whole server
            # state lives TP-sharded from the start.
            from colearn_federated_learning_tpu.parallel import tp as tp_lib

            self.params = tp_lib.shard_params(self.params, mesh, self.tp_axis)
        self.server_state = strategies.init_server_state(self.params, c.fed)

        # --- local trainer -------------------------------------------
        self.scaffold = c.fed.strategy == "scaffold"
        self.fednova = c.fed.strategy == "fednova"
        if c.fed.secure_agg and c.fed.secure_agg_neighbors and (
            c.fed.secure_agg_neighbors % 2 or c.fed.secure_agg_neighbors < 2
        ):
            raise ValueError(
                "secure_agg_neighbors must be an even integer >= 2, got "
                f"{c.fed.secure_agg_neighbors}"
            )
        if c.fed.secure_agg and not 0.0 < c.fed.secure_agg_threshold <= 1.0:
            raise ValueError(
                "secure_agg_threshold must be in (0, 1], got "
                f"{c.fed.secure_agg_threshold}"
            )
        if self.scaffold and (c.fed.secure_agg or c.fed.dp_clip > 0.0):
            raise ValueError(
                "scaffold is incompatible with secure_agg/dp hooks: the "
                "control-variate deltas are a second payload the masks and "
                "noise calibration do not cover"
            )
        if self.scaffold and self.tp_size > 1:
            raise ValueError(
                "scaffold with a model (TP) axis is unsupported: the "
                "host-resident variate store is unsharded and the per-round "
                "gather/scatter would funnel TP shards through one host"
            )
        # Byzantine-robust aggregation (fed/robust.py).
        from colearn_federated_learning_tpu.fed.robust import AGGREGATORS

        if c.fed.aggregator not in AGGREGATORS:
            raise ValueError(
                f"unknown aggregator {c.fed.aggregator!r}; use {AGGREGATORS}"
            )
        self.robust = c.fed.aggregator != "mean"
        if self.robust:
            if not 0.0 <= c.fed.trim_fraction < 0.5:
                raise ValueError(
                    "trim_fraction must be in [0, 0.5), got "
                    f"{c.fed.trim_fraction}"
                )
            if c.fed.secure_agg:
                raise ValueError(
                    "robust aggregators need the individual updates; "
                    "secure-agg masks only cancel in a plain sum"
                )
            if self.scaffold:
                raise ValueError(
                    "scaffold assumes mean aggregation of its control "
                    "variates; use aggregator='mean'"
                )
            if c.fed.dp_noise_multiplier > 0.0:
                raise ValueError(
                    "robust aggregation of noised updates is not the "
                    "Gaussian mechanism the RDP accountant models; use "
                    "dp_clip alone (norm bounding) with robust aggregators"
                )
        self.local_update, self.num_steps = setup_lib.local_trainer_for_config(
            c, self.model.apply, shards.capacity,
            grad_sync_axes=(self.seq_axis,) if self.sp else (),
        )
        # SCAFFOLD per-client control variates: one params-shaped pytree per
        # client, stacked on the client axis — resident on HOST (numpy).
        # Each round gathers only the COHORT's variates into the jit round
        # program and scatters the updated block back, so device memory is
        # O(cohort × model), not O(num_clients × model) — the flagship
        # configs (thousands of clients × ViT) never fit the full stack.
        if self.scaffold:
            self.client_c = jax.tree.map(
                lambda w: np.zeros((self.num_clients,) + w.shape, w.dtype),
                self.params,
            )
        else:
            self.client_c = None

        # --- cohort ---------------------------------------------------
        cohort = c.fed.cohort_size or self.num_clients
        self.cohort_size = min(cohort, self.num_clients)
        if mesh is not None:
            d = self.clients_size
            # per-device cohort must be equal and static
            self.cohort_per_device = max(1, self.cohort_size // d)
            adjusted = self.cohort_per_device * d
            if adjusted != self.cohort_size:
                import warnings

                warnings.warn(
                    f"cohort_size={self.cohort_size} is not a multiple of the "
                    f"{d}-way client axis; using {adjusted} "
                    f"({self.cohort_per_device}/device)",
                    stacklevel=2,
                )
            self.cohort_size = adjusted
        if (self.robust and c.fed.aggregator in ("trimmed_mean", "krum")
                and int(c.fed.trim_fraction * self.cohort_size + 1e-4) < 1):
            # floor(trim · cohort) == 0 trims/excludes nothing — the
            # "robust" aggregate would silently be the plain mean while
            # still paying uniform weights and the secure-agg/DP bans.
            what = ("trims zero clients" if c.fed.aggregator == "trimmed_mean"
                    else "assumes zero Byzantine clients (f = 0)")
            if self.cohort_size < 3:
                # Any fraction satisfying floor(trim·cohort) >= 1 here
                # would breach the < 0.5 cap: no valid value exists.
                raise ValueError(
                    f"aggregator={c.fed.aggregator!r} needs a cohort of at "
                    f"least 3 (got {self.cohort_size}); use "
                    "aggregator='median'"
                )
            import math

            # Round the suggestion UP so following it actually passes.
            ok_frac = math.ceil(1e6 / self.cohort_size) / 1e6
            raise ValueError(
                f"trim_fraction={c.fed.trim_fraction} {what} at "
                f"cohort_size={self.cohort_size}; raise it to at least "
                f"{ok_frac:.6f} (or use aggregator='median')"
            )
        # DP noise accounting divides by the number of REAL clients expected
        # to contribute (ghost padding never contributes).  If stragglers
        # drop mid-round the realized central noise is below nominal — a
        # known property of DP-FedAvg with dropouts; see privacy/dp.py.
        self.dp_cohort = min(self.cohort_size, self.real_num_clients)
        # Adaptive clipping (privacy/dp.py, quantile tracking): the clip
        # norm is a DEVICE scalar threaded operand -> metric through the
        # round program, so back-to-back rounds adapt it with no host sync.
        self.adaptive_clip = c.fed.dp_adaptive_clip
        if self.adaptive_clip:
            if c.fed.dp_clip <= 0.0:
                raise ValueError(
                    "dp_adaptive_clip needs dp_clip > 0 as the initial norm"
                )
            z = c.fed.dp_noise_multiplier
            if z > 0.0:
                self.dp_bit_noise = c.fed.dp_bit_noise or max(
                    self.dp_cohort / 20.0, 1.0
                )
                # The bit query spends part of the budget; the update noise
                # is inflated so the JOINT per-round mechanism still costs
                # the configured z — the accountant below stays valid as-is.
                self.dp_z = dp_lib.adaptive_noise_multiplier(
                    z, self.dp_bit_noise
                )
            else:
                self.dp_bit_noise = 0.0
                self.dp_z = 0.0
        self._dp_clip = jnp.float32(c.fed.dp_clip)
        # RDP accountant: cumulative (ε, δ) per round when DP is on
        # (privacy/accountant.py; each round is one subsampled Gaussian
        # mechanism with q = cohort / N at central noise σ).
        from colearn_federated_learning_tpu.privacy.accountant import (
            RdpAccountant,
        )

        self.accountant = RdpAccountant.from_config(
            c.fed, sampling_rate=self.dp_cohort / self.real_num_clients
        )

        # --- compiled programs (construction: fed/programs.py) -------
        # The per-program cohort width: full cohort on the vmap path, the
        # per-device slice on the mesh path (cohort_step sizes its
        # straggler-budget vector off this).
        self.cohort_size_local = (
            self.cohort_size if mesh is None else self.cohort_per_device
        )
        self.base_key = prng.experiment_key(c.run.seed)
        # CompileTracker fingerprints every call's abstract signature: the
        # expected first compile lands in telemetry.compile_total, any
        # LATER new signature is a recompile with an attributed reason
        # (telemetry.recompile_total{fn,reason}) — a coordinator silently
        # recompiling every round becomes a visible counter + round-record
        # field.  Attribute access (.lower, for the perf script's AOT
        # path) passes through to the jitted fn.
        self._round_fn = telemetry.CompileTracker(
            programs.build_round_fn(self), name="engine.round")
        self._eval_fn = self._build_eval_fn()
        self._flops_per_round: Optional[float] = None
        # Recording stays off until fit() opens a trace window (trace_dir);
        # span() still yields timed spans either way, so run_round's phase
        # durations are always available to the metrics JSONL.
        self.tracer = telemetry.Tracer(process="engine", enabled=False)
        self.last_trace_path: Optional[str] = None
        self._device_data = self._place_data()
        self.history: list[dict] = []
        self._ckpt = None

    # ------------------------------------------------------------------
    # data placement
    # ------------------------------------------------------------------
    def _place_data(self):
        with self.tracer.span("h2d_transfer") as sp:
            x = jnp.asarray(self.shards.x)
            y = jnp.asarray(self.shards.y)
            counts = jnp.asarray(self.shards.counts)
            ids = jnp.asarray(self.client_ids)
            if self.mesh is not None:
                ax = self.client_axis
                # Under SP each client's token dim is also sharded (last
                # axis of the (clients, capacity, seq_len) block).
                x_spec = (
                    P(ax, None, self.seq_axis) if self.sp else P(ax)
                )
                x = jax.device_put(x, NamedSharding(self.mesh, x_spec))
                sh = NamedSharding(self.mesh, P(ax))
                y, counts, ids = (
                    jax.device_put(a, sh) for a in (y, counts, ids)
                )
            y, counts, ids = jax.block_until_ready((y, counts, ids))
            x = jax.block_until_ready(x)
        telemetry.get_registry().gauge("engine.h2d_transfer_s").set(
            sp.duration_s
        )
        return (x, y, counts, ids)

    # ------------------------------------------------------------------
    # compiled programs: construction lives in fed/programs.py (round
    # program vmap/mesh builders, per-client eval, personalization,
    # similarity) -- the engine only orchestrates.
    # ------------------------------------------------------------------
    def _build_client_eval_fn(self):
        # Thin delegate kept as a method: clustered FL swaps models in
        # and rebuilds per-cluster programs through it (fed/clustered.py).
        return programs.build_client_eval_fn(self)


    # ------------------------------------------------------------------
    # evaluation (held-out global test set, SURVEY.md §3d)
    # ------------------------------------------------------------------
    def _build_eval_fn(self):
        return make_eval_fn(
            self.eval_model.apply,
            self.dataset.x_test,
            self.dataset.y_test,
            batch=max(self.config.fed.batch_size, 64),
        )

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def _host_sample_cohort(self, round_idx: int):
        """Cohort selection on HOST — same key derivation and ranking as the
        in-program sampler, run eagerly so the scaffold path can gather the
        cohort's variate rows before dispatching the round.

        Returns ``(sel, rows)``: ``sel`` are the per-device-local slot
        indices the round program consumes; ``rows`` the absolute rows of
        the (interleaved) client-stacked arrays, for host gather/scatter.
        """
        r = jnp.asarray(round_idx, jnp.int32)
        counts = jnp.asarray(self.shards.counts)
        if self.mesh is None:
            if self.cohort_size < self.num_clients:
                skey = prng.sampling_key(self.base_key, r)
                sel = np.asarray(
                    rank_cohort(skey, counts, self.cohort_size)
                ).astype(np.int32)
            else:
                sel = np.arange(self.num_clients, dtype=np.int32)
            return sel, sel
        D, cpd = self.clients_size, self.cohort_per_device
        L = self.num_clients // D
        skey = prng.sampling_key(self.base_key, r)
        sels, rows = [], []
        for d in range(D):
            if cpd < L:
                dkey = jax.random.fold_in(skey, d)
                s = np.asarray(
                    rank_cohort(dkey, counts[d * L:(d + 1) * L], cpd)
                ).astype(np.int32)
            else:
                s = np.arange(L, dtype=np.int32)
            sels.append(s)
            rows.append(d * L + s)
        return np.concatenate(sels), np.concatenate(rows)

    def run_round(self, sync: bool = True) -> dict:
        """One federated round.  ``sync=False`` skips the host conversion of
        the round metrics (they stay as device scalars), so back-to-back
        rounds pipeline on the device with no host round-trip between them —
        one device→host sync per round otherwise costs a full RPC round-trip
        on remote-tunnel platforms.  (SCAFFOLD rounds still synchronize
        regardless: the cohort-resident variate gather/scatter is a
        per-round host⇄device exchange by design.)  Call
        :meth:`finalize_history` after a ``sync=False`` loop to materialize
        the floats."""
        r = len(self.history)
        if self.scaffold:
            # Gather the cohort's variates from the host store; scatter the
            # refreshed block back afterwards (device memory stays
            # O(cohort × model)).
            with self.tracer.span("cohort_sample", round=r) as sample_sp:
                sel, rows = self._host_sample_cohort(r)
                c_cohort = jax.tree.map(lambda l: l[rows], self.client_c)
                sel_dev = jnp.asarray(sel)
                if self.mesh is not None:
                    sh = NamedSharding(self.mesh, P(self.client_axis))
                    sel_dev = jax.device_put(sel_dev, sh)
                    c_cohort = jax.tree.map(
                        lambda l: jax.device_put(jnp.asarray(l), sh), c_cohort
                    )
        else:
            # The non-scaffold cohort is sampled INSIDE the jit program, so
            # its cost is part of the fused client_update span.
            sel, rows, sel_dev, c_cohort = None, None, None, None
            sample_sp = None
        # The round program is ONE fused jit call (sample → local SGD →
        # aggregate → server update); phases inside it can't be split
        # without extra device barriers, so it gets a single span — made
        # honest by a barrier only while a trace window is open (blocking
        # every round would serialise the sync=False pipeline).
        with self.tracer.span("client_update", round=r,
                              cohort=self.cohort_size) as update_sp:
            self.server_state, metrics, new_c = self._round_fn(
                self.server_state,
                self.base_key,
                jnp.asarray(r, jnp.int32),
                *self._device_data,
                sel_dev,
                c_cohort,
                self._dp_clip,
            )
            if self.tracer.enabled:
                jax.block_until_ready(self.server_state.params)
        if self.adaptive_clip:
            # Feed the adapted clip into the next round as a device scalar
            # (no host round-trip; sync=False rounds keep pipelining).
            self._dp_clip = metrics["dp_clip"]
        if self.scaffold:
            with self.tracer.span("scatter_variates", round=r):
                updated = jax.tree.map(np.asarray, new_c)

                def scatter(full, upd):
                    full[rows] = upd
                    return full

                self.client_c = jax.tree.map(scatter, self.client_c, updated)
        with self.tracer.span("sync_metrics", round=r) as sync_sp:
            if sync:
                # ONE batched device→host transfer for the whole metrics
                # dict — per-scalar float() would cost one RPC round-trip
                # each on remote-tunnel platforms (65 ms × n_metrics per
                # round).
                out = {k: float(v)
                       for k, v in jax.device_get(metrics).items()}
            else:
                out = dict(metrics)      # device scalars; sync deferred
        out["round"] = r
        out["phase_update_s"] = update_sp.duration_s
        out["phase_sync_s"] = sync_sp.duration_s
        if sample_sp is not None:
            out["phase_cohort_sample_s"] = sample_sp.duration_s
        # Key present only when something went wrong — a healthy run's
        # records stay byte-identical (tested layout contract).
        if self._round_fn.recompiles:
            out["recompiles"] = self._round_fn.recompiles
        telemetry.get_registry().counter("engine.rounds_total").inc()
        if self.accountant is not None:
            self.accountant.step()
            out["dp_epsilon"] = self.accountant.epsilon()
            out["dp_delta"] = self.accountant.delta
        self.history.append(out)
        return out

    def round_cost_analysis(self) -> dict:
        """XLA's own cost analysis of the compiled round program for the
        CURRENT operand shapes (AOT lower+compile, cached per signature
        by the tracker).  ``flops_per_round`` applies the local-SGD trip
        count: XLA counts a while/scan BODY ONCE (trip counts are not
        modeled) and the local-SGD scan holds essentially all the FLOPs —
        the reported count is identical for local_steps=1 and
        local_steps=8 — so the per-round figure scales by num_steps."""
        if self.scaffold:
            sel, rows = self._host_sample_cohort(0)
            c_cohort = jax.tree.map(lambda l: l[rows], self.client_c)
            sel_dev = jnp.asarray(sel)
        else:
            sel_dev, c_cohort = None, None
        cost = self._round_fn.cost_analysis(
            self.server_state, self.base_key, jnp.asarray(0, jnp.int32),
            *self._device_data, sel_dev, c_cohort, self._dp_clip,
        )
        if cost.get("flops"):
            cost["flops_per_round"] = cost["flops"] * self.num_steps
        return cost

    def finalize_history(self) -> list[dict]:
        """Materialize any deferred (``sync=False``) round metrics to floats
        — blocks until the device work that produced them is done.  The
        whole history is fetched in ONE batched transfer (sequential
        per-scalar reads would pay a full RPC round-trip each on
        remote-tunnel platforms)."""
        fetched = jax.device_get(self.history)
        self.history = [
            {k: (float(v) if hasattr(v, "dtype") else v)
             for k, v in rec.items()}
            for rec in fetched
        ]
        return self.history

    def evaluate(self) -> tuple[float, float]:
        loss, acc = self._eval_fn(self.server_state.params)
        return float(loss), float(acc)

    def evaluate_detection(self, benign_class: int = 0) -> dict:
        """Detection-oriented held-out report (per-class P/R/F1, macro-F1,
        alarm detection/false-alarm rates) — the metrics the reference's
        IoT anomaly deployment cares about, where accuracy alone hides an
        always-benign classifier.  One jit scan accumulating the global
        confusion matrix; host-side summarization
        (fed/evaluation.detection_report)."""
        if not hasattr(self, "_conf_eval_fn"):
            self._conf_eval_fn = make_confusion_eval_fn(
                self.eval_model.apply,
                self.dataset.x_test,
                self.dataset.y_test,
                batch=max(self.config.fed.batch_size, 64),
                num_classes=self.config.model.num_classes,
            )
        conf = np.asarray(self._conf_eval_fn(self.server_state.params))
        return detection_report(conf, benign_class=benign_class)

    # ---- federated (per-client) evaluation ---------------------------
    def evaluate_per_client(self) -> dict:
        """Score the CURRENT global model on every client's local shard.

        The reference's evaluator role scores one held-out set (SURVEY.md
        §3d); this is the federated-native complement — the model's fit to
        each client's own distribution, the quantity that matters under
        non-IID partitions.  One jit program, vmapped over clients (and
        sharded over the client axis on a mesh); returns per-client arrays
        in ORIGINAL client-id order plus weighted aggregates and the
        across-client accuracy spread.
        """
        if not hasattr(self, "_client_eval_fn"):
            self._client_eval_fn = self._build_client_eval_fn()
        loss, acc = self._client_eval_fn(
            self.server_state.params, *self._device_data[:3]
        )
        loss, acc = np.asarray(loss), np.asarray(acc)
        counts = np.asarray(self.shards.counts)
        # Undo the mesh interleaving, drop ghost clients.
        order = np.argsort(self.client_ids, kind="stable")
        loss, acc, counts = loss[order], acc[order], counts[order]
        real = counts > 0
        loss, acc, counts = loss[real], acc[real], counts[real]
        from colearn_federated_learning_tpu.fed.evaluation import (
            summarize_per_client,
        )

        out = summarize_per_client(loss, acc, counts)
        out.update(per_client_loss=loss, per_client_acc=acc,
                   num_examples=counts)
        return out

    # ---- client update similarity (clustered FL) ----------------------
    def client_update_similarity(self, steps: int = 1) -> np.ndarray:
        """(N, N) cosine similarity of every client's local update from
        the CURRENT global model — the clustering signal of clustered FL
        (fed/clustered.py): clients drawn from the same concept produce
        aligned updates, concept-shifted clients anti-align.

        One jit program: vmapped local steps over ALL clients, flatten,
        one gram matmul (MXU).  On the vmap path the (N, P) matrix never
        leaves the device.  On a mesh each device trains only ITS client
        block, L2-normalizes the (N/D, P) rows, all_gathers the
        normalized deltas over the client axis (robust aggregation pays
        the same O(N·P) price — order statistics and gram matrices are
        not psum-decomposable), computes its (N/D, N) strip of the gram
        on the MXU, and the strips reassemble to the sharded (N, N)
        output; rows/cols are then returned to ORIGINAL client-id order
        with ghost padding dropped.
        """
        if self.scaffold:
            raise NotImplementedError(
                "clustering uses the plain local trainer; run it with a "
                "stateless strategy"
            )
        if getattr(self, "_sim_key", None) != steps:
            self._sim_key = steps
            self._sim_fn = programs.build_similarity_fn(self, steps)
        sim = np.asarray(self._sim_fn(
            self.server_state.params, *self._device_data, self.base_key
        ))
        if self.mesh is not None:
            # Undo the mesh interleaving on BOTH axes; drop ghost padding.
            keep = self.id_order_slots()
            sim = sim[np.ix_(keep, keep)]
        return sim

    def id_order_slots(self) -> np.ndarray:
        """Array-slot index of every REAL client, in original client-id
        order — the inverse of the mesh interleaving with ghost padding
        dropped; the identity on the vmap path.

        Ghosts are identified by id (``id >= real_num_clients``: padding
        appends them after the real clients), NOT by ``counts == 0`` — a
        real client whose partition happens to be empty must keep its
        slot so per-id indexing (clustered FL labels) stays aligned
        across engine paths."""
        if self.mesh is None:
            return np.arange(self.num_clients)
        ids = np.asarray(self.client_ids)
        order = np.argsort(ids, kind="stable")
        return order[:self.real_num_clients]

    # ---- personalized evaluation (fine-tune-then-eval) ----------------
    def evaluate_personalized(self, steps: int = 5,
                              lr: Optional[float] = None) -> dict:
        """Per-client personalization probe: fine-tune the CURRENT global
        model on the first half of each client's shard for ``steps`` local
        SGD steps, then score BOTH the global and the personalized model on
        the held-out second half.  The spread between the two is the value
        personalization adds under this partition — the FedPer-style
        question the reference cannot ask (its evaluator scores one global
        holdout).  One jit program, vmapped over clients (sharded over the
        client axis on a mesh).

        Clients with fewer than 2 examples have no holdout half and are
        dropped from the aggregates.
        """
        key = (steps, lr)
        if getattr(self, "_pers_eval_key", None) != key:
            self._pers_eval_fn = programs.build_personalized_eval_fn(
                self, steps, lr if lr is not None else self.config.fed.lr
            )
            self._pers_eval_key = key
        g_acc, p_acc, n_eval = self._pers_eval_fn(
            self.server_state.params, *self._device_data
        )
        g_acc, p_acc = np.asarray(g_acc), np.asarray(p_acc)
        n_eval = np.asarray(n_eval)
        order = np.argsort(self.client_ids, kind="stable")
        g_acc, p_acc, n_eval = g_acc[order], p_acc[order], n_eval[order]
        real = n_eval > 0
        g_acc, p_acc, n_eval = g_acc[real], p_acc[real], n_eval[real]
        if n_eval.sum() == 0:
            # No client holds the >= 2 examples a holdout half needs.
            return {
                "global_acc": 0.0, "personalized_acc": 0.0,
                "personalization_gain": 0.0,
                "per_client_global_acc": g_acc,
                "per_client_personalized_acc": p_acc,
                "num_eval_examples": n_eval,
                "num_clients_evaluated": 0,
            }
        w = n_eval / n_eval.sum()
        return {
            "global_acc": float((g_acc * w).sum()),
            "personalized_acc": float((p_acc * w).sum()),
            "personalization_gain": float(((p_acc - g_acc) * w).sum()),
            "per_client_global_acc": g_acc,
            "per_client_personalized_acc": p_acc,
            "num_eval_examples": n_eval,
            "num_clients_evaluated": int(real.sum()),
        }

    # ---- checkpoint/resume (SURVEY.md §5; ckpt/manager.py) -----------
    def _checkpointer(self):
        if self._ckpt is None:
            from colearn_federated_learning_tpu.ckpt import RoundCheckpointer

            self._ckpt = RoundCheckpointer.for_run(self.config.run)
        return self._ckpt

    def save_checkpoint(self) -> None:
        # Scaffold's per-client variates are part of the training state and
        # checkpoint alongside the server state (None otherwise).
        self._checkpointer().save(
            len(self.history), (self.server_state, self.client_c), self.history
        )

    def restore_checkpoint(self) -> int:
        """Restore the latest checkpoint; returns the resumed round index."""
        state, history, step = self._checkpointer().restore(
            (self.server_state, self.client_c)
        )
        self.server_state, self.client_c = state
        self.history = history
        if self.accountant is not None:
            # ε must account for every round already spent before the kill.
            self.accountant.steps = step
        if self.adaptive_clip and history:
            # The clip state rides the per-round metrics (one scalar per
            # record), so resume continues from the adapted norm.
            self._dp_clip = jnp.float32(history[-1]["dp_clip"])
        return step

    def fit(self, rounds: Optional[int] = None, log_fn=None) -> list[dict]:
        """Run ``rounds`` more federated rounds.  ``rounds=None`` means "up
        to the configured total": after a restore at round k, the default
        runs the REMAINING config.fed.rounds - k rounds, not a fresh full
        run."""
        if rounds is None:
            rounds = max(0, self.config.fed.rounds - len(self.history))
        run = self.config.run
        eval_every = max(1, run.eval_every)
        log_every = max(1, run.log_every)
        ckpt_every = max(0, run.checkpoint_every)
        want_ckpt = bool(run.checkpoint_dir)
        last_round = len(self.history) + rounds - 1  # fit() may be called again
        telem = telemetry.RoundTelemetry(run, self.tracer)
        # FLOPs capture is opt-in with the trace window (the AOT compile
        # behind cost_analysis does not share the jit cache, so it is a
        # real one-time cost) and cached across fit() calls.
        if telem.tracing and self._flops_per_round is None:
            self._flops_per_round = self.round_cost_analysis().get(
                "flops_per_round")
        try:
            for _ in range(rounds):
                t0 = time.perf_counter()
                telem.before_round(len(self.history))
                with self.tracer.span("round", round=len(self.history)):
                    rec = self.run_round()
                    if telem.profiling and not self.tracer.enabled:
                        # The jax trace window must contain the round's
                        # device work — only synchronise while actually
                        # profiling (blocking every round would serialise
                        # the async dispatch pipeline; the span tracer
                        # already put up its own barrier in run_round).
                        jax.block_until_ready(self.server_state.params)
                    telem.after_round(rec["round"])
                    rec["round_time_s"] = time.perf_counter() - t0
                    # Both keys appear only when their source exists —
                    # memory_stats() is empty on CPU, flops capture is
                    # trace-window opt-in — so default-run records stay
                    # byte-identical (tested layout contract).
                    stats = telemetry.sample_device_memory()
                    if stats.get("bytes_in_use"):
                        rec["hbm_used_gb"] = round(
                            stats["bytes_in_use"] / 2**30, 3)
                    if self._flops_per_round:
                        rec["flops_per_round"] = self._flops_per_round
                    if (rec["round"] % eval_every == 0
                            or rec["round"] == last_round):
                        with self.tracer.span("evaluate") as ev_sp:
                            loss, acc = self.evaluate()
                        rec["eval_loss"], rec["eval_acc"] = loss, acc
                        rec["phase_eval_s"] = ev_sp.duration_s
                    if log_fn is not None and (
                        rec["round"] % log_every == 0
                        or rec["round"] == last_round
                    ):
                        log_fn(rec)
                    # With a checkpoint_dir, the final round ALWAYS
                    # checkpoints even when no periodic cadence is
                    # configured, so --resume works.
                    if want_ckpt and (
                        (ckpt_every and (rec["round"] + 1) % ckpt_every == 0)
                        or rec["round"] == last_round
                    ):
                        with self.tracer.span("checkpoint") as ck_sp:
                            self.save_checkpoint()
                        rec["phase_checkpoint_s"] = ck_sp.duration_s
                telemetry.get_registry().histogram(
                    "engine.round_time_s").observe(rec["round_time_s"])
                # end_round AFTER the round span closed — an early window
                # flush must include the final traced round.
                telem.end_round(rec["round"])
        finally:
            # An exception mid-window (eval/log/ckpt) must not leave the
            # process-global jax profiler trace running, and whatever spans
            # were recorded still reach disk.
            self.last_trace_path = telem.close()
        return self.history
