"""Federated round orchestration, fully on-device.

This replaces the reference's coordinator process (SURVEY.md §3a: MQTT
enrollment → websocket broadcast → per-worker PyTorch epochs → host-side
``fed_avg``) with a single jit-compiled round function:

- single chip: clients are a ``vmap`` axis,
- multi chip:  clients are a ``shard_map`` axis over a ``jax.sharding.Mesh``
  and the weighted average lowers to ``jax.lax.psum`` over ICI
  (BASELINE.json ``north_star``).

One call = one federated round: cohort sampling → broadcast (implicit: the
global params are an operand) → local SGD per client → privacy hooks →
weighted aggregation → server update.  Shapes are static across rounds, so
the program compiles once.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.data.sharding import (
    ClientShards,
    pack_client_shards,
    pad_clients_to_multiple,
)
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.fed.evaluation import (
    detection_report,
    make_confusion_eval_fn,
    make_eval_fn,
)
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.privacy import dp as dp_lib
from colearn_federated_learning_tpu.privacy import secure_agg as sa_lib
from colearn_federated_learning_tpu.utils import prng, pytrees
from colearn_federated_learning_tpu.utils import config as config_lib
from colearn_federated_learning_tpu.utils.config import ExperimentConfig


def _resolve_devices(backend: str) -> list:
    """Device list for --backend=auto|cpu|tpu (auto prefers accelerators).

    ``auto`` degrades to the CPU backend when the default backend fails to
    initialize (a flaky TPU plugin must not kill a CPU-capable run);
    ``tpu`` stays strict and surfaces the error."""
    if backend == "auto":
        try:
            return jax.devices()
        except Exception:
            return jax.devices("cpu")
    devices = jax.devices()
    if backend == "cpu":
        devices = [d for d in devices if d.platform == "cpu"] or jax.devices("cpu")
    elif backend == "tpu":
        tpu = [d for d in devices if d.platform not in ("cpu",)]
        if not tpu:
            raise RuntimeError("--backend=tpu requested but no accelerator present")
        devices = tpu
    else:
        raise ValueError(f"unknown backend {backend!r} (use auto|cpu|tpu)")
    return devices


def _rank_cohort(skey, counts, k):
    """Uniform sample of ``k`` clients WITHOUT replacement among real
    clients: ghosts (count 0) are pushed to the end of the ranking and only
    picked if the cohort exceeds real clients.  Pure jnp — the SAME function
    runs traced inside the round program (fedavg paths) and eagerly on host
    (the scaffold path, which must know the cohort before dispatch to gather
    its variate rows); any edit applies to both."""
    scores = jax.random.uniform(skey, counts.shape)
    scores = scores + (counts == 0) * 1e3
    return jnp.argsort(scores)[:k]


class FederatedLearner:
    """End-to-end federated experiment: data, model, round loop, eval.

    ``mesh``: optional ``jax.sharding.Mesh``.  The ``config.run.mesh_axis``
    (clients) axis is required; a ``seq`` axis adds ring-attention sequence
    parallelism, and a ``model`` axis adds GSPMD tensor/expert parallelism
    (parallel/tp.py) — any combination up to the 3-D
    (clients, seq, model) mesh.  Client state shards over the client axis
    and aggregation runs as psum over it.  When None, everything runs on
    one device via vmap.
    """

    @classmethod
    def from_config(
        cls,
        config: ExperimentConfig,
        dataset: Optional[data_registry.Dataset] = None,
    ) -> "FederatedLearner":
        """Build a learner honoring ``config.run.backend`` (the CLI's
        ``--backend=tpu|cpu|auto``, BASELINE.json ``north_star``): resolve
        devices and lay clients over a 1-D mesh — or, with
        ``attn_impl="ring"``, a 2-D (clients, seq) mesh, or, with
        ``run.tp_size > 1``, a 2-D (clients, model) tensor-parallel
        mesh."""
        from colearn_federated_learning_tpu.parallel.mesh import make_mesh

        devices = _resolve_devices(config.run.backend)
        r = config.run
        if config.model.attn_impl in ("ring", "ulysses") and r.tp_size > 1:
            raise ValueError(
                "from_config cannot auto-lay a 3-D (clients, seq, model) "
                "mesh; build it with parallel.mesh.make_mesh and pass "
                "mesh= explicitly"
            )
        mesh = None
        if r.tp_size > 1 and len(devices) % r.tp_size != 0:
            # Non-divisible device counts would otherwise surface as an
            # opaque reshape error inside make_mesh((-1, tp_size)).
            import warnings

            warnings.warn(
                f"tp_size={r.tp_size} needs a device count that is a "
                f"multiple of it, have {len(devices)}; running without "
                f"tensor parallelism",
                stacklevel=2,
            )
        if len(devices) > 1:
            if config.model.attn_impl in ("ring", "ulysses"):
                mesh = make_mesh((r.mesh_axis, r.seq_axis), devices=devices)
            elif r.tp_size > 1 and len(devices) % r.tp_size == 0:
                mesh = make_mesh((r.mesh_axis, r.tp_axis), (-1, r.tp_size),
                                 devices=devices)
            else:
                mesh = Mesh(np.array(devices), (r.mesh_axis,))
        return cls(config, dataset=dataset, mesh=mesh)

    def __init__(
        self,
        config: ExperimentConfig,
        dataset: Optional[data_registry.Dataset] = None,
        mesh: Optional[Mesh] = None,
        partitions: Optional[list] = None,
    ):
        """``partitions``: optional explicit per-client index lists into the
        dataset's train split, overriding ``config.data.partition`` —
        callers that already know exactly who owns which rows (clustered
        FL preserving member shards) inject them here."""
        self.config = config
        self.mesh = mesh
        c = config
        config_lib.validate_experiment(c)

        # --- mesh axes ------------------------------------------------
        # 1-D mesh: clients only.  2-D (attn_impl="ring"): + an inner ``seq``
        # axis (sequence parallelism; parallel/ring.py).  A ``model`` axis
        # (parallel/tp.py) adds tensor/expert parallelism: it is left to the
        # AUTOMATIC partitioner (shard_map axis_names excludes it), params
        # are sharded over it by the TP rules, and XLA inserts the TP
        # collectives inside each client's local step.
        self.client_axis = c.run.mesh_axis
        self.seq_axis = c.run.seq_axis
        self.tp_axis = c.run.tp_axis
        if mesh is not None:
            if self.client_axis not in mesh.shape:
                raise ValueError(
                    f"mesh axes {tuple(mesh.shape)} lack the client axis "
                    f"{self.client_axis!r}"
                )
            self.clients_size = mesh.shape[self.client_axis]
            self.seq_size = mesh.shape.get(self.seq_axis, 1)
            self.tp_size = mesh.shape.get(self.tp_axis, 1)
            extra = set(mesh.shape) - {
                self.client_axis, self.seq_axis, self.tp_axis
            }
            if extra:
                raise ValueError(f"unsupported mesh axes {sorted(extra)}")
        else:
            self.clients_size = 1
            self.seq_size = 1
            self.tp_size = 1
        self.sp = self.seq_size > 1
        if self.sp and c.model.attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"a {self.seq_size}-way {self.seq_axis!r} mesh axis requires "
                "model.attn_impl='ring' or 'ulysses'"
            )
        if (c.model.attn_impl in ("ring", "ulysses") and mesh is not None
                and not self.sp):
            raise ValueError(
                f"attn_impl={c.model.attn_impl!r} on a mesh requires a "
                f"{self.seq_axis!r} axis of size > 1"
            )

        # --- data -----------------------------------------------------
        self.dataset = dataset or data_registry.get_dataset(
            c.data.dataset, seed=c.run.seed
        )
        labels = np.asarray(self.dataset.y_train)
        parts = (partitions if partitions is not None
                 else setup_lib.partition_for_config(c, labels))
        shards = pack_client_shards(
            np.asarray(self.dataset.x_train), labels, parts,
            capacity=c.data.max_examples_per_client,
        )
        self.real_num_clients = shards.num_clients   # pre-ghost-padding
        if self.sp:
            seq_len = shards.x.shape[-1]
            if shards.x.ndim != 3:
                raise ValueError(
                    "sequence parallelism needs (tokens,)-shaped examples, "
                    f"got example shape {shards.x.shape[2:]}"
                )
            if seq_len % self.seq_size:
                raise ValueError(
                    f"seq_len {seq_len} is not divisible by the "
                    f"{self.seq_size}-way {self.seq_axis!r} axis"
                )
            if (c.model.attn_impl == "ulysses"
                    and c.model.num_heads % self.seq_size):
                # Fail eagerly like the seq_len check above — the kernel's
                # own guard would only fire deep inside the first trace.
                raise ValueError(
                    f"attn_impl='ulysses' needs num_heads "
                    f"({c.model.num_heads}) divisible by the "
                    f"{self.seq_size}-way {self.seq_axis!r} axis; use "
                    "attn_impl='ring'"
                )
        if mesh is not None:
            shards = pad_clients_to_multiple(shards, self.clients_size)
            # Interleave so real clients spread evenly across devices (ghost
            # padding would otherwise pile onto the last devices and starve
            # their per-device cohorts).  ``client_ids[slot]`` is the
            # ORIGINAL client identity of each array slot; all PRNG is keyed
            # on it, keeping results placement-independent.
            D = self.clients_size
            L = shards.num_clients // D
            order = np.array(
                [j * D + d for d in range(D) for j in range(L)], dtype=np.int32
            )
            shards = ClientShards(
                x=shards.x[order], y=shards.y[order], counts=shards.counts[order]
            )
            self.client_ids = order
        else:
            self.client_ids = np.arange(shards.num_clients, dtype=np.int32)
        self.shards = shards
        self.num_clients = shards.num_clients

        # --- model ----------------------------------------------------
        # Under SP the trained module runs on sequence SHARDS inside
        # shard_map; its dense-attention twin (identical param pytree) is
        # used for init and full-sequence evaluation outside the mesh.
        train_model_cfg = (
            c.model if self.sp else setup_lib.local_model_config(c.model)
        )
        self.model = model_registry.build_model(
            train_model_cfg, seq_axis_name=self.seq_axis if self.sp else None
        )
        if self.sp:
            self.eval_model = model_registry.build_model(
                setup_lib.local_model_config(c.model)
            )
        else:
            self.eval_model = self.model
        example_x = jnp.asarray(shards.x[0, : c.fed.batch_size])
        ikey = prng.init_key(prng.experiment_key(c.run.seed))
        self.params = model_registry.init_params(self.eval_model, example_x, ikey)
        if self.tp_size > 1:
            # Tensor parallelism: shard the wide param dims over the model
            # axis (parallel/tp.py rules); ``init_server_state``'s
            # zeros_like leaves inherit the shardings, so the whole server
            # state lives TP-sharded from the start.
            from colearn_federated_learning_tpu.parallel import tp as tp_lib

            self.params = tp_lib.shard_params(self.params, mesh, self.tp_axis)
        self.server_state = strategies.init_server_state(self.params, c.fed)

        # --- local trainer -------------------------------------------
        self.scaffold = c.fed.strategy == "scaffold"
        self.fednova = c.fed.strategy == "fednova"
        if c.fed.secure_agg and c.fed.secure_agg_neighbors and (
            c.fed.secure_agg_neighbors % 2 or c.fed.secure_agg_neighbors < 2
        ):
            raise ValueError(
                "secure_agg_neighbors must be an even integer >= 2, got "
                f"{c.fed.secure_agg_neighbors}"
            )
        if self.scaffold and (c.fed.secure_agg or c.fed.dp_clip > 0.0):
            raise ValueError(
                "scaffold is incompatible with secure_agg/dp hooks: the "
                "control-variate deltas are a second payload the masks and "
                "noise calibration do not cover"
            )
        if self.scaffold and self.tp_size > 1:
            raise ValueError(
                "scaffold with a model (TP) axis is unsupported: the "
                "host-resident variate store is unsharded and the per-round "
                "gather/scatter would funnel TP shards through one host"
            )
        # Byzantine-robust aggregation (fed/robust.py).
        from colearn_federated_learning_tpu.fed.robust import AGGREGATORS

        if c.fed.aggregator not in AGGREGATORS:
            raise ValueError(
                f"unknown aggregator {c.fed.aggregator!r}; use {AGGREGATORS}"
            )
        self.robust = c.fed.aggregator != "mean"
        if self.robust:
            if not 0.0 <= c.fed.trim_fraction < 0.5:
                raise ValueError(
                    "trim_fraction must be in [0, 0.5), got "
                    f"{c.fed.trim_fraction}"
                )
            if c.fed.secure_agg:
                raise ValueError(
                    "robust aggregators need the individual updates; "
                    "secure-agg masks only cancel in a plain sum"
                )
            if self.scaffold:
                raise ValueError(
                    "scaffold assumes mean aggregation of its control "
                    "variates; use aggregator='mean'"
                )
            if c.fed.dp_noise_multiplier > 0.0:
                raise ValueError(
                    "robust aggregation of noised updates is not the "
                    "Gaussian mechanism the RDP accountant models; use "
                    "dp_clip alone (norm bounding) with robust aggregators"
                )
        self.local_update, self.num_steps = setup_lib.local_trainer_for_config(
            c, self.model.apply, shards.capacity,
            grad_sync_axes=(self.seq_axis,) if self.sp else (),
        )
        # SCAFFOLD per-client control variates: one params-shaped pytree per
        # client, stacked on the client axis — resident on HOST (numpy).
        # Each round gathers only the COHORT's variates into the jit round
        # program and scatters the updated block back, so device memory is
        # O(cohort × model), not O(num_clients × model) — the flagship
        # configs (thousands of clients × ViT) never fit the full stack.
        if self.scaffold:
            self.client_c = jax.tree.map(
                lambda w: np.zeros((self.num_clients,) + w.shape, w.dtype),
                self.params,
            )
        else:
            self.client_c = None

        # --- cohort ---------------------------------------------------
        cohort = c.fed.cohort_size or self.num_clients
        self.cohort_size = min(cohort, self.num_clients)
        if mesh is not None:
            d = self.clients_size
            # per-device cohort must be equal and static
            self.cohort_per_device = max(1, self.cohort_size // d)
            adjusted = self.cohort_per_device * d
            if adjusted != self.cohort_size:
                import warnings

                warnings.warn(
                    f"cohort_size={self.cohort_size} is not a multiple of the "
                    f"{d}-way client axis; using {adjusted} "
                    f"({self.cohort_per_device}/device)",
                    stacklevel=2,
                )
            self.cohort_size = adjusted
        if (self.robust and c.fed.aggregator in ("trimmed_mean", "krum")
                and int(c.fed.trim_fraction * self.cohort_size + 1e-4) < 1):
            # floor(trim · cohort) == 0 trims/excludes nothing — the
            # "robust" aggregate would silently be the plain mean while
            # still paying uniform weights and the secure-agg/DP bans.
            what = ("trims zero clients" if c.fed.aggregator == "trimmed_mean"
                    else "assumes zero Byzantine clients (f = 0)")
            if self.cohort_size < 3:
                # Any fraction satisfying floor(trim·cohort) >= 1 here
                # would breach the < 0.5 cap: no valid value exists.
                raise ValueError(
                    f"aggregator={c.fed.aggregator!r} needs a cohort of at "
                    f"least 3 (got {self.cohort_size}); use "
                    "aggregator='median'"
                )
            import math

            # Round the suggestion UP so following it actually passes.
            ok_frac = math.ceil(1e6 / self.cohort_size) / 1e6
            raise ValueError(
                f"trim_fraction={c.fed.trim_fraction} {what} at "
                f"cohort_size={self.cohort_size}; raise it to at least "
                f"{ok_frac:.6f} (or use aggregator='median')"
            )
        # DP noise accounting divides by the number of REAL clients expected
        # to contribute (ghost padding never contributes).  If stragglers
        # drop mid-round the realized central noise is below nominal — a
        # known property of DP-FedAvg with dropouts; see privacy/dp.py.
        self.dp_cohort = min(self.cohort_size, self.real_num_clients)
        # Adaptive clipping (privacy/dp.py, quantile tracking): the clip
        # norm is a DEVICE scalar threaded operand -> metric through the
        # round program, so back-to-back rounds adapt it with no host sync.
        self.adaptive_clip = c.fed.dp_adaptive_clip
        if self.adaptive_clip:
            if c.fed.dp_clip <= 0.0:
                raise ValueError(
                    "dp_adaptive_clip needs dp_clip > 0 as the initial norm"
                )
            z = c.fed.dp_noise_multiplier
            if z > 0.0:
                self.dp_bit_noise = c.fed.dp_bit_noise or max(
                    self.dp_cohort / 20.0, 1.0
                )
                # The bit query spends part of the budget; the update noise
                # is inflated so the JOINT per-round mechanism still costs
                # the configured z — the accountant below stays valid as-is.
                self.dp_z = dp_lib.adaptive_noise_multiplier(
                    z, self.dp_bit_noise
                )
            else:
                self.dp_bit_noise = 0.0
                self.dp_z = 0.0
        self._dp_clip = jnp.float32(c.fed.dp_clip)
        # RDP accountant: cumulative (ε, δ) per round when DP is on
        # (privacy/accountant.py; each round is one subsampled Gaussian
        # mechanism with q = cohort / N at central noise σ).
        from colearn_federated_learning_tpu.privacy.accountant import (
            RdpAccountant,
        )

        self.accountant = RdpAccountant.from_config(
            c.fed, sampling_rate=self.dp_cohort / self.real_num_clients
        )

        # --- compiled programs ---------------------------------------
        self.base_key = prng.experiment_key(c.run.seed)
        self._round_fn = self._build_round_fn()
        self._eval_fn = self._build_eval_fn()
        self._device_data = self._place_data()
        self.history: list[dict] = []
        self._ckpt = None

    # ------------------------------------------------------------------
    # data placement
    # ------------------------------------------------------------------
    def _place_data(self):
        x = jnp.asarray(self.shards.x)
        y = jnp.asarray(self.shards.y)
        counts = jnp.asarray(self.shards.counts)
        ids = jnp.asarray(self.client_ids)
        if self.mesh is not None:
            ax = self.client_axis
            # Under SP each client's token dim is also sharded (last axis of
            # the (clients, capacity, seq_len) block).
            x_spec = (
                P(ax, None, self.seq_axis) if self.sp else P(ax)
            )
            x = jax.device_put(x, NamedSharding(self.mesh, x_spec))
            sh = NamedSharding(self.mesh, P(ax))
            y, counts, ids = (jax.device_put(a, sh) for a in (y, counts, ids))
        return (x, y, counts, ids)

    # ------------------------------------------------------------------
    # one round, single-device (vmap over the cohort)
    # ------------------------------------------------------------------
    def _cohort_step(self, params, local_ids, global_ids, mask_cohort_ids,
                     x, y, counts, key, round_idx,
                     control=None, c_blk=None, clip=None):
        """Shared per-cohort logic: local training + privacy + weighting.

        ``local_ids`` index into the (possibly per-device) ``x/y/counts``
        blocks; ``global_ids`` are the mesh-wide client identities used for
        PRNG derivation, so results are bit-identical regardless of how
        clients are placed on devices.  ``mask_cohort_ids`` is the FULL
        round cohort (all devices) that secure-agg masks pair against.
        ``control`` / ``c_blk`` are the scaffold global variate and the
        COHORT-ALIGNED block of per-client variates (one row per cohort
        slot, gathered host-side from the full store before the call).
        Returns (weighted_delta_sum, total_weight, metrics, scaffold_extras)
        — the caller finishes aggregation either locally (vmap path) or
        with a psum (shard_map path); ``scaffold_extras`` is None or
        ``(delta_c_uniform_sum, n_contributors, updated_cohort_block)``.
        """
        c = self.config.fed
        cx = jnp.take(x, local_ids, axis=0)
        cy = jnp.take(y, local_ids, axis=0)
        ccounts = jnp.take(counts, local_ids, axis=0)

        # Per-(client, round) keys: placement-independent determinism.
        keys = jax.vmap(lambda i: prng.client_round_key(key, i, round_idx))(global_ids)

        # Straggler simulation: each cohort slot draws a per-CLIENT budget
        # (keyed on global id, so placement-independent).
        if c.straggler_prob > 0.0:
            skey = prng.straggler_key(key, round_idx)

            def budget_for(i):
                k = jax.random.fold_in(skey, i)
                slow = jax.random.bernoulli(k, c.straggler_prob)
                frac = jax.random.uniform(jax.random.fold_in(k, 1))
                return jnp.where(
                    slow, (frac * self.num_steps).astype(jnp.int32), self.num_steps
                )

            budgets = jax.vmap(budget_for)(global_ids)
        else:
            budgets = jnp.full((self.cohort_size_local,), self.num_steps, jnp.int32)

        # Round-level client-lr schedule factor, computed in-graph from
        # the round operand (no retrace, no host sync).
        lr_scale = strategies.lr_scale_for_round(c, round_idx)

        if self.scaffold:
            c_i = c_blk                      # already one row per cohort slot
            sres = jax.vmap(
                self.local_update,
                in_axes=(None, 0, 0, 0, 0, 0, 0, None, None),
            )(params, cx, cy, ccounts, keys, budgets, c_i, control, lr_scale)
            results = sres.result
        else:
            sres = None
            results = jax.vmap(
                self.local_update, in_axes=(None, 0, 0, 0, 0, 0, None)
            )(params, cx, cy, ccounts, keys, budgets, lr_scale)
        deltas = results.delta
        completed = results.completed
        nova_a = None
        if self.fednova:
            # FedNova (Wang et al., pattern only): normalize each delta by
            # its effective local-step coefficient a_i, so heterogeneous
            # step counts (straggler budgets!) stop biasing the objective;
            # the round epilogue rescales the mean by the weighted mean a.
            m = c.momentum
            tau = jnp.maximum(results.steps_run, 1.0)
            if m > 0.0:
                nova_a = (tau - m * (1.0 - m ** tau) / (1.0 - m)) / (1.0 - m)
            else:
                nova_a = tau
            deltas = jax.vmap(
                lambda d, a: pytrees.tree_scale(d, 1.0 / a)
            )(deltas, nova_a)
        # Round telemetry: per-client update norms (the quantity operators
        # tune dp_clip against).  ONLY for non-private plain runs — under
        # DP the exact un-noised norms are an unaccounted release (the
        # adaptive path pays for even a 1-bit norm query), and under
        # secure-agg they are precisely what the masks exist to hide.
        track_norms = not (c.dp_clip > 0.0 or c.secure_agg)
        if track_norms:
            norms = jax.vmap(pytrees.tree_global_norm)(deltas)

        # SCAFFOLD averages uniformly over the sampled cohort (the variate
        # algebra assumes it); DP/secure-agg force uniform weights too.
        uniform_weights = (c.dp_clip > 0.0 or c.secure_agg or self.scaffold
                           or self.robust)
        bits = None
        if c.dp_clip > 0.0:
            dp_keys = jax.vmap(lambda i: prng.dp_key(key, i, round_idx))(global_ids)
            if self.adaptive_clip:
                # Traced clip scalar + per-client quantile bit (pre-clip
                # norm <= clip), update noise at the inflated multiplier.
                deltas, bits = jax.vmap(
                    lambda d, k: dp_lib.clip_and_noise_with_bit(
                        d, clip, self.dp_z, self.dp_cohort, k
                    )
                )(deltas, dp_keys)
            else:
                deltas = jax.vmap(
                    lambda d, k: dp_lib.clip_and_noise(
                        d, c.dp_clip, c.dp_noise_multiplier, self.dp_cohort, k
                    )
                )(deltas, dp_keys)

        nonghost = (results.num_examples > 0)
        # The ONE contributor mask (real, non-straggler) every aggregation
        # branch and metric below derives from.
        contrib = completed & nonghost
        if uniform_weights:
            weights = contrib.astype(jnp.float32)
        else:
            weights = results.num_examples.astype(jnp.float32) * contrib

        sa_bit_sum = None
        if c.secure_agg:
            # Clients pre-scale by their weight, then add pairwise masks;
            # masks cancel in the plain SUM over the cohort.  Masks pair
            # GLOBAL ids, so cancellation holds across devices too (the
            # final sum is the psum over the mesh).
            wdeltas = jax.vmap(lambda d, w: pytrees.tree_scale(d, w))(deltas, weights)
            # The per-round pairing graph (ring permutation or complete
            # graph) is computed ONCE here, not per vmap lane — each lane
            # then does only O(partners) PRG work.
            partners = sa_lib.partner_table(
                key, global_ids, mask_cohort_ids, round_idx,
                neighbors=c.secure_agg_neighbors,
            )
            masked = jax.vmap(
                lambda d, i, prt: sa_lib.mask_update(d, key, i, prt,
                                                     round_idx)
            )(wdeltas, global_ids, partners)
            wsum = jax.tree.map(lambda l: jnp.sum(l, axis=0), masked)
            if bits is not None:
                # Adaptive clipping under secure-agg: the quantile bit is a
                # second payload — mask it on its own pair stream so only
                # the cohort SUM is visible, like the deltas (the
                # contribution weighting is folded in pre-mask).
                # std ≫ 1: a unit-scale mask on a {0,1} payload would leak
                # the bit with constant statistical advantage; at 1e3 the
                # float32 cancellation residual (~1e-7·std·√cohort) is
                # still far below the O(cohort) bit sum.
                masked_bits = jax.vmap(
                    lambda b, i, prt: sa_lib.mask_scalar(b, key, i, prt,
                                                         round_idx, std=1e3)
                )(bits * contrib.astype(jnp.float32), global_ids, partners)
                sa_bit_sum = jnp.sum(masked_bits)
        elif self.robust:
            # Coordinate-wise robust statistic over the FULL cohort
            # (fed/robust.py).  Order statistics are not psum-decomposable,
            # so on a mesh the stacked deltas are all-gathered over the
            # client axis first and the aggregate comes out replicated —
            # the round epilogue uses it directly (no psum, no division).
            from colearn_federated_learning_tpu.fed.robust import (
                robust_aggregate,
            )

            if self.mesh is not None:
                ax = self.client_axis
                all_deltas = jax.tree.map(
                    lambda l: jax.lax.all_gather(l, ax, axis=0, tiled=True),
                    deltas,
                )
                all_contrib = jax.lax.all_gather(contrib, ax, axis=0,
                                                 tiled=True)
            else:
                all_deltas, all_contrib = deltas, contrib
            wsum = robust_aggregate(all_deltas, all_contrib,
                                    c.aggregator, c.trim_fraction)
        else:
            wsum = pytrees.tree_weighted_sum(deltas, weights)

        total_w = jnp.sum(weights)
        loss_sum = jnp.sum(results.mean_loss * weights)
        # "completed" reports real contributors only (ghost padding slots
        # always finish their budget but never contribute).
        n_completed = jnp.sum(contrib.astype(jnp.int32))
        # Quantile-bit sum over CONTRIBUTORS (the clip adapts to the norms
        # that actually entered the aggregate).  Under secure-agg the
        # masked sum computed above stands in (cancellation ⇒ same value
        # up to float32 residual).
        if sa_bit_sum is not None:
            bit_sum = sa_bit_sum
        elif bits is not None:
            bit_sum = jnp.sum(bits * contrib.astype(jnp.float32))
        else:
            bit_sum = jnp.zeros((), jnp.float32)
        if track_norms:
            cf = contrib.astype(jnp.float32)
            norm_sum = jnp.sum(norms * cf)
            norm_max = jnp.max(norms * cf)
        else:
            norm_sum = norm_max = jnp.zeros((), jnp.float32)
        # FedNova: weighted sum of the a_i coefficients — the epilogue's
        # mean rescale factor is nova_sum / total_w.
        nova_sum = (
            jnp.sum(weights * nova_a)
            if nova_a is not None else jnp.zeros((), jnp.float32)
        )

        extras = None
        if self.scaffold:
            uw = contrib.astype(jnp.float32)
            dc_sum = pytrees.tree_weighted_sum(sres.delta_c, uw)
            # Refresh only contributors' variates; non-contributor rows keep
            # their old values.  The caller scatters this cohort block back
            # into the host-resident full store.
            c_masked = jax.tree.map(
                lambda new, old: jnp.where(
                    contrib.reshape((-1,) + (1,) * (new.ndim - 1)), new, old
                ),
                sres.c_new, c_i,
            )
            extras = (dc_sum, n_completed.astype(jnp.float32), c_masked)
        return (wsum, total_w,
                (loss_sum, n_completed, bit_sum, norm_sum, norm_max,
                 nova_sum), extras)

    def _finish_round(self, server_state, wsum, total_w, loss_sum, n_comp,
                      dc_sum=None, n_contrib=None, bit_sum=None, clip=None,
                      key=None, round_idx=None, norm_sum=None,
                      norm_max=None, nova_sum=None):
        """Shared round epilogue (vmap and shard_map paths): mean delta,
        server update, metrics.  Zero contributors (all stragglers) → no-op
        update; the explicit gate matters under secure_agg, where wsum is
        not exactly zero but the float32 mask-cancellation residual."""
        denom = jnp.where(total_w > 0, total_w, 1.0)
        if self.robust:
            # wsum IS the robust aggregate (zero when nobody contributed);
            # total_w only normalizes the loss metric below.
            mean_delta = wsum
        else:
            mean_delta = pytrees.tree_scale(
                wsum, jnp.where(total_w > 0, 1.0 / denom, 0.0)
            )
        if self.fednova and nova_sum is not None:
            # Rescale the mean of NORMALIZED deltas by the weighted-mean
            # step coefficient (tau_eff), completing d = tau_eff * mean.
            mean_delta = pytrees.tree_scale(mean_delta, nova_sum / denom)
        mean_delta_c = participation = None
        if self.scaffold:
            safe_n = jnp.maximum(n_contrib, 1.0)
            mean_delta_c = pytrees.tree_scale(
                dc_sum, jnp.where(n_contrib > 0, 1.0 / safe_n, 0.0)
            )
            participation = n_contrib / float(self.real_num_clients)
        new_state = strategies.server_update(server_state, mean_delta,
                                             self.config.fed,
                                             mean_delta_c=mean_delta_c,
                                             participation=participation)
        metrics = {
            "train_loss": loss_sum / denom,
            "completed": n_comp,
            "total_weight": total_w,
        }
        track_norms = not (self.config.fed.dp_clip > 0.0
                           or self.config.fed.secure_agg)
        if norm_sum is not None and track_norms:
            safe_n = jnp.maximum(n_comp.astype(jnp.float32), 1.0)
            metrics["delta_norm_mean"] = norm_sum / safe_n
            metrics["delta_norm_max"] = norm_max
        if self.adaptive_clip:
            # Noised quantile fraction -> geometric clip step.  In the
            # shard_map path this runs replicated AFTER the psums: every
            # device derives the identical noise from the shared key, so
            # the updated clip stays replicated.
            c = self.config.fed
            bnoise = (
                self.dp_bit_noise
                * jax.random.normal(prng.clip_bit_key(key, round_idx), ())
                if self.dp_bit_noise > 0.0 else 0.0
            )
            frac = jnp.clip(
                (bit_sum + bnoise)
                / jnp.maximum(n_comp.astype(jnp.float32), 1.0),
                0.0, 1.0,
            )
            new_clip = dp_lib.adaptive_clip_update(
                clip, frac, c.dp_target_quantile, c.dp_clip_lr
            )
            # A zero-contributor round (all stragglers) carries no norm
            # evidence: freeze the clip like the server update freezes.
            new_clip = jnp.where(n_comp > 0, new_clip, clip)
            metrics["dp_clip"] = jnp.maximum(new_clip, 1e-6)
            metrics["dp_bit_frac"] = frac
        return new_state, metrics

    def _manual_axes(self) -> frozenset:
        """Mesh axes the round shard_map is MANUAL over: clients (+ seq
        under SP).  A ``model`` (TP) axis stays out of the set, so the
        automatic partitioner handles it — params arrive sharded over it
        (parallel/tp.py) and XLA inserts the tensor-parallel collectives."""
        axes = {self.client_axis}
        if self.sp:
            axes.add(self.seq_axis)
        return frozenset(axes)

    def _donate_argnums(self) -> tuple[int, ...]:
        """Donate the consumed round state (server_state, cohort variate
        block) so XLA reuses their HBM in place — matters for big models.
        CPU ignores donation with a warning, so skip."""
        devs = self.mesh.devices.flat if self.mesh is not None else jax.devices()
        first = next(iter(devs))
        return () if first.platform == "cpu" else (0, 8)

    def _build_round_fn(self):
        c = self.config.fed
        ax = self.config.run.mesh_axis

        if self.mesh is None:
            self.cohort_size_local = self.cohort_size

            def round_fn(server_state, key, round_idx, x, y, counts, ids,
                         sel_in, c_cohort, clip_in):
                if self.scaffold:
                    # Cohort-resident variates: the cohort was sampled on
                    # host (so its variate rows could be gathered) and
                    # arrives as an operand.
                    sel = sel_in
                else:
                    skey = prng.sampling_key(key, round_idx)
                    if self.cohort_size < self.num_clients:
                        sel = _rank_cohort(skey, counts, self.cohort_size)
                    else:
                        sel = jnp.arange(self.num_clients)
                cohort_global = jnp.take(ids, sel)
                wsum, total_w, stats, extras = self._cohort_step(
                    server_state.params, sel, cohort_global,
                    cohort_global, x, y, counts, key, round_idx,
                    control=server_state.control, c_blk=c_cohort,
                    clip=clip_in,
                )
                (loss_sum, n_comp, bit_sum, norm_sum, norm_max,
                 nova_sum) = stats
                dc_sum, n_contrib, new_c = (
                    extras if extras is not None else (None, None, None)
                )
                new_state, metrics = self._finish_round(
                    server_state, wsum, total_w, loss_sum, n_comp,
                    dc_sum=dc_sum, n_contrib=n_contrib, bit_sum=bit_sum,
                    clip=clip_in, key=key, round_idx=round_idx,
                    norm_sum=norm_sum, norm_max=norm_max,
                    nova_sum=nova_sum,
                )
                return new_state, metrics, new_c

            return jax.jit(round_fn, donate_argnums=self._donate_argnums())

        # ---- multi-chip: shard_map over the client axis (and, under SP,
        # the sequence axis — every collective below names ONLY the client
        # axis, so the ring collectives inside the model stay on ``seq``).
        mesh = self.mesh
        ax = self.client_axis
        self.cohort_size_local = self.cohort_per_device
        local_clients = self.num_clients // self.clients_size

        def body(server_state, key, round_idx, x_blk, y_blk, counts_blk,
                 ids_blk, sel_blk, c_blk, clip_in):
            if self.scaffold:
                sel = sel_blk            # host-sampled (cohort-resident c)
            else:
                dev = jax.lax.axis_index(ax)
                skey = jax.random.fold_in(
                    prng.sampling_key(key, round_idx), dev
                )
                if self.cohort_per_device < local_clients:
                    # This device's slice of the cohort among its REAL
                    # clients (interleaved placement spreads reals evenly).
                    sel = _rank_cohort(skey, counts_blk,
                                       self.cohort_per_device)
                else:
                    sel = jnp.arange(local_clients)
            cohort_global = jnp.take(ids_blk, sel)
            # Secure-agg masks pair against the FULL mesh-wide cohort: a
            # cheap all_gather of the (cohort_per_device,) id vectors.
            mask_cohort = jax.lax.all_gather(cohort_global, ax).reshape(-1)
            wsum, total_w, stats, extras = self._cohort_step(
                server_state.params, sel, cohort_global, mask_cohort,
                x_blk, y_blk, counts_blk, key, round_idx,
                control=server_state.control, c_blk=c_blk, clip=clip_in,
            )
            (loss_sum, n_comp, bit_sum, norm_sum, norm_max,
             nova_sum) = stats
            # FedAvg across the pod: one psum over ICI per leaf.  (Robust
            # aggregates are already global+replicated — no psum.)
            if not self.robust:
                wsum = jax.tree.map(lambda l: jax.lax.psum(l, ax), wsum)
            total_w = jax.lax.psum(total_w, ax)
            loss_sum = jax.lax.psum(loss_sum, ax)
            n_comp = jax.lax.psum(n_comp, ax)
            bit_sum = jax.lax.psum(bit_sum, ax)
            norm_sum = jax.lax.psum(norm_sum, ax)
            norm_max = jax.lax.pmax(norm_max, ax)
            nova_sum = jax.lax.psum(nova_sum, ax)
            if extras is not None:
                dc_sum, n_contrib, new_c = extras
                dc_sum = jax.tree.map(lambda l: jax.lax.psum(l, ax), dc_sum)
                n_contrib = jax.lax.psum(n_contrib, ax)
            else:
                dc_sum, n_contrib, new_c = None, None, None
            new_state, metrics = self._finish_round(
                server_state, wsum, total_w, loss_sum, n_comp,
                dc_sum=dc_sum, n_contrib=n_contrib, bit_sum=bit_sum,
                clip=clip_in, key=key, round_idx=round_idx,
                norm_sum=norm_sum, norm_max=norm_max,
                nova_sum=nova_sum,
            )
            return new_state, metrics, new_c

        x_spec = P(ax, None, self.seq_axis) if self.sp else P(ax)
        c_spec = P(ax) if self.scaffold else P()
        sel_spec = P(ax) if self.scaffold else P()
        sharded = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(), P(), x_spec, P(ax), P(ax), P(ax), sel_spec,
                      c_spec, P()),
            out_specs=(P(), P(), c_spec),
            axis_names=self._manual_axes(),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=self._donate_argnums())

    # ------------------------------------------------------------------
    # evaluation (held-out global test set, SURVEY.md §3d)
    # ------------------------------------------------------------------
    def _build_eval_fn(self):
        return make_eval_fn(
            self.eval_model.apply,
            self.dataset.x_test,
            self.dataset.y_test,
            batch=max(self.config.fed.batch_size, 64),
        )

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def _host_sample_cohort(self, round_idx: int):
        """Cohort selection on HOST — same key derivation and ranking as the
        in-program sampler, run eagerly so the scaffold path can gather the
        cohort's variate rows before dispatching the round.

        Returns ``(sel, rows)``: ``sel`` are the per-device-local slot
        indices the round program consumes; ``rows`` the absolute rows of
        the (interleaved) client-stacked arrays, for host gather/scatter.
        """
        r = jnp.asarray(round_idx, jnp.int32)
        counts = jnp.asarray(self.shards.counts)
        if self.mesh is None:
            if self.cohort_size < self.num_clients:
                skey = prng.sampling_key(self.base_key, r)
                sel = np.asarray(
                    _rank_cohort(skey, counts, self.cohort_size)
                ).astype(np.int32)
            else:
                sel = np.arange(self.num_clients, dtype=np.int32)
            return sel, sel
        D, cpd = self.clients_size, self.cohort_per_device
        L = self.num_clients // D
        skey = prng.sampling_key(self.base_key, r)
        sels, rows = [], []
        for d in range(D):
            if cpd < L:
                dkey = jax.random.fold_in(skey, d)
                s = np.asarray(
                    _rank_cohort(dkey, counts[d * L:(d + 1) * L], cpd)
                ).astype(np.int32)
            else:
                s = np.arange(L, dtype=np.int32)
            sels.append(s)
            rows.append(d * L + s)
        return np.concatenate(sels), np.concatenate(rows)

    def run_round(self, sync: bool = True) -> dict:
        """One federated round.  ``sync=False`` skips the host conversion of
        the round metrics (they stay as device scalars), so back-to-back
        rounds pipeline on the device with no host round-trip between them —
        one device→host sync per round otherwise costs a full RPC round-trip
        on remote-tunnel platforms.  (SCAFFOLD rounds still synchronize
        regardless: the cohort-resident variate gather/scatter is a
        per-round host⇄device exchange by design.)  Call
        :meth:`finalize_history` after a ``sync=False`` loop to materialize
        the floats."""
        r = len(self.history)
        if self.scaffold:
            # Gather the cohort's variates from the host store; scatter the
            # refreshed block back afterwards (device memory stays
            # O(cohort × model)).
            sel, rows = self._host_sample_cohort(r)
            c_cohort = jax.tree.map(lambda l: l[rows], self.client_c)
            sel_dev = jnp.asarray(sel)
            if self.mesh is not None:
                sh = NamedSharding(self.mesh, P(self.client_axis))
                sel_dev = jax.device_put(sel_dev, sh)
                c_cohort = jax.tree.map(
                    lambda l: jax.device_put(jnp.asarray(l), sh), c_cohort
                )
        else:
            sel, rows, sel_dev, c_cohort = None, None, None, None
        self.server_state, metrics, new_c = self._round_fn(
            self.server_state,
            self.base_key,
            jnp.asarray(r, jnp.int32),
            *self._device_data,
            sel_dev,
            c_cohort,
            self._dp_clip,
        )
        if self.adaptive_clip:
            # Feed the adapted clip into the next round as a device scalar
            # (no host round-trip; sync=False rounds keep pipelining).
            self._dp_clip = metrics["dp_clip"]
        if self.scaffold:
            updated = jax.tree.map(np.asarray, new_c)

            def scatter(full, upd):
                full[rows] = upd
                return full

            self.client_c = jax.tree.map(scatter, self.client_c, updated)
        if sync:
            # ONE batched device→host transfer for the whole metrics dict —
            # per-scalar float() would cost one RPC round-trip each on
            # remote-tunnel platforms (65 ms × n_metrics per round).
            out = {k: float(v) for k, v in jax.device_get(metrics).items()}
        else:
            out = dict(metrics)          # device scalars; sync deferred
        out["round"] = r
        if self.accountant is not None:
            self.accountant.step()
            out["dp_epsilon"] = self.accountant.epsilon()
            out["dp_delta"] = self.accountant.delta
        self.history.append(out)
        return out

    def finalize_history(self) -> list[dict]:
        """Materialize any deferred (``sync=False``) round metrics to floats
        — blocks until the device work that produced them is done.  The
        whole history is fetched in ONE batched transfer (sequential
        per-scalar reads would pay a full RPC round-trip each on
        remote-tunnel platforms)."""
        fetched = jax.device_get(self.history)
        self.history = [
            {k: (float(v) if hasattr(v, "dtype") else v)
             for k, v in rec.items()}
            for rec in fetched
        ]
        return self.history

    def evaluate(self) -> tuple[float, float]:
        loss, acc = self._eval_fn(self.server_state.params)
        return float(loss), float(acc)

    def evaluate_detection(self, benign_class: int = 0) -> dict:
        """Detection-oriented held-out report (per-class P/R/F1, macro-F1,
        alarm detection/false-alarm rates) — the metrics the reference's
        IoT anomaly deployment cares about, where accuracy alone hides an
        always-benign classifier.  One jit scan accumulating the global
        confusion matrix; host-side summarization
        (fed/evaluation.detection_report)."""
        if not hasattr(self, "_conf_eval_fn"):
            self._conf_eval_fn = make_confusion_eval_fn(
                self.eval_model.apply,
                self.dataset.x_test,
                self.dataset.y_test,
                batch=max(self.config.fed.batch_size, 64),
                num_classes=self.config.model.num_classes,
            )
        conf = np.asarray(self._conf_eval_fn(self.server_state.params))
        return detection_report(conf, benign_class=benign_class)

    # ---- federated (per-client) evaluation ---------------------------
    def evaluate_per_client(self) -> dict:
        """Score the CURRENT global model on every client's local shard.

        The reference's evaluator role scores one held-out set (SURVEY.md
        §3d); this is the federated-native complement — the model's fit to
        each client's own distribution, the quantity that matters under
        non-IID partitions.  One jit program, vmapped over clients (and
        sharded over the client axis on a mesh); returns per-client arrays
        in ORIGINAL client-id order plus weighted aggregates and the
        across-client accuracy spread.
        """
        if not hasattr(self, "_client_eval_fn"):
            self._client_eval_fn = self._build_client_eval_fn()
        loss, acc = self._client_eval_fn(
            self.server_state.params, *self._device_data[:3]
        )
        loss, acc = np.asarray(loss), np.asarray(acc)
        counts = np.asarray(self.shards.counts)
        # Undo the mesh interleaving, drop ghost clients.
        order = np.argsort(self.client_ids, kind="stable")
        loss, acc, counts = loss[order], acc[order], counts[order]
        real = counts > 0
        loss, acc, counts = loss[real], acc[real], counts[real]
        from colearn_federated_learning_tpu.fed.evaluation import (
            summarize_per_client,
        )

        out = summarize_per_client(loss, acc, counts)
        out.update(per_client_loss=loss, per_client_acc=acc,
                   num_examples=counts)
        return out

    # ---- client update similarity (clustered FL) ----------------------
    def client_update_similarity(self, steps: int = 1) -> np.ndarray:
        """(N, N) cosine similarity of every client's local update from
        the CURRENT global model — the clustering signal of clustered FL
        (fed/clustered.py): clients drawn from the same concept produce
        aligned updates, concept-shifted clients anti-align.

        One jit program: vmapped local steps over ALL clients, flatten,
        one gram matmul (MXU).  On the vmap path the (N, P) matrix never
        leaves the device.  On a mesh each device trains only ITS client
        block, L2-normalizes the (N/D, P) rows, all_gathers the
        normalized deltas over the client axis (robust aggregation pays
        the same O(N·P) price — order statistics and gram matrices are
        not psum-decomposable), computes its (N/D, N) strip of the gram
        on the MXU, and the strips reassemble to the sharded (N, N)
        output; rows/cols are then returned to ORIGINAL client-id order
        with ghost padding dropped.
        """
        if self.scaffold:
            raise NotImplementedError(
                "clustering uses the plain local trainer; run it with a "
                "stateless strategy"
            )
        if getattr(self, "_sim_key", None) != steps:
            self._sim_key = steps
            budget = jnp.asarray(min(steps, self.num_steps), jnp.int32)

            def flat_norm_deltas(params, x, y, counts, ids, key, n_rows):
                keys = jax.vmap(
                    lambda i: prng.client_round_key(key, i, 1 << 23)
                )(ids)
                budgets = jnp.full((n_rows,), budget, jnp.int32)
                res = jax.vmap(self.local_update,
                               in_axes=(None, 0, 0, 0, 0, 0))(
                    params, x, y, counts, keys, budgets
                )
                X = jnp.concatenate(
                    [l.reshape(n_rows, -1).astype(jnp.float32)
                     for l in jax.tree.leaves(res.delta)], axis=1,
                )
                return X / jnp.maximum(
                    jnp.linalg.norm(X, axis=1, keepdims=True), 1e-12
                )

            if self.mesh is None:
                def sim(params, x, y, counts, ids, key):
                    Xn = flat_norm_deltas(params, x, y, counts, ids, key,
                                          self.num_clients)
                    return Xn @ Xn.T

                self._sim_fn = jax.jit(sim)
            else:
                ax = self.client_axis
                local_clients = self.num_clients // self.clients_size

                def sim_body(params, x_blk, y_blk, counts_blk, ids_blk,
                             key):
                    Xn = flat_norm_deltas(params, x_blk, y_blk, counts_blk,
                                          ids_blk, key, local_clients)
                    x_all = jax.lax.all_gather(Xn, ax)
                    x_all = x_all.reshape(-1, Xn.shape[1])     # (N, P)
                    return Xn @ x_all.T                        # (N/D, N)

                x_spec = (P(ax, None, self.seq_axis) if self.sp
                          else P(ax))
                self._sim_fn = jax.jit(shard_map(
                    sim_body,
                    mesh=self.mesh,
                    in_specs=(P(), x_spec, P(ax), P(ax), P(ax), P()),
                    out_specs=P(ax, None),
                    axis_names=self._manual_axes(),
                    check_vma=False,
                ))
        sim = np.asarray(self._sim_fn(
            self.server_state.params, *self._device_data, self.base_key
        ))
        if self.mesh is not None:
            # Undo the mesh interleaving on BOTH axes; drop ghost padding.
            keep = self.id_order_slots()
            sim = sim[np.ix_(keep, keep)]
        return sim

    def id_order_slots(self) -> np.ndarray:
        """Array-slot index of every REAL client, in original client-id
        order — the inverse of the mesh interleaving with ghost padding
        dropped; the identity on the vmap path.

        Ghosts are identified by id (``id >= real_num_clients``: padding
        appends them after the real clients), NOT by ``counts == 0`` — a
        real client whose partition happens to be empty must keep its
        slot so per-id indexing (clustered FL labels) stays aligned
        across engine paths."""
        if self.mesh is None:
            return np.arange(self.num_clients)
        ids = np.asarray(self.client_ids)
        order = np.argsort(ids, kind="stable")
        return order[:self.real_num_clients]

    # ---- personalized evaluation (fine-tune-then-eval) ----------------
    def evaluate_personalized(self, steps: int = 5,
                              lr: Optional[float] = None) -> dict:
        """Per-client personalization probe: fine-tune the CURRENT global
        model on the first half of each client's shard for ``steps`` local
        SGD steps, then score BOTH the global and the personalized model on
        the held-out second half.  The spread between the two is the value
        personalization adds under this partition — the FedPer-style
        question the reference cannot ask (its evaluator scores one global
        holdout).  One jit program, vmapped over clients (sharded over the
        client axis on a mesh).

        Clients with fewer than 2 examples have no holdout half and are
        dropped from the aggregates.
        """
        key = (steps, lr)
        if getattr(self, "_pers_eval_key", None) != key:
            self._pers_eval_fn = self._build_personalized_eval_fn(
                steps, lr if lr is not None else self.config.fed.lr
            )
            self._pers_eval_key = key
        g_acc, p_acc, n_eval = self._pers_eval_fn(
            self.server_state.params, *self._device_data
        )
        g_acc, p_acc = np.asarray(g_acc), np.asarray(p_acc)
        n_eval = np.asarray(n_eval)
        order = np.argsort(self.client_ids, kind="stable")
        g_acc, p_acc, n_eval = g_acc[order], p_acc[order], n_eval[order]
        real = n_eval > 0
        g_acc, p_acc, n_eval = g_acc[real], p_acc[real], n_eval[real]
        if n_eval.sum() == 0:
            # No client holds the >= 2 examples a holdout half needs.
            return {
                "global_acc": 0.0, "personalized_acc": 0.0,
                "personalization_gain": 0.0,
                "per_client_global_acc": g_acc,
                "per_client_personalized_acc": p_acc,
                "num_eval_examples": n_eval,
                "num_clients_evaluated": 0,
            }
        w = n_eval / n_eval.sum()
        return {
            "global_acc": float((g_acc * w).sum()),
            "personalized_acc": float((p_acc * w).sum()),
            "personalization_gain": float(((p_acc - g_acc) * w).sum()),
            "per_client_global_acc": g_acc,
            "per_client_personalized_acc": p_acc,
            "num_eval_examples": n_eval,
            "num_clients_evaluated": int(real.sum()),
        }

    def _build_personalized_eval_fn(self, steps: int, lr: float):
        import dataclasses

        c = self.config
        apply_fn = (self.model if self.sp else self.eval_model).apply
        # The fine-tune is the CONFIG's local trainer (same optimizer,
        # momentum, MoE aux loss, prox term) with the step budget and lr
        # overridden — setup_lib keeps the wiring identical to training.
        ft_config = c.replace(fed=dataclasses.replace(
            c.fed,
            strategy=c.fed.strategy if c.fed.strategy == "fedprox" else "fedavg",
            local_steps=steps, lr=lr, straggler_prob=0.0,
        ))
        update, _ = setup_lib.local_trainer_for_config(
            ft_config, apply_fn, self.shards.capacity,
            grad_sync_axes=(self.seq_axis,) if self.sp else (),
        )
        budget = jnp.asarray(steps, jnp.int32)
        batch = max(c.fed.batch_size, 64)
        cap = self.shards.capacity
        n_chunks = int(np.ceil(cap / batch))
        padded = n_chunks * batch

        def score(params, cx, cy, lo, hi):
            """Mean accuracy over shard rows [lo, hi), scanned in
            batch-sized chunks (bounded activation memory, same scheme as
            _build_client_eval_fn)."""
            pad = padded - cap
            cxp = jnp.concatenate(
                [cx, jnp.zeros((pad,) + cx.shape[1:], cx.dtype)]
            ) if pad else cx
            cyp = jnp.concatenate([cy, jnp.zeros((pad,), cy.dtype)]) if pad else cy
            xb = cxp.reshape((n_chunks, batch) + cx.shape[1:])
            yb = cyp.reshape((n_chunks, batch))
            base = jnp.arange(n_chunks) * batch

            def chunk(carry, inp):
                x_, y_, b = inp
                logits = apply_fn({"params": params}, x_, train=False)
                correct = (jnp.argmax(logits, axis=-1) == y_).astype(jnp.float32)
                rows = b + jnp.arange(batch)
                m = ((rows >= lo) & (rows < hi)).astype(jnp.float32)
                a, n = carry
                return (a + jnp.sum(correct * m), n + jnp.sum(m)), None

            (a, n), _ = jax.lax.scan(chunk, (0.0, 0.0), (xb, yb, base))
            return a / jnp.maximum(n, 1.0)

        def one_client(params, cx, cy, count, gid):
            n_ft = count // 2                       # fine-tune half
            n_eval = jnp.where(count >= 2, count - n_ft, 0)
            # Purpose-distinct key: round index past any training round.
            key = prng.client_round_key(
                self.base_key, gid, jnp.asarray(1 << 24, jnp.int32)
            )
            res = update(params, cx, cy, jnp.maximum(n_ft, 1), key, budget)
            pers = pytrees.tree_add(params, res.delta)
            g_acc = score(params, cx, cy, n_ft, count)
            p_acc = score(pers, cx, cy, n_ft, count)
            return g_acc, p_acc, n_eval

        vmapped = jax.vmap(one_client, in_axes=(None, 0, 0, 0, 0))
        if self.mesh is None:
            return jax.jit(vmapped)
        ax = self.client_axis
        x_spec = P(ax, None, self.seq_axis) if self.sp else P(ax)
        return jax.jit(shard_map(
            vmapped, mesh=self.mesh,
            in_specs=(P(), x_spec, P(ax), P(ax), P(ax)),
            out_specs=(P(ax), P(ax), P(ax)),
            axis_names=self._manual_axes(),
            check_vma=False,
        ))

    def _build_client_eval_fn(self):
        batch = max(self.config.fed.batch_size, 64)
        cap = self.shards.capacity
        n_chunks = int(np.ceil(cap / batch))
        padded = n_chunks * batch
        # Under SP the shard data arrives sequence-sharded, so the eval
        # must run the ring-attention (SP-aware) module, not the dense twin.
        apply_fn = (self.model if self.sp else self.eval_model).apply

        def one_client(params, cx, cy, count):
            # Pad the shard to whole chunks; only rows < count score.
            pad = padded - cap
            cxp = jnp.concatenate(
                [cx, jnp.zeros((pad,) + cx.shape[1:], cx.dtype)]
            ) if pad else cx
            cyp = jnp.concatenate([cy, jnp.zeros((pad,), cy.dtype)]) if pad else cy
            xb = cxp.reshape((n_chunks, batch) + cx.shape[1:])
            yb = cyp.reshape((n_chunks, batch))
            base = jnp.arange(n_chunks) * batch

            def step(carry, inp):
                x_, y_, b = inp
                logits = apply_fn({"params": params}, x_, train=False)
                ce = jax.nn.log_softmax(logits.astype(jnp.float32))
                nll = -jnp.take_along_axis(ce, y_[:, None], axis=1)[:, 0]
                correct = (jnp.argmax(logits, axis=-1) == y_).astype(jnp.float32)
                m = ((b + jnp.arange(batch)) < count).astype(jnp.float32)
                l, a, n = carry
                return (l + jnp.sum(nll * m), a + jnp.sum(correct * m),
                        n + jnp.sum(m)), None

            (l, a, n), _ = jax.lax.scan(step, (0.0, 0.0, 0.0), (xb, yb, base))
            n = jnp.maximum(n, 1.0)
            return l / n, a / n

        vmapped = jax.vmap(one_client, in_axes=(None, 0, 0, 0))
        if self.mesh is None:
            return jax.jit(vmapped)

        ax = self.client_axis
        x_spec = P(ax, None, self.seq_axis) if self.sp else P(ax)
        return jax.jit(shard_map(
            vmapped, mesh=self.mesh,
            in_specs=(P(), x_spec, P(ax), P(ax)),
            out_specs=(P(ax), P(ax)),
            axis_names=self._manual_axes(),
            check_vma=False,
        ))

    # ---- checkpoint/resume (SURVEY.md §5; ckpt/manager.py) -----------
    def _checkpointer(self):
        if self._ckpt is None:
            from colearn_federated_learning_tpu.ckpt import RoundCheckpointer

            self._ckpt = RoundCheckpointer.for_run(self.config.run)
        return self._ckpt

    def save_checkpoint(self) -> None:
        # Scaffold's per-client variates are part of the training state and
        # checkpoint alongside the server state (None otherwise).
        self._checkpointer().save(
            len(self.history), (self.server_state, self.client_c), self.history
        )

    def restore_checkpoint(self) -> int:
        """Restore the latest checkpoint; returns the resumed round index."""
        state, history, step = self._checkpointer().restore(
            (self.server_state, self.client_c)
        )
        self.server_state, self.client_c = state
        self.history = history
        if self.accountant is not None:
            # ε must account for every round already spent before the kill.
            self.accountant.steps = step
        if self.adaptive_clip and history:
            # The clip state rides the per-round metrics (one scalar per
            # record), so resume continues from the adapted norm.
            self._dp_clip = jnp.float32(history[-1]["dp_clip"])
        return step

    def fit(self, rounds: Optional[int] = None, log_fn=None) -> list[dict]:
        """Run ``rounds`` more federated rounds.  ``rounds=None`` means "up
        to the configured total": after a restore at round k, the default
        runs the REMAINING config.fed.rounds - k rounds, not a fresh full
        run."""
        if rounds is None:
            rounds = max(0, self.config.fed.rounds - len(self.history))
        run = self.config.run
        eval_every = max(1, run.eval_every)
        log_every = max(1, run.log_every)
        ckpt_every = max(0, run.checkpoint_every)
        want_ckpt = bool(run.checkpoint_dir)
        last_round = len(self.history) + rounds - 1  # fit() may be called again
        from colearn_federated_learning_tpu.utils.profiling import RoundProfiler

        profiler = RoundProfiler(run.profile_dir)
        try:
            for _ in range(rounds):
                t0 = time.perf_counter()
                profiler.before_round(len(self.history))
                rec = self.run_round()
                if profiler._active:
                    # The trace window must contain the round's device work —
                    # only synchronise while actually tracing (blocking every
                    # round would serialise the async dispatch pipeline).
                    jax.block_until_ready(self.server_state.params)
                profiler.after_round(rec["round"])
                rec["round_time_s"] = time.perf_counter() - t0
                if rec["round"] % eval_every == 0 or rec["round"] == last_round:
                    loss, acc = self.evaluate()
                    rec["eval_loss"], rec["eval_acc"] = loss, acc
                if log_fn is not None and (
                    rec["round"] % log_every == 0 or rec["round"] == last_round
                ):
                    log_fn(rec)
                # With a checkpoint_dir, the final round ALWAYS checkpoints
                # even when no periodic cadence is configured, so --resume
                # works.
                if want_ckpt and (
                    (ckpt_every and (rec["round"] + 1) % ckpt_every == 0)
                    or rec["round"] == last_round
                ):
                    self.save_checkpoint()
        finally:
            # An exception mid-window (eval/log/ckpt) must not leave the
            # process-global jax profiler trace running.
            profiler.close()
        return self.history
