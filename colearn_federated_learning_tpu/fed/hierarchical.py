"""Hierarchical (edge → cloud) federation — HierFAVG-style two-tier rounds.

CoLearn's deployment picture is IoT devices behind edge gateways; the
reference still aggregates FLAT (every device talks to the one
coordinator, SURVEY.md §3a).  This module adds the two-tier topology
(Liu et al. 1905.06641, client-edge-cloud pattern only): each EDGE GROUP
runs full federated rounds over its own client population — reusing the
jit round engine unchanged, one ``FederatedLearner`` per group — and every
``sync_period`` rounds the edge models average into the cloud model
(weighted by group example counts), which re-seeds every group.

Communication shape this buys at the edge: devices talk only to their
gateway every round; the WAN link carries one model per group every
``sync_period`` rounds — a 1/sync_period cut of the reference's
cloud-bound traffic.

Scope: cloud sync averages PARAMS, so the strategies whose server state is
exactly params (fedavg / fedprox) are supported; adaptive server
optimizers keep per-group moments that a param average would silently
desynchronise, and scaffold's variates live per-client — both are
rejected loudly.

Secure aggregation composes GROUP-LOCALLY here (DisAgg-style): each edge
group is its own ``FederatedLearner`` over ``clients_per_group`` clients,
so with ``fed.secure_agg`` on, pair masks (and the dropout-recovery share
fan-outs, privacy/dropout.py) span only the group — the per-device mask
cost is O(group + neighbors) instead of O(cohort), and the system-wide
pair count drops from O(cohort²) to O(cohort · group).  The cloud tier
averages already-unmasked group means, exactly like the plain path.
:meth:`HierarchicalLearner.mask_cost_summary` quantifies the cut.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.faults import fileplane, inject
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.fed.evaluation import make_eval_fn
from colearn_federated_learning_tpu.telemetry import registry as _metrics
from colearn_federated_learning_tpu.utils import pytrees
from colearn_federated_learning_tpu.utils.config import ExperimentConfig


class HierarchicalLearner:
    """Two-tier federated simulation (see module docstring).

    ``num_groups`` edge groups each own a disjoint contiguous shard of the
    training corpus and ``num_clients // num_groups`` clients, partitioned
    within the group by the config's scheme (iid / dirichlet) — each edge
    domain is its own population, which is exactly the non-IID structure
    hierarchical FL exists for.
    """

    def __init__(self, config: ExperimentConfig, num_groups: int = 2,
                 sync_period: int = 2):
        if num_groups < 2:
            raise ValueError(f"num_groups must be >= 2, got {num_groups}")
        if sync_period < 1:
            raise ValueError(f"sync_period must be >= 1, got {sync_period}")
        if config.fed.strategy not in ("fedavg", "fedprox"):
            raise ValueError(
                "hierarchical sync averages params; strategy "
                f"{config.fed.strategy!r} carries extra server state "
                "(moments/variates) a param average would desynchronise"
            )
        self.config = config
        self.num_groups = num_groups
        self.sync_period = sync_period

        if config.data.num_clients % num_groups:
            raise ValueError(
                f"num_clients={config.data.num_clients} is not divisible "
                f"by num_groups={num_groups}; remainder clients would be "
                "silently dropped while their data still lands in a group"
            )
        base = data_registry.get_dataset(config.data.dataset,
                                         seed=config.run.seed)
        self.dataset = base        # registry branch visibility (disk/synth)
        n = len(base.y_train)
        clients_per_group = config.data.num_clients // num_groups
        self.groups: list[FederatedLearner] = []
        self.group_examples: list[int] = []
        for g in range(num_groups):
            lo = g * n // num_groups
            hi = (g + 1) * n // num_groups
            ds = dataclasses.replace(
                base,
                x_train=base.x_train[lo:hi], y_train=base.y_train[lo:hi],
            )
            gcfg = config.replace(
                data=dataclasses.replace(config.data,
                                         num_clients=clients_per_group),
                run=dataclasses.replace(
                    config.run, name=f"{config.run.name}_edge{g}",
                    # Distinct seeds de-correlate group cohort sampling /
                    # client PRNG streams (client ids restart at 0 in
                    # every group).
                    seed=config.run.seed * num_groups + g,
                ),
            )
            # from_config resolves --backend and lays any client mesh,
            # exactly like the flat path.
            self.groups.append(FederatedLearner.from_config(gcfg, dataset=ds))
            self.group_examples.append(int(np.asarray(ds.y_train).size))

        # Cloud model: start every group from the SAME init (group 0's).
        self.global_params = self.groups[0].params
        # Cloud aggregation as ONE jit program: eager per-leaf tree math
        # would pay a remote dispatch per op on tunnel-attached TPUs.
        import jax

        w = np.asarray(self.group_examples, np.float64)
        ws = tuple(float(x) for x in (w / w.sum()))

        @jax.jit
        def _sync(group_params):
            acc = pytrees.tree_scale(group_params[0], ws[0])
            for wi, p in zip(ws[1:], group_params[1:]):
                acc = pytrees.tree_add(acc, pytrees.tree_scale(p, wi))
            return acc

        self._sync_fn = _sync
        self._seed_groups()
        self._eval_fn = make_eval_fn(
            self.groups[0].eval_model.apply, base.x_test, base.y_test,
            batch=max(config.fed.batch_size, 64),
        )
        self.history: list[dict] = []

    # ------------------------------------------------------------------
    def _seed_groups(self, round_idx: Optional[int] = None) -> None:
        faulted = inject.active_plan() is not None
        for i, g in enumerate(self.groups):
            if faulted and fileplane.should_drop(f"g{i}", round_idx,
                                                 fileplane.HOP_SEED):
                # Cloud→edge downlink lost: the group keeps training from
                # its own stale model until the next successful sync.
                continue
            g.server_state = g.server_state._replace(
                params=self.global_params
            )

    def _cloud_sync(self, round_idx: Optional[int] = None) -> list[str]:
        """Cloud aggregation: example-count-weighted mean of edge models.

        Under an installed FaultPlan, ``drop_silo`` specs keyed by group
        (``g0``, ``g1``, ...) on hop ``sync`` lose that group's uplink:
        the cloud mean renormalizes over the survivors (eager fallback —
        the jit path assumes the full fixed-weight cohort).  Returns the
        dropped group idents."""
        if inject.active_plan() is None:
            self.global_params = self._sync_fn(
                tuple(g.server_state.params for g in self.groups)
            )
            self._seed_groups()
            return []
        dropped: list[str] = []
        alive: list[tuple[float, object]] = []
        for i, g in enumerate(self.groups):
            ident = f"g{i}"
            if fileplane.should_drop(ident, round_idx, fileplane.HOP_SYNC):
                dropped.append(ident)
                _metrics.get_registry().counter(
                    "fed.hier_groups_dropped_total",
                    labels={"group": ident}).inc()
                continue
            alive.append((float(self.group_examples[i]), g.server_state.params))
        if alive:
            total = sum(w for w, _ in alive)
            acc = pytrees.tree_scale(alive[0][1], alive[0][0] / total)
            for w, p in alive[1:]:
                acc = pytrees.tree_add(acc, pytrees.tree_scale(p, w / total))
            self.global_params = acc
        # else: every uplink lost — the cloud model simply stays stale.
        self._seed_groups(round_idx)
        return dropped

    def run_round(self) -> dict:
        """One edge round in EVERY group; cloud sync on period boundaries."""
        r = len(self.history)
        recs = [g.run_round() for g in self.groups]
        synced = (r + 1) % self.sync_period == 0
        dropped: list[str] = []
        if synced:
            dropped = self._cloud_sync(r)
        out = {
            "round": r,
            "synced": synced,
            "train_loss": float(np.mean([x["train_loss"] for x in recs])),
            "completed": float(np.sum([x["completed"] for x in recs])),
            "group_losses": [float(x["train_loss"]) for x in recs],
        }
        if dropped:
            out["groups_dropped"] = dropped
        self.history.append(out)
        return out

    def mask_cost_summary(self) -> dict:
        """Per-device secure-agg cost of THIS topology vs the flat one.

        Pure arithmetic on :func:`privacy.dropout.mask_cost` — no masking
        has to be enabled to ask.  ``quadratic_ratio`` is the system-wide
        pair-count cut the two-tier topology buys (flat O(cohort²) pairs
        over grouped O(cohort · group)); bench_fleet's ``--mask-sweep``
        reports the same columns at the 1M-device point."""
        from colearn_federated_learning_tpu.privacy import dropout

        cohort = self.config.data.num_clients
        group = cohort // self.num_groups
        cost = dropout.mask_cost(
            cohort=cohort,
            param_count=pytrees.tree_size(self.global_params),
            neighbors=self.config.fed.secure_agg_neighbors,
            group_size=group,
        )
        cost["num_groups"] = self.num_groups
        cost["group_size"] = group
        cost["quadratic_ratio"] = (
            cost["flat_pairs_total"] / max(1, cost["grouped_pairs_total"])
        )
        return cost

    def evaluate(self) -> tuple[float, float]:
        """Cloud-model score on the global holdout.  Between syncs the
        cloud model is the LAST synced one; call after a sync boundary for
        the freshest aggregate."""
        loss, acc = self._eval_fn(self.global_params)
        return float(loss), float(acc)

    def fit(self, rounds: Optional[int] = None, log_fn=None) -> list[dict]:
        rounds = rounds if rounds is not None else self.config.fed.rounds
        run = self.config.run
        last_round = len(self.history) + rounds - 1
        for _ in range(rounds):
            rec = self.run_round()
            if rec["round"] == last_round and not rec["synced"]:
                # Terminal sync (standard HierFAVG): the reported final
                # model must fold the groups' last partial period, not a
                # stale cloud aggregate.
                dropped = self._cloud_sync(rec["round"])
                rec["synced"] = True
                if dropped:
                    rec["groups_dropped"] = dropped
            if rec["synced"]:
                loss, acc = self.evaluate()
                rec["eval_loss"], rec["eval_acc"] = loss, acc
            if log_fn is not None and (
                rec["round"] % max(1, run.log_every) == 0
                or rec["round"] == last_round
            ):
                log_fn(rec)
        return self.history


# ---- tree-async secure-agg groundwork (per-buffer mask cohorts) ----------
def buffer_mask_cohorts(assignment: dict, pruned=()) -> dict:
    """Per-buffer mask cohorts for the tree-async plane.

    ``assignment`` maps device id -> aggregator id (the async root's
    slice assignment).  Pairwise masks only cancel within a COMPLETE
    sum, and in tree-async mode each aggregator's buffer is folded (and
    staleness-discounted) as its own partial — so a mask pair must never
    span two buffers.  Each buffer therefore becomes its own pairing
    cohort, exactly the group-local math :meth:`HierarchicalLearner
    .mask_cost_summary` prices for the edge tier.

    ``pruned`` devices are excluded from the pair graph UP FRONT: a
    pruned client is a *predicted* dropout — the root pauses its pump
    before mask setup, it never commits a mask, and its absence costs
    zero share recoveries.  (A *reactive* dropout — a device that masks
    and then dies mid-buffer — costs its ``degree`` share recoveries,
    as on the sync plane.)

    Returns ``agg_id -> sorted device-id list`` (deterministic cohort
    order: the mask PRG seeds key off pair order).
    """
    cut = {str(d) for d in pruned}
    out: dict = {}
    for dev, aid in assignment.items():
        if str(dev) in cut:
            continue
        out.setdefault(aid, []).append(str(dev))
    return {aid: sorted(devs, key=str) for aid, devs in sorted(out.items())}


def async_mask_cost(assignment: dict, param_count: int,
                    neighbors: int = 0, pruned=()) -> dict:
    """Analytic secure-agg cost of the per-buffer cohort layout.

    Prices what :func:`buffer_mask_cohorts` buys: per-buffer pair
    degrees (each device's masks span only its buffer), the predicted-
    dropout accounting (pruned devices cost ZERO recoveries because
    they are excluded before mask commitment), and the per-buffer
    reactive-recovery bill a mid-buffer death would cost instead."""
    from colearn_federated_learning_tpu.privacy import dropout

    cohorts = buffer_mask_cohorts(assignment, pruned=pruned)
    active = sum(len(devs) for devs in cohorts.values())
    per_buffer: dict = {}
    pairs_total = 0
    for aid, devs in cohorts.items():
        if not devs:
            continue
        cost = dropout.mask_cost(
            cohort=max(1, active), param_count=param_count,
            neighbors=neighbors, group_size=len(devs))
        degree = cost["pairs_per_device"]
        per_buffer[aid] = {
            "devices": len(devs),
            "pairs_per_device": degree,
            "mask_flops_per_device": cost["mask_flops_per_device"],
            # What ONE reactive (mid-buffer) dropout in this buffer
            # would cost: its degree's worth of share recoveries.
            "reactive_recovery_shares": degree,
        }
        pairs_total += len(devs) * degree // 2
    predicted = sum(1 for d in assignment if str(d) in
                    {str(p) for p in pruned})
    return {
        "buffers": per_buffer,
        "active_devices": active,
        "pairs_total": pairs_total,
        "predicted_dropouts": predicted,
        # The headline: a predicted dropout never masked, so it costs
        # nothing to recover from — unlike a reactive one.
        "predicted_recovery_shares": 0,
    }
