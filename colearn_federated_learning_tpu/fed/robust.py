"""Byzantine-robust aggregation: coordinate-wise median / trimmed mean.

The reference's aggregator is a plain weighted mean (SURVEY.md §2
``fed_avg(weights, sizes)``) — a single malicious or faulty IoT device can
steer it arbitrarily.  These robust statistics bound that influence
(Yin et al. 1803.01498, coordinate-wise median/trimmed-mean — pattern
only): up to ⌊(n-1)/2⌋ (median) or ⌊trim·n⌋ (trimmed mean) corrupted
clients per coordinate are tolerated.

TPU-native shape: the whole cohort's deltas are already STACKED on the
leading axis (the engine vmaps clients), so each statistic is one
``jnp.sort`` over that axis per leaf — static shapes, no host round-trip.
Contributor masking (ghost padding, dropped stragglers) is handled by
pushing masked rows to the sort's tail as NaN and indexing with the
dynamic contributor count.  On a mesh the engine all-gathers the stacked
deltas over the client axis first (robust statistics are not
psum-decomposable), so device memory is O(cohort × model) during the
aggregation — the price of order statistics over the full cohort.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATORS = ("mean", "median", "trimmed_mean", "krum")


def _median_leaf(xs: jax.Array, n_valid: jax.Array) -> jax.Array:
    """Median over the leading axis of ``xs`` (pre-sorted, NaNs last),
    among the first ``n_valid`` rows."""
    hi = jnp.maximum(n_valid, 1) // 2
    lo = jnp.maximum(n_valid - 1, 0) // 2
    pair = jnp.take(xs, jnp.stack([lo, hi]), axis=0)   # dynamic gather
    return 0.5 * (pair[0] + pair[1])


def _trimmed_leaf(xs: jax.Array, n_valid: jax.Array,
                  trim_fraction: float) -> jax.Array:
    """Mean of the sorted rows [k, n_valid - k), k = floor(trim·n_valid).
    The epsilon guards float32 products that are exactly integral in
    exact arithmetic (e.g. 0.45 · 20) from rounding DOWN a trim."""
    k = jnp.floor(trim_fraction * n_valid + 1e-4).astype(jnp.int32)
    # Runtime dropouts can shrink n_valid below 1/trim_fraction, which
    # would silently degrade the "robust" statistic to a plain mean for
    # that round.  Whenever the caller asked for ANY trimming and at
    # least 3 contributors remain, trim at least one row per side.
    if trim_fraction > 0.0:
        k = jnp.where(n_valid >= 3, jnp.maximum(k, 1), k)
    idx = jnp.arange(xs.shape[0])
    sel = (idx >= k) & (idx < n_valid - k)
    selb = sel.reshape((-1,) + (1,) * (xs.ndim - 1))
    kept = jnp.where(selb, jnp.where(jnp.isnan(xs), 0.0, xs), 0.0)
    count = jnp.maximum(jnp.sum(sel), 1)
    return jnp.sum(kept, axis=0) / count


def _krum(stacked, maskb, n_valid, byz_fraction: float):
    """Multi-Krum (Blanchard et al. 1703.02757, pattern only): score each
    update by the sum of its ``n_valid − f − 2`` smallest squared
    distances to other updates, select the ``n_valid − f`` best-scored,
    average them.  ``f = floor(byz_fraction · n_valid)``.

    Distance work is one gram matmul over the flattened cohort matrix —
    (n, P)·(P, n) lands on the MXU; everything else is (n, n)-sized.
    """
    leaves = jax.tree.leaves(stacked)
    X = jnp.concatenate(
        [l.reshape(l.shape[0], -1).astype(jnp.float32) for l in leaves],
        axis=1,
    )                                                   # (n, P)
    # Rows with ANY nonfinite entry are excluded by construction (score
    # forced to inf below): a valid-but-diverged or inf-submitting client
    # must never be selected, and a masked straggler's NaN garbage must
    # not leak.  The matrix itself is then sanitized so 0·NaN / 0·inf
    # cannot poison the distance or selection matmuls.
    row_bad = ~jnp.all(jnp.isfinite(X), axis=1)         # (n,)
    X = jnp.where(jnp.isfinite(X), X, 0.0)
    n = X.shape[0]
    # A nonfinite submitter is excluded EVERYWHERE: its zero-sanitized row
    # must not act as anyone's nearest neighbor either (it would shrink
    # small-norm clients' scores and shift the selection cutoff).
    ok = maskb & ~row_bad
    mf = ok.astype(jnp.float32)
    sq = jnp.sum(X * X, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (X @ X.T)    # (n, n)
    inf = jnp.float32(3e38)
    invalid = (1.0 - mf[:, None]) + (1.0 - mf[None, :])
    d2 = jnp.where((invalid > 0) | jnp.eye(n, dtype=bool), inf, d2)
    d2 = jnp.maximum(d2, 0.0)                           # gram round-off

    f = jnp.floor(byz_fraction * n_valid + 1e-4).astype(jnp.int32)
    # Same straggler hazard as the trimmed mean: a shrunken runtime
    # n_valid must not round the assumed Byzantine count down to 0 (that
    # would select ALL n_valid rows — plain mean).  Assume at least one
    # attacker whenever the caller configured a nonzero fraction and
    # enough contributors remain to exclude one.
    if byz_fraction > 0.0:
        f = jnp.where(n_valid >= 3, jnp.maximum(f, 1), f)
    k_nb = jnp.maximum(n_valid - f - 2, 1)              # neighbors scored
    d2s = jnp.sort(d2, axis=1)                          # inf sorts last
    nb_mask = (jnp.arange(n)[None, :] < k_nb).astype(jnp.float32)
    # CLAMP huge distances rather than zeroing by value comparison: an
    # attacker whose magnitudes overflow float32 would otherwise score 0
    # (every neighbor distance "invalid") and be SELECTED — clamped, its
    # astronomically bad score excludes it like any far outlier.
    scores = jnp.sum(jnp.minimum(d2s, 1e30) * nb_mask, axis=1)
    scores = jnp.where(ok & ~jnp.isnan(scores), scores, jnp.inf)

    m_sel = jnp.maximum(n_valid - f, 1)                 # multi-Krum size
    order = jnp.argsort(scores)
    rank = jnp.argsort(order)
    # Never average in an excluded (inf-score) row, even when fewer than
    # m_sel rows survive the exclusions.
    sel = ((rank < m_sel) & maskb & jnp.isfinite(scores)).astype(jnp.float32)
    mean_flat = (sel @ X) / jnp.maximum(jnp.sum(sel), 1.0)

    out, off = [], 0
    for l in leaves:
        size = int(np.prod(l.shape[1:])) if l.ndim > 1 else 1
        out.append(mean_flat[off:off + size].reshape(l.shape[1:]))
        off += size
    return jax.tree.unflatten(jax.tree.structure(stacked), out)


def robust_aggregate(stacked, mask, method: str,
                     trim_fraction: float = 0.1):
    """Aggregate client deltas robustly.

    Args:
      stacked: pytree whose leaves carry clients on axis 0.
      mask: (n,) bool/float — True for rows that actually contributed
        (real, non-straggler clients).
      method: "median" | "trimmed_mean" | "krum".
      trim_fraction: per-side trim for "trimmed_mean"; the assumed
        Byzantine FRACTION f/n for "krum".

    Returns the aggregated delta pytree (float32 leaves); all-zero when no
    row contributed (the engine's no-op-round convention).
    """
    if method not in AGGREGATORS[1:]:
        raise ValueError(f"unknown robust aggregator {method!r}; "
                         f"use one of {AGGREGATORS[1:]}")
    if not 0.0 <= trim_fraction < 0.5:
        # >= 0.5 trims everything (a silent all-zero aggregate); negative
        # trims would count phantom rows into the mean.
        raise ValueError(
            f"trim_fraction must be in [0, 0.5), got {trim_fraction}"
        )
    maskb = mask.astype(bool)
    n_valid = jnp.sum(maskb.astype(jnp.int32))

    if method == "krum":
        out = _krum(stacked, maskb, n_valid, trim_fraction)
        return jax.tree.map(
            lambda x: jnp.where(n_valid > 0, x, 0.0), out
        )

    def leaf(x):
        m = maskb.reshape((-1,) + (1,) * (x.ndim - 1))
        xf = jnp.where(m, x.astype(jnp.float32), jnp.nan)
        xs = jnp.sort(xf, axis=0)                     # NaNs sort last
        if method == "median":
            out = _median_leaf(xs, n_valid)
        else:
            out = _trimmed_leaf(xs, n_valid, trim_fraction)
        return jnp.where(n_valid > 0, out, 0.0)

    return jax.tree.map(leaf, stacked)
