"""Held-out evaluation (SURVEY.md §3d evaluator role), standalone.

One jit-compiled scan over padded test batches — shared by the engine's
periodic eval and the file-based evaluator (`colearn eval`), which needs no
training setup at all.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def _pad_batches(x_test, y_test, batch: int):
    """(xb, yb, mb) device arrays: the test set padded to whole
    ``batch``-sized chunks with a validity mask — static shapes, shared
    by every eval builder in this module."""
    x_test = np.asarray(x_test)
    y_test = np.asarray(y_test)
    n = len(x_test)
    n_batches = int(np.ceil(n / batch))
    pad = n_batches * batch - n
    x_pad = np.concatenate(
        [x_test, np.zeros((pad,) + x_test.shape[1:], x_test.dtype)])
    y_pad = np.concatenate([y_test, np.zeros((pad,), y_test.dtype)])
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    xb = jnp.asarray(x_pad.reshape((n_batches, batch) + x_test.shape[1:]))
    yb = jnp.asarray(y_pad.reshape((n_batches, batch)))
    mb = jnp.asarray(mask.reshape((n_batches, batch)))
    return xb, yb, mb


def make_eval_fn(apply_fn: Callable, x_test, y_test, batch: int) -> Callable:
    """Build ``eval_fn(params) -> (mean_loss, accuracy)`` over the test set,
    reduced in a single ``lax.scan`` — one compile."""
    xb, yb, mb = _pad_batches(x_test, y_test, batch)

    @jax.jit
    def eval_fn(params):
        def step(carry, inp):
            x, y, m = inp
            logits = apply_fn({"params": params}, x, train=False)
            ce = jax.nn.log_softmax(logits.astype(jnp.float32))
            nll = -jnp.take_along_axis(ce, y[:, None], axis=1)[:, 0]
            correct = (jnp.argmax(logits, axis=-1) == y).astype(jnp.float32)
            loss_sum, acc_sum, m_sum = carry
            return (
                loss_sum + jnp.sum(nll * m),
                acc_sum + jnp.sum(correct * m),
                m_sum + jnp.sum(m),
            ), None

        (loss_sum, acc_sum, m_sum), _ = jax.lax.scan(
            step, (0.0, 0.0, 0.0), (xb, yb, mb)
        )
        return loss_sum / m_sum, acc_sum / m_sum

    return eval_fn


def make_confusion_eval_fn(apply_fn: Callable, x_test, y_test, batch: int,
                           num_classes: int) -> Callable:
    """Build ``fn(params) -> (C, C) confusion matrix`` (rows = true class,
    cols = prediction) over the test set — same padded-scan structure as
    :func:`make_eval_fn`, accumulating one scatter-add per batch."""
    xb, yb, mb = _pad_batches(x_test, y_test, batch)
    C = num_classes

    @jax.jit
    def conf_fn(params):
        def step(conf, inp):
            x, y, m = inp
            logits = apply_fn({"params": params}, x, train=False)
            pred = jnp.argmax(logits, axis=-1)
            flat = y.astype(jnp.int32) * C + pred.astype(jnp.int32)
            return conf.at[flat].add(m), None

        conf, _ = jax.lax.scan(step, jnp.zeros(C * C, jnp.float32),
                               (xb, yb, mb))
        return conf.reshape(C, C)

    return conf_fn


def detection_report(conf: np.ndarray, benign_class: int = 0) -> dict:
    """Detection-oriented metrics from a confusion matrix — the quantities
    the reference's IoT network-anomaly deployment actually cares about
    (SURVEY.md §0: MUD-compliant edge anomaly detection), where plain
    accuracy hides a useless always-benign classifier:

    - per-class precision/recall/F1 + macro-F1;
    - binary ALARM view (any non-benign prediction is an alarm):
      ``detection_rate`` = P(alarm | attack), ``false_alarm_rate`` =
      P(alarm | benign).
    """
    conf = np.asarray(conf, np.float64)
    C = conf.shape[0]
    tp = np.diag(conf)
    support = conf.sum(axis=1)
    predicted = conf.sum(axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        precision = np.where(predicted > 0, tp / predicted, 0.0)
        recall = np.where(support > 0, tp / support, 0.0)
        f1 = np.where(precision + recall > 0,
                      2 * precision * recall / (precision + recall), 0.0)
    attack = np.arange(C) != benign_class
    attack_total = conf[attack].sum()
    benign_total = conf[benign_class].sum()
    alarms_on_attack = conf[attack][:, attack].sum()
    alarms_on_benign = conf[benign_class, attack].sum()
    return {
        "accuracy": float(tp.sum() / max(conf.sum(), 1.0)),
        "per_class_precision": precision,
        "per_class_recall": recall,
        "per_class_f1": f1,
        "macro_f1": float(f1[support > 0].mean()) if (support > 0).any()
        else 0.0,
        "detection_rate": float(alarms_on_attack / max(attack_total, 1.0)),
        "false_alarm_rate": float(alarms_on_benign / max(benign_total, 1.0)),
        "support": support,
    }


def sanitize_report(rep: dict) -> dict:
    """JSON-ready copy of a metrics report (numpy arrays -> lists) — the
    ONE serialization rule shared by every report printer (CLI stderr
    dumps, file-plane eval records)."""
    return {k: (v.tolist() if hasattr(v, "tolist") else v)
            for k, v in rep.items()}


def summarize_per_client(losses, accs, counts) -> dict:
    """Example-weighted aggregates + accuracy spread over per-client
    scores — ONE definition shared by the engine's vmapped per-client
    eval and the socket coordinator's wire-plane fan-out."""
    import numpy as np

    losses = np.asarray(losses, np.float64)
    accs = np.asarray(accs, np.float64)
    counts = np.asarray(counts, np.float64)
    w = counts / counts.sum()
    return {
        "weighted_loss": float((losses * w).sum()),
        "weighted_acc": float((accs * w).sum()),
        "acc_p10": float(np.percentile(accs, 10)),
        "acc_p50": float(np.percentile(accs, 50)),
        "acc_p90": float(np.percentile(accs, 90)),
    }
