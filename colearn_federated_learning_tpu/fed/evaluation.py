"""Held-out evaluation (SURVEY.md §3d evaluator role), standalone.

One jit-compiled scan over padded test batches — shared by the engine's
periodic eval and the file-based evaluator (`colearn eval`), which needs no
training setup at all.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def make_eval_fn(apply_fn: Callable, x_test, y_test, batch: int) -> Callable:
    """Build ``eval_fn(params) -> (mean_loss, accuracy)`` over the test set.

    The set is padded to a whole number of ``batch``-sized chunks with a
    validity mask, then reduced in a single ``lax.scan`` — static shapes,
    one compile.
    """
    x_test = np.asarray(x_test)
    y_test = np.asarray(y_test)
    n = len(x_test)
    n_batches = int(np.ceil(n / batch))
    pad = n_batches * batch - n
    x_pad = np.concatenate([x_test, np.zeros((pad,) + x_test.shape[1:], x_test.dtype)])
    y_pad = np.concatenate([y_test, np.zeros((pad,), y_test.dtype)])
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    xb = jnp.asarray(x_pad.reshape((n_batches, batch) + x_test.shape[1:]))
    yb = jnp.asarray(y_pad.reshape((n_batches, batch)))
    mb = jnp.asarray(mask.reshape((n_batches, batch)))

    @jax.jit
    def eval_fn(params):
        def step(carry, inp):
            x, y, m = inp
            logits = apply_fn({"params": params}, x, train=False)
            ce = jax.nn.log_softmax(logits.astype(jnp.float32))
            nll = -jnp.take_along_axis(ce, y[:, None], axis=1)[:, 0]
            correct = (jnp.argmax(logits, axis=-1) == y).astype(jnp.float32)
            loss_sum, acc_sum, m_sum = carry
            return (
                loss_sum + jnp.sum(nll * m),
                acc_sum + jnp.sum(correct * m),
                m_sum + jnp.sum(m),
            ), None

        (loss_sum, acc_sum, m_sum), _ = jax.lax.scan(
            step, (0.0, 0.0, 0.0), (xb, yb, mb)
        )
        return loss_sum / m_sum, acc_sum / m_sum

    return eval_fn


def summarize_per_client(losses, accs, counts) -> dict:
    """Example-weighted aggregates + accuracy spread over per-client
    scores — ONE definition shared by the engine's vmapped per-client
    eval and the socket coordinator's wire-plane fan-out."""
    import numpy as np

    losses = np.asarray(losses, np.float64)
    accs = np.asarray(accs, np.float64)
    counts = np.asarray(counts, np.float64)
    w = counts / counts.sum()
    return {
        "weighted_loss": float((losses * w).sum()),
        "weighted_acc": float((accs * w).sum()),
        "acc_p10": float(np.percentile(accs, 10)),
        "acc_p50": float(np.percentile(accs, 50)),
        "acc_p90": float(np.percentile(accs, 90)),
    }
