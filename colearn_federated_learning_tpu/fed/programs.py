"""Jit program construction for the federated engine (fed/engine.py).

Everything that BUILDS a compiled program lives here; the engine keeps
orchestration (data placement, host-side cohort bookkeeping, the public
API).  Extracted from the 1,400-line engine in round 5 (VERDICT r4 weak
#6) with no behavior change — the functions take the learner (``ln``)
and read the same attributes the former methods read off ``self``.

Shared interface of the two round-program builders: both return a jitted
function with the SAME signature

    round_fn(server_state, key, round_idx, x, y, counts, ids,
             sel, c_cohort, clip) -> (new_state, metrics, new_cohort_c)

- vmap path (``ln.mesh is None``): clients are a vmap axis; aggregation
  is a weighted tree-sum on one device.
- mesh path: clients are a manual shard_map axis over
  ``ln.mesh`` and aggregation lowers to ``jax.lax.psum`` over ICI
  (BASELINE.json north_star); a ``model`` (TP) axis, when present, is
  left to the automatic partitioner, and a ``seq`` axis carries the
  ring/Ulysses sequence-parallel collectives inside the model.

The per-cohort body (``cohort_step``) and the round epilogue
(``finish_round``) are shared verbatim between the two paths — the mesh
builder only adds the cross-device psums between them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from colearn_federated_learning_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.privacy import dp as dp_lib
from colearn_federated_learning_tpu.privacy import secure_agg as sa_lib
from colearn_federated_learning_tpu.utils import prng, pytrees


def rank_cohort(skey, counts, k):
    """Uniform sample of ``k`` clients WITHOUT replacement among real
    clients: ghosts (count 0) are pushed to the end of the ranking and only
    picked if the cohort exceeds real clients.  Pure jnp — the SAME function
    runs traced inside the round program (fedavg paths) and eagerly on host
    (the scaffold path, which must know the cohort before dispatch to gather
    its variate rows; fleetsim's host sampler too); any edit applies to
    all of them.  Public: engine.py and fleetsim/sim.py import it."""
    scores = jax.random.uniform(skey, counts.shape)
    scores = scores + (counts == 0) * 1e3
    return jnp.argsort(scores)[:k]


# Back-compat alias for the historical private name.
_rank_cohort = rank_cohort


def manual_axes(ln) -> frozenset:
    """Mesh axes the round shard_map is MANUAL over: clients (+ seq
    under SP).  A ``model`` (TP) axis stays out of the set, so the
    automatic partitioner handles it — params arrive sharded over it
    (parallel/tp.py) and XLA inserts the tensor-parallel collectives."""
    axes = {ln.client_axis}
    if ln.sp:
        axes.add(ln.seq_axis)
    return frozenset(axes)


def donate_argnums(ln) -> tuple[int, ...]:
    """Donate the consumed round state (server_state, cohort variate
    block) so XLA reuses their HBM in place — matters for big models.
    CPU ignores donation with a warning, so skip."""
    devs = ln.mesh.devices.flat if ln.mesh is not None else jax.devices()
    first = next(iter(devs))
    return () if first.platform == "cpu" else (0, 8)


def cohort_step(ln, params, local_ids, global_ids, mask_cohort_ids,
                x, y, counts, key, round_idx,
                control=None, c_blk=None, clip=None):
    """Shared per-cohort logic: local training + privacy + weighting.

    ``local_ids`` index into the (possibly per-device) ``x/y/counts``
    blocks; ``global_ids`` are the mesh-wide client identities used for
    PRNG derivation, so results are bit-identical regardless of how
    clients are placed on devices.  ``mask_cohort_ids`` is the FULL
    round cohort (all devices) that secure-agg masks pair against.
    ``control`` / ``c_blk`` are the scaffold global variate and the
    COHORT-ALIGNED block of per-client variates (one row per cohort
    slot, gathered host-side from the full store before the call).
    Returns (weighted_delta_sum, total_weight, metrics, scaffold_extras)
    — the caller finishes aggregation either locally (vmap path) or
    with a psum (shard_map path); ``scaffold_extras`` is None or
    ``(delta_c_uniform_sum, n_contributors, updated_cohort_block)``.
    """
    c = ln.config.fed
    cx = jnp.take(x, local_ids, axis=0)
    cy = jnp.take(y, local_ids, axis=0)
    ccounts = jnp.take(counts, local_ids, axis=0)

    # Per-(client, round) keys: placement-independent determinism.
    keys = jax.vmap(lambda i: prng.client_round_key(key, i, round_idx))(global_ids)

    # Straggler simulation: each cohort slot draws a per-CLIENT budget
    # (keyed on global id, so placement-independent).
    if c.straggler_prob > 0.0:
        skey = prng.straggler_key(key, round_idx)

        def budget_for(i):
            k = jax.random.fold_in(skey, i)
            slow = jax.random.bernoulli(k, c.straggler_prob)
            frac = jax.random.uniform(jax.random.fold_in(k, 1))
            return jnp.where(
                slow, (frac * ln.num_steps).astype(jnp.int32), ln.num_steps
            )

        budgets = jax.vmap(budget_for)(global_ids)
    else:
        budgets = jnp.full((ln.cohort_size_local,), ln.num_steps, jnp.int32)

    # Round-level client-lr schedule factor, computed in-graph from
    # the round operand (no retrace, no host sync).
    lr_scale = strategies.lr_scale_for_round(c, round_idx)

    if ln.scaffold:
        c_i = c_blk                      # already one row per cohort slot
        sres = jax.vmap(
            ln.local_update,
            in_axes=(None, 0, 0, 0, 0, 0, 0, None, None),
        )(params, cx, cy, ccounts, keys, budgets, c_i, control, lr_scale)
        results = sres.result
    else:
        sres = None
        results = jax.vmap(
            ln.local_update, in_axes=(None, 0, 0, 0, 0, 0, None)
        )(params, cx, cy, ccounts, keys, budgets, lr_scale)
    deltas = results.delta
    completed = results.completed
    nova_a = None
    if ln.fednova:
        # FedNova (Wang et al., pattern only): normalize each delta by
        # its effective local-step coefficient a_i, so heterogeneous
        # step counts (straggler budgets!) stop biasing the objective;
        # the round epilogue rescales the mean by the weighted mean a.
        m = c.momentum
        tau = jnp.maximum(results.steps_run, 1.0)
        if m > 0.0:
            nova_a = (tau - m * (1.0 - m ** tau) / (1.0 - m)) / (1.0 - m)
        else:
            nova_a = tau
        deltas = jax.vmap(
            lambda d, a: pytrees.tree_scale(d, 1.0 / a)
        )(deltas, nova_a)
    # Round telemetry: per-client update norms (the quantity operators
    # tune dp_clip against).  ONLY for non-private plain runs — under
    # DP the exact un-noised norms are an unaccounted release (the
    # adaptive path pays for even a 1-bit norm query), and under
    # secure-agg they are precisely what the masks exist to hide.
    track_norms = not (c.dp_clip > 0.0 or c.secure_agg)
    if track_norms:
        norms = jax.vmap(pytrees.tree_global_norm)(deltas)

    # SCAFFOLD averages uniformly over the sampled cohort (the variate
    # algebra assumes it); DP/secure-agg force uniform weights too.
    uniform_weights = (c.dp_clip > 0.0 or c.secure_agg or ln.scaffold
                       or ln.robust)
    bits = None
    if c.dp_clip > 0.0:
        dp_keys = jax.vmap(lambda i: prng.dp_key(key, i, round_idx))(global_ids)
        if ln.adaptive_clip:
            # Traced clip scalar + per-client quantile bit (pre-clip
            # norm <= clip), update noise at the inflated multiplier.
            deltas, bits = jax.vmap(
                lambda d, k: dp_lib.clip_and_noise_with_bit(
                    d, clip, ln.dp_z, ln.dp_cohort, k
                )
            )(deltas, dp_keys)
        else:
            deltas = jax.vmap(
                lambda d, k: dp_lib.clip_and_noise(
                    d, c.dp_clip, c.dp_noise_multiplier, ln.dp_cohort, k
                )
            )(deltas, dp_keys)

    nonghost = (results.num_examples > 0)
    # The ONE contributor mask (real, non-straggler) every aggregation
    # branch and metric below derives from.
    contrib = completed & nonghost
    if uniform_weights:
        weights = contrib.astype(jnp.float32)
    else:
        weights = results.num_examples.astype(jnp.float32) * contrib

    sa_bit_sum = None
    if c.secure_agg:
        # Clients pre-scale by their weight, then add pairwise masks;
        # masks cancel in the plain SUM over the cohort.  Masks pair
        # GLOBAL ids, so cancellation holds across devices too (the
        # final sum is the psum over the mesh).
        wdeltas = jax.vmap(lambda d, w: pytrees.tree_scale(d, w))(deltas, weights)
        # The per-round pairing graph (ring permutation or complete
        # graph) is computed ONCE here, not per vmap lane — each lane
        # then does only O(partners) PRG work.
        partners = sa_lib.partner_table(
            key, global_ids, mask_cohort_ids, round_idx,
            neighbors=c.secure_agg_neighbors,
        )
        masked = jax.vmap(
            lambda d, i, prt: sa_lib.mask_update(d, key, i, prt,
                                                 round_idx)
        )(wdeltas, global_ids, partners)
        wsum = jax.tree.map(lambda l: jnp.sum(l, axis=0), masked)
        if bits is not None:
            # Adaptive clipping under secure-agg: the quantile bit is a
            # second payload — mask it on its own pair stream so only
            # the cohort SUM is visible, like the deltas (the
            # contribution weighting is folded in pre-mask).
            # std ≫ 1: a unit-scale mask on a {0,1} payload would leak
            # the bit with constant statistical advantage; at 1e3 the
            # float32 cancellation residual (~1e-7·std·√cohort) is
            # still far below the O(cohort) bit sum.
            masked_bits = jax.vmap(
                lambda b, i, prt: sa_lib.mask_scalar(b, key, i, prt,
                                                     round_idx, std=1e3)
            )(bits * contrib.astype(jnp.float32), global_ids, partners)
            sa_bit_sum = jnp.sum(masked_bits)
    elif ln.robust:
        # Coordinate-wise robust statistic over the FULL cohort
        # (fed/robust.py).  Order statistics are not psum-decomposable,
        # so on a mesh the stacked deltas are all-gathered over the
        # client axis first and the aggregate comes out replicated —
        # the round epilogue uses it directly (no psum, no division).
        from colearn_federated_learning_tpu.fed.robust import (
            robust_aggregate,
        )

        if ln.mesh is not None:
            ax = ln.client_axis
            all_deltas = jax.tree.map(
                lambda l: jax.lax.all_gather(l, ax, axis=0, tiled=True),
                deltas,
            )
            all_contrib = jax.lax.all_gather(contrib, ax, axis=0,
                                             tiled=True)
        else:
            all_deltas, all_contrib = deltas, contrib
        wsum = robust_aggregate(all_deltas, all_contrib,
                                c.aggregator, c.trim_fraction)
    else:
        wsum = pytrees.tree_weighted_sum(deltas, weights)

    total_w = jnp.sum(weights)
    loss_sum = jnp.sum(results.mean_loss * weights)
    # "completed" reports real contributors only (ghost padding slots
    # always finish their budget but never contribute).
    n_completed = jnp.sum(contrib.astype(jnp.int32))
    # Quantile-bit sum over CONTRIBUTORS (the clip adapts to the norms
    # that actually entered the aggregate).  Under secure-agg the
    # masked sum computed above stands in (cancellation ⇒ same value
    # up to float32 residual).
    if sa_bit_sum is not None:
        bit_sum = sa_bit_sum
    elif bits is not None:
        bit_sum = jnp.sum(bits * contrib.astype(jnp.float32))
    else:
        bit_sum = jnp.zeros((), jnp.float32)
    if track_norms:
        cf = contrib.astype(jnp.float32)
        norm_sum = jnp.sum(norms * cf)
        norm_max = jnp.max(norms * cf)
    else:
        norm_sum = norm_max = jnp.zeros((), jnp.float32)
    # FedNova: weighted sum of the a_i coefficients — the epilogue's
    # mean rescale factor is nova_sum / total_w.
    nova_sum = (
        jnp.sum(weights * nova_a)
        if nova_a is not None else jnp.zeros((), jnp.float32)
    )

    extras = None
    if ln.scaffold:
        uw = contrib.astype(jnp.float32)
        dc_sum = pytrees.tree_weighted_sum(sres.delta_c, uw)
        # Refresh only contributors' variates; non-contributor rows keep
        # their old values.  The caller scatters this cohort block back
        # into the host-resident full store.
        c_masked = jax.tree.map(
            lambda new, old: jnp.where(
                contrib.reshape((-1,) + (1,) * (new.ndim - 1)), new, old
            ),
            sres.c_new, c_i,
        )
        extras = (dc_sum, n_completed.astype(jnp.float32), c_masked)
    return (wsum, total_w,
            (loss_sum, n_completed, bit_sum, norm_sum, norm_max,
             nova_sum), extras)


def finish_round(ln, server_state, wsum, total_w, loss_sum, n_comp,
                 dc_sum=None, n_contrib=None, bit_sum=None, clip=None,
                 key=None, round_idx=None, norm_sum=None,
                 norm_max=None, nova_sum=None):
    """Shared round epilogue (vmap and shard_map paths): mean delta,
    server update, metrics.  Zero contributors (all stragglers) → no-op
    update; the explicit gate matters under secure_agg, where wsum is
    not exactly zero but the float32 mask-cancellation residual."""
    denom = jnp.where(total_w > 0, total_w, 1.0)
    if ln.robust:
        # wsum IS the robust aggregate (zero when nobody contributed);
        # total_w only normalizes the loss metric below.
        mean_delta = wsum
    else:
        mean_delta = pytrees.tree_scale(
            wsum, jnp.where(total_w > 0, 1.0 / denom, 0.0)
        )
    if ln.fednova and nova_sum is not None:
        # Rescale the mean of NORMALIZED deltas by the weighted-mean
        # step coefficient (tau_eff), completing d = tau_eff * mean.
        mean_delta = pytrees.tree_scale(mean_delta, nova_sum / denom)
    mean_delta_c = participation = None
    if ln.scaffold:
        safe_n = jnp.maximum(n_contrib, 1.0)
        mean_delta_c = pytrees.tree_scale(
            dc_sum, jnp.where(n_contrib > 0, 1.0 / safe_n, 0.0)
        )
        participation = n_contrib / float(ln.real_num_clients)
    new_state = strategies.server_update(server_state, mean_delta,
                                         ln.config.fed,
                                         mean_delta_c=mean_delta_c,
                                         participation=participation)
    metrics = {
        "train_loss": loss_sum / denom,
        "completed": n_comp,
        "total_weight": total_w,
    }
    track_norms = not (ln.config.fed.dp_clip > 0.0
                       or ln.config.fed.secure_agg)
    if norm_sum is not None and track_norms:
        safe_n = jnp.maximum(n_comp.astype(jnp.float32), 1.0)
        metrics["delta_norm_mean"] = norm_sum / safe_n
        metrics["delta_norm_max"] = norm_max
    if ln.adaptive_clip:
        # Noised quantile fraction -> geometric clip step.  In the
        # shard_map path this runs replicated AFTER the psums: every
        # device derives the identical noise from the shared key, so
        # the updated clip stays replicated.
        c = ln.config.fed
        bnoise = (
            ln.dp_bit_noise
            * jax.random.normal(prng.clip_bit_key(key, round_idx), ())
            if ln.dp_bit_noise > 0.0 else 0.0
        )
        frac = jnp.clip(
            (bit_sum + bnoise)
            / jnp.maximum(n_comp.astype(jnp.float32), 1.0),
            0.0, 1.0,
        )
        new_clip = dp_lib.adaptive_clip_update(
            clip, frac, c.dp_target_quantile, c.dp_clip_lr
        )
        # A zero-contributor round (all stragglers) carries no norm
        # evidence: freeze the clip like the server update freezes.
        new_clip = jnp.where(n_comp > 0, new_clip, clip)
        metrics["dp_clip"] = jnp.maximum(new_clip, 1e-6)
        metrics["dp_bit_frac"] = frac
    return new_state, metrics


def _build_vmap_round(ln):
    """Single-device path: clients are a vmap axis inside cohort_step."""

    def round_fn(server_state, key, round_idx, x, y, counts, ids,
                 sel_in, c_cohort, clip_in):
        if ln.scaffold:
            # Cohort-resident variates: the cohort was sampled on
            # host (so its variate rows could be gathered) and
            # arrives as an operand.
            sel = sel_in
        else:
            skey = prng.sampling_key(key, round_idx)
            if ln.cohort_size < ln.num_clients:
                sel = rank_cohort(skey, counts, ln.cohort_size)
            else:
                sel = jnp.arange(ln.num_clients)
        cohort_global = jnp.take(ids, sel)
        wsum, total_w, stats, extras = cohort_step(
            ln, server_state.params, sel, cohort_global,
            cohort_global, x, y, counts, key, round_idx,
            control=server_state.control, c_blk=c_cohort,
            clip=clip_in,
        )
        (loss_sum, n_comp, bit_sum, norm_sum, norm_max,
         nova_sum) = stats
        dc_sum, n_contrib, new_c = (
            extras if extras is not None else (None, None, None)
        )
        new_state, metrics = finish_round(
            ln, server_state, wsum, total_w, loss_sum, n_comp,
            dc_sum=dc_sum, n_contrib=n_contrib, bit_sum=bit_sum,
            clip=clip_in, key=key, round_idx=round_idx,
            norm_sum=norm_sum, norm_max=norm_max,
            nova_sum=nova_sum,
        )
        return new_state, metrics, new_c

    return jax.jit(round_fn, donate_argnums=donate_argnums(ln))


def _build_mesh_round(ln):
    """Multi-chip path: shard_map over the client axis (and, under SP,
    the sequence axis — every collective below names ONLY the client
    axis, so the ring collectives inside the model stay on ``seq``)."""
    mesh = ln.mesh
    ax = ln.client_axis
    local_clients = ln.num_clients // ln.clients_size

    def body(server_state, key, round_idx, x_blk, y_blk, counts_blk,
             ids_blk, sel_blk, c_blk, clip_in):
        if ln.scaffold:
            sel = sel_blk            # host-sampled (cohort-resident c)
        else:
            dev = jax.lax.axis_index(ax)
            skey = jax.random.fold_in(
                prng.sampling_key(key, round_idx), dev
            )
            if ln.cohort_per_device < local_clients:
                # This device's slice of the cohort among its REAL
                # clients (interleaved placement spreads reals evenly).
                sel = rank_cohort(skey, counts_blk,
                                   ln.cohort_per_device)
            else:
                sel = jnp.arange(local_clients)
        cohort_global = jnp.take(ids_blk, sel)
        # Secure-agg masks pair against the FULL mesh-wide cohort: a
        # cheap all_gather of the (cohort_per_device,) id vectors.
        mask_cohort = jax.lax.all_gather(cohort_global, ax).reshape(-1)
        wsum, total_w, stats, extras = cohort_step(
            ln, server_state.params, sel, cohort_global, mask_cohort,
            x_blk, y_blk, counts_blk, key, round_idx,
            control=server_state.control, c_blk=c_blk, clip=clip_in,
        )
        (loss_sum, n_comp, bit_sum, norm_sum, norm_max,
         nova_sum) = stats
        # FedAvg across the pod: one psum over ICI per leaf.  (Robust
        # aggregates are already global+replicated — no psum.)
        if not ln.robust:
            wsum = jax.tree.map(lambda l: jax.lax.psum(l, ax), wsum)
        total_w = jax.lax.psum(total_w, ax)
        loss_sum = jax.lax.psum(loss_sum, ax)
        n_comp = jax.lax.psum(n_comp, ax)
        bit_sum = jax.lax.psum(bit_sum, ax)
        norm_sum = jax.lax.psum(norm_sum, ax)
        norm_max = jax.lax.pmax(norm_max, ax)
        nova_sum = jax.lax.psum(nova_sum, ax)
        if extras is not None:
            dc_sum, n_contrib, new_c = extras
            dc_sum = jax.tree.map(lambda l: jax.lax.psum(l, ax), dc_sum)
            n_contrib = jax.lax.psum(n_contrib, ax)
        else:
            dc_sum, n_contrib, new_c = None, None, None
        new_state, metrics = finish_round(
            ln, server_state, wsum, total_w, loss_sum, n_comp,
            dc_sum=dc_sum, n_contrib=n_contrib, bit_sum=bit_sum,
            clip=clip_in, key=key, round_idx=round_idx,
            norm_sum=norm_sum, norm_max=norm_max,
            nova_sum=nova_sum,
        )
        return new_state, metrics, new_c

    x_spec = P(ax, None, ln.seq_axis) if ln.sp else P(ax)
    c_spec = P(ax) if ln.scaffold else P()
    sel_spec = P(ax) if ln.scaffold else P()
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(), P(), x_spec, P(ax), P(ax), P(ax), sel_spec,
                  c_spec, P()),
        out_specs=(P(), P(), c_spec),
        axis_names=manual_axes(ln),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donate_argnums(ln))


def build_round_fn(ln):
    """The one entry the engine calls: dispatch on mesh presence; both
    builders honor the shared signature documented in the module
    docstring (``ln.cohort_size_local`` is set by the engine before the
    call — cohort_size on the vmap path, cohort_per_device on the mesh
    path)."""
    return _build_vmap_round(ln) if ln.mesh is None else _build_mesh_round(ln)


# ---------------------------------------------------------------------
# per-client programs (eval / personalization / similarity)
# ---------------------------------------------------------------------
def build_client_eval_fn(ln):
    """Per-client (loss, acc) of the CURRENT global params on each
    client's own shard — vmapped, sharded over the client axis on a
    mesh.  Chunked scan bounds activation memory."""
    batch = max(ln.config.fed.batch_size, 64)
    cap = ln.shards.capacity
    n_chunks = int(np.ceil(cap / batch))
    padded = n_chunks * batch
    # Under SP the shard data arrives sequence-sharded, so the eval
    # must run the ring-attention (SP-aware) module, not the dense twin.
    apply_fn = (ln.model if ln.sp else ln.eval_model).apply

    def one_client(params, cx, cy, count):
        # Pad the shard to whole chunks; only rows < count score.
        pad = padded - cap
        cxp = jnp.concatenate(
            [cx, jnp.zeros((pad,) + cx.shape[1:], cx.dtype)]
        ) if pad else cx
        cyp = jnp.concatenate([cy, jnp.zeros((pad,), cy.dtype)]) if pad else cy
        xb = cxp.reshape((n_chunks, batch) + cx.shape[1:])
        yb = cyp.reshape((n_chunks, batch))
        base = jnp.arange(n_chunks) * batch

        def step(carry, inp):
            x_, y_, b = inp
            logits = apply_fn({"params": params}, x_, train=False)
            ce = jax.nn.log_softmax(logits.astype(jnp.float32))
            nll = -jnp.take_along_axis(ce, y_[:, None], axis=1)[:, 0]
            correct = (jnp.argmax(logits, axis=-1) == y_).astype(jnp.float32)
            m = ((b + jnp.arange(batch)) < count).astype(jnp.float32)
            l, a, n = carry
            return (l + jnp.sum(nll * m), a + jnp.sum(correct * m),
                    n + jnp.sum(m)), None

        (l, a, n), _ = jax.lax.scan(step, (0.0, 0.0, 0.0), (xb, yb, base))
        n = jnp.maximum(n, 1.0)
        return l / n, a / n

    vmapped = jax.vmap(one_client, in_axes=(None, 0, 0, 0))
    if ln.mesh is None:
        return jax.jit(vmapped)

    ax = ln.client_axis
    x_spec = P(ax, None, ln.seq_axis) if ln.sp else P(ax)
    return jax.jit(shard_map(
        vmapped, mesh=ln.mesh,
        in_specs=(P(), x_spec, P(ax), P(ax)),
        out_specs=(P(ax), P(ax)),
        axis_names=manual_axes(ln),
        check_vma=False,
    ))


def build_personalized_eval_fn(ln, steps: int, lr: float):
    """Fine-tune-then-eval probe: ``steps`` local SGD steps on the first
    half of each client's shard, score global vs personalized params on
    the second half (fed/engine.evaluate_personalized)."""
    import dataclasses

    from colearn_federated_learning_tpu.fed import setup as setup_lib

    c = ln.config
    apply_fn = (ln.model if ln.sp else ln.eval_model).apply
    # The fine-tune is the CONFIG's local trainer (same optimizer,
    # momentum, MoE aux loss, prox term) with the step budget and lr
    # overridden — setup_lib keeps the wiring identical to training.
    ft_config = c.replace(fed=dataclasses.replace(
        c.fed,
        strategy=c.fed.strategy if c.fed.strategy == "fedprox" else "fedavg",
        local_steps=steps, lr=lr, straggler_prob=0.0,
    ))
    update, _ = setup_lib.local_trainer_for_config(
        ft_config, apply_fn, ln.shards.capacity,
        grad_sync_axes=(ln.seq_axis,) if ln.sp else (),
    )
    budget = jnp.asarray(steps, jnp.int32)
    batch = max(c.fed.batch_size, 64)
    cap = ln.shards.capacity
    n_chunks = int(np.ceil(cap / batch))
    padded = n_chunks * batch

    def score(params, cx, cy, lo, hi):
        """Mean accuracy over shard rows [lo, hi), scanned in
        batch-sized chunks (bounded activation memory, same scheme as
        build_client_eval_fn)."""
        pad = padded - cap
        cxp = jnp.concatenate(
            [cx, jnp.zeros((pad,) + cx.shape[1:], cx.dtype)]
        ) if pad else cx
        cyp = jnp.concatenate([cy, jnp.zeros((pad,), cy.dtype)]) if pad else cy
        xb = cxp.reshape((n_chunks, batch) + cx.shape[1:])
        yb = cyp.reshape((n_chunks, batch))
        base = jnp.arange(n_chunks) * batch

        def chunk(carry, inp):
            x_, y_, b = inp
            logits = apply_fn({"params": params}, x_, train=False)
            correct = (jnp.argmax(logits, axis=-1) == y_).astype(jnp.float32)
            rows = b + jnp.arange(batch)
            m = ((rows >= lo) & (rows < hi)).astype(jnp.float32)
            a, n = carry
            return (a + jnp.sum(correct * m), n + jnp.sum(m)), None

        (a, n), _ = jax.lax.scan(chunk, (0.0, 0.0), (xb, yb, base))
        return a / jnp.maximum(n, 1.0)

    def one_client(params, cx, cy, count, gid):
        n_ft = count // 2                       # fine-tune half
        n_eval = jnp.where(count >= 2, count - n_ft, 0)
        # Purpose-distinct key: round index past any training round.
        key = prng.client_round_key(
            ln.base_key, gid, jnp.asarray(1 << 24, jnp.int32)
        )
        res = update(params, cx, cy, jnp.maximum(n_ft, 1), key, budget)
        pers = pytrees.tree_add(params, res.delta)
        g_acc = score(params, cx, cy, n_ft, count)
        p_acc = score(pers, cx, cy, n_ft, count)
        return g_acc, p_acc, n_eval

    vmapped = jax.vmap(one_client, in_axes=(None, 0, 0, 0, 0))
    if ln.mesh is None:
        return jax.jit(vmapped)
    ax = ln.client_axis
    x_spec = P(ax, None, ln.seq_axis) if ln.sp else P(ax)
    return jax.jit(shard_map(
        vmapped, mesh=ln.mesh,
        in_specs=(P(), x_spec, P(ax), P(ax), P(ax)),
        out_specs=(P(ax), P(ax), P(ax)),
        axis_names=manual_axes(ln),
        check_vma=False,
    ))


def build_similarity_fn(ln, steps: int):
    """(N, N) cosine-similarity program over every client's local update
    (clustered FL signal; fed/engine.client_update_similarity documents
    the mesh strategy — all_gather the normalized deltas, per-device gram
    strips on the MXU)."""
    budget = jnp.asarray(min(steps, ln.num_steps), jnp.int32)

    def flat_norm_deltas(params, x, y, counts, ids, key, n_rows):
        keys = jax.vmap(
            lambda i: prng.client_round_key(key, i, 1 << 23)
        )(ids)
        budgets = jnp.full((n_rows,), budget, jnp.int32)
        res = jax.vmap(ln.local_update,
                       in_axes=(None, 0, 0, 0, 0, 0))(
            params, x, y, counts, keys, budgets
        )
        X = jnp.concatenate(
            [l.reshape(n_rows, -1).astype(jnp.float32)
             for l in jax.tree.leaves(res.delta)], axis=1,
        )
        return X / jnp.maximum(
            jnp.linalg.norm(X, axis=1, keepdims=True), 1e-12
        )

    if ln.mesh is None:
        def sim(params, x, y, counts, ids, key):
            Xn = flat_norm_deltas(params, x, y, counts, ids, key,
                                  ln.num_clients)
            return Xn @ Xn.T

        return jax.jit(sim)

    ax = ln.client_axis
    local_clients = ln.num_clients // ln.clients_size

    def sim_body(params, x_blk, y_blk, counts_blk, ids_blk, key):
        Xn = flat_norm_deltas(params, x_blk, y_blk, counts_blk,
                              ids_blk, key, local_clients)
        x_all = jax.lax.all_gather(Xn, ax)
        x_all = x_all.reshape(-1, Xn.shape[1])     # (N, P)
        return Xn @ x_all.T                        # (N/D, N)

    x_spec = (P(ax, None, ln.seq_axis) if ln.sp
              else P(ax))
    return jax.jit(shard_map(
        sim_body,
        mesh=ln.mesh,
        in_specs=(P(), x_spec, P(ax), P(ax), P(ax), P()),
        out_specs=P(ax, None),
        axis_names=manual_axes(ln),
        check_vma=False,
    ))
