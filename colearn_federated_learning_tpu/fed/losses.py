"""Loss and metric functions (float32 accumulation regardless of model dtype)."""

from __future__ import annotations

import jax.numpy as jnp
import optax


def softmax_cross_entropy(logits, labels) -> jnp.ndarray:
    """Mean cross-entropy; logits (B, K) float32, labels (B,) int."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


def accuracy(logits, labels) -> jnp.ndarray:
    return (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32).mean()
