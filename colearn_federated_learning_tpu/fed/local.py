"""Jit-compiled local training: one client's whole round as a single lax.scan.

The reference's hot loop is a Python ``for epoch: for batch:`` PyTorch loop
inside each PySyft worker process (SURVEY.md §3c).  Here the entire local
round — E epochs of minibatch SGD, optionally with a FedProx proximal term —
is one ``lax.scan`` over steps, compiled once and then ``vmap``-ed over the
client axis (single chip) or ``shard_map``-ed over a mesh (multi chip), per
BASELINE.json ``north_star`` ("each TPU core simulates one client running
jit-compiled local SGD").

Straggler handling (SURVEY.md §5 "failure detection"): the scan always runs
the full static step count, but each client carries a ``step_budget``; steps
past the budget are masked to no-ops with ``jnp.where``, so a straggler's
partial progress exists but its FedAvg weight is zeroed by the engine when
the budget falls below the completion threshold.  Shapes stay static — no
recompilation per round (SURVEY.md §7 hard part #2).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from colearn_federated_learning_tpu.fed import losses
from colearn_federated_learning_tpu.utils import pytrees


class LocalResult(NamedTuple):
    delta: Any               # params pytree: local_params - global_params
    num_examples: jnp.ndarray  # () int32 — true shard size (FedAvg weight)
    completed: jnp.ndarray     # () bool — ran >= min required steps
    mean_loss: jnp.ndarray     # () float32 over executed steps
    steps_run: jnp.ndarray     # () float32 — executed step count (FedNova
                               # normalizes by it; varies under stragglers)


class ScaffoldResult(NamedTuple):
    result: LocalResult
    c_new: Any               # this client's updated control variate
    delta_c: Any             # c_new - c_old (server control update)


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def make_optimizer(lr: float, momentum: float,
                   name: str = "sgd") -> optax.GradientTransformation:
    """Client-side optimizer.

    ``sgd``: plain SGD(+momentum) matching torch semantics: buf = m*buf + g;
    p -= lr*buf (optax ``trace`` with nesterov=False, SURVEY.md §7 hard
    part #4 — optimizer parity with the reference's PyTorch SGD).
    ``adam`` / ``adamw``: adaptive local optimizers (common for the text
    configs; the reference's workers run whatever torch.optim they choose).
    """
    if name == "sgd":
        if momentum > 0:
            return optax.sgd(lr, momentum=momentum, nesterov=False)
        return optax.sgd(lr)
    if name == "adam":
        return optax.adam(lr)
    if name == "adamw":
        return optax.adamw(lr)
    raise ValueError(f"unknown local optimizer {name!r} (sgd|adam|adamw)")


def _sown_aux_mean(intermediates) -> jnp.ndarray | None:
    """Mean of all ``moe_aux`` values sown during apply (models/moe.py's
    Switch load-balance loss, one per MoE layer); None when nothing sown."""
    vals = [
        leaf
        for path, leaf in jax.tree_util.tree_leaves_with_path(intermediates)
        if any(getattr(p, "key", None) == "moe_aux" for p in path)
    ]
    if not vals:
        return None
    return sum(vals) / len(vals)


def make_local_update(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    num_steps: int,
    batch_size: int,
    prox_mu: float = 0.0,
    min_steps_fraction: float = 0.25,
    grad_sync_axes: tuple[str, ...] = (),
    scaffold: bool = False,
    lr: float = 0.0,
    aux_loss_weight: float = 0.0,
) -> Callable:
    """Build ``local_update(global_params, x, y, count, key, step_budget)``.

    With ``scaffold=True`` the signature gains trailing ``(c_i, c)``
    control-variate pytrees and the return becomes a ``ScaffoldResult``
    (SCAFFOLD, Karimireddy et al. 2019: per-step grads are corrected by
    ``- c_i + c``, and the client's variate refreshes via option II,
    ``c_i' = c_i - c + (w_global - w_local)/(K·lr)`` over the K executed
    steps).  ``lr`` must then be the client learning rate.

    - ``x``: (M, ...) padded shard, ``y``: (M,), ``count``: () true size.
    - ``num_steps`` is the static per-round step budget (epochs * ceil(M/B)).
    - Sampling: each step draws ``batch_size`` uniform indices in
      [0, count) — i.i.d. sampling-with-replacement, the standard choice for
      static-shape federated simulation.
    - ``grad_sync_axes``: mesh axes the model's activations are sharded
      over (sequence parallelism).  Per-step grads are pmean'd over them —
      paired with the model's ``psum_for_grad_pmean`` pooling collective
      (parallel/collectives.py) this reconstructs exact full-sequence grads
      on every shard, so params stay replicated through local training.
    """
    min_steps = max(1, int(num_steps * min_steps_fraction))
    # Build-time only — the returned closure is jit-traced, where Python
    # side effects would silently run once and vanish.
    from colearn_federated_learning_tpu.telemetry import get_registry

    reg = get_registry()
    reg.counter("local.trainers_built").inc()
    reg.gauge("local.steps_per_round").set(num_steps)

    def loss_fn(params, global_params, xb, yb):
        if aux_loss_weight > 0.0:
            # MoE models sow their load-balance loss into "intermediates";
            # running every model this way would be harmless (flax returns
            # an empty dict) but the mutable round-trip is only paid when
            # the config asks for it.
            logits, updates = apply_fn(
                {"params": params}, xb, train=True, mutable=["intermediates"]
            )
            aux = _sown_aux_mean(updates.get("intermediates", {}))
            extra = aux_loss_weight * aux if aux is not None else 0.0
        else:
            logits = apply_fn({"params": params}, xb, train=True)
            extra = 0.0
        loss = losses.softmax_cross_entropy(logits, yb) + extra
        if prox_mu > 0.0:
            # FedProx: + μ/2 ‖w − w_global‖² (BASELINE config #3, μ=0.01).
            # Under SP its grads flow through the (replicated) params on
            # every shard; the pmean convention keeps that exact.
            loss = loss + 0.5 * prox_mu * pytrees.tree_sq_norm(
                pytrees.tree_sub(params, global_params)
            )
        return loss

    grad_fn = jax.value_and_grad(loss_fn)

    if scaffold and lr <= 0.0:
        raise ValueError("scaffold=True requires the client lr")

    def run_steps(global_params, x, y, count, key, step_budget, correction,
                  lr_scale):
        opt_state = optimizer.init(global_params)
        safe_count = jnp.maximum(count, 1)

        def step(carry, t):
            params, opt_state = carry
            k = jax.random.fold_in(key, t)
            idx = jax.random.randint(k, (batch_size,), 0, safe_count)
            xb = jnp.take(x, idx, axis=0)
            yb = jnp.take(y, idx, axis=0)
            loss, grads = grad_fn(params, global_params, xb, yb)
            for ax in grad_sync_axes:
                grads = jax.tree.map(lambda g: jax.lax.pmean(g, ax), grads)
            if correction is not None:
                grads = pytrees.tree_add(grads, correction)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            if lr_scale is not None:
                # Round-level lr schedule (strategies.lr_scale_for_round):
                # scaling the UPDATE equals running at lr·scale for SGD
                # (+momentum, linear in lr from a zero buffer) and for
                # Adam (update ∝ lr; grad scaling would be a no-op there).
                updates = pytrees.tree_scale(updates, lr_scale)
            new_params = optax.apply_updates(params, updates)
            active = t < step_budget
            params = _tree_where(active, new_params, params)
            opt_state = _tree_where(active, new_opt_state, opt_state)
            return (params, opt_state), loss * active

        (params, _), step_losses = jax.lax.scan(
            step, (global_params, opt_state), jnp.arange(num_steps)
        )
        executed = jnp.minimum(step_budget, num_steps).astype(jnp.float32)
        mean_loss = jnp.sum(step_losses) / jnp.maximum(executed, 1.0)
        result = LocalResult(
            delta=pytrees.tree_sub(params, global_params),
            num_examples=count.astype(jnp.int32),
            completed=step_budget >= min_steps,
            mean_loss=mean_loss,
            steps_run=executed,
        )
        return result, executed

    if not scaffold:
        def local_update(global_params, x, y, count, key, step_budget,
                         lr_scale=None):
            result, _ = run_steps(global_params, x, y, count, key,
                                  step_budget, None, lr_scale)
            return result

        return local_update

    def scaffold_update(global_params, x, y, count, key, step_budget, c_i, c,
                        lr_scale=None):
        correction = pytrees.tree_sub(c, c_i)     # grads - c_i + c
        result, executed = run_steps(global_params, x, y, count, key,
                                     step_budget, correction, lr_scale)
        # Option II refresh: c_i' = c_i - c + (w_g - w_local)/(K·lr_eff),
        # where lr_eff folds in the round-level schedule factor.  Past a
        # zero-floor cosine horizon lr_eff hits 0 while delta is exactly
        # 0 — clamp so the refresh stays 0/eps = finite instead of 0·inf
        # = NaN poisoning the variates.
        lr_eff = lr if lr_scale is None else lr * lr_scale
        scale = 1.0 / (jnp.maximum(executed, 1.0)
                       * jnp.maximum(lr_eff, 1e-12))
        c_new = pytrees.tree_add(
            pytrees.tree_sub(c_i, c),
            pytrees.tree_scale(result.delta, -scale),
        )
        return ScaffoldResult(
            result=result,
            c_new=c_new,
            delta_c=pytrees.tree_sub(c_new, c_i),
        )

    return scaffold_update


def make_lora_local_update(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    num_steps: int,
    batch_size: int,
    rank: int,
    alpha: float,
    prox_mu: float = 0.0,
    min_steps_fraction: float = 0.25,
    aux_loss_weight: float = 0.0,
) -> Callable:
    """Build ``lora_update(base_params, factors, x, y, count, key,
    step_budget, lr_scale=None)`` — the factor-only twin of
    :func:`make_local_update`.

    The base params are a FROZEN constant of the loss: autodiff runs
    w.r.t. the factor tree only, the forward pass applies the adapters
    through :func:`fed.lora.apply_adapters`, and the returned
    ``LocalResult.delta`` is a FACTOR delta (trained - received factors)
    — the O(r·d) tree the uplink ships.  Structure mirrors the dense
    trainer exactly (same scan, same per-step fold_in sampling, same
    ``step_budget`` masking, same lr_scale semantics), so shapes stay
    static and the jitted program holds ONE compile signature across
    rounds (pinned via telemetry CompileTracker in tests).

    ``prox_mu`` applies FedProx's proximal pull on the FACTORS
    (``mu/2 * ||f - f_global||^2``) — the natural restriction when the
    factors are the only trainable coordinates."""
    from colearn_federated_learning_tpu.fed import lora

    min_steps = max(1, int(num_steps * min_steps_fraction))
    from colearn_federated_learning_tpu.telemetry import get_registry

    reg = get_registry()
    reg.counter("local.trainers_built").inc()
    reg.gauge("local.steps_per_round").set(num_steps)

    def loss_fn(factors, base_params, global_factors, xb, yb):
        params = lora.apply_adapters(base_params, factors, alpha, rank)
        if aux_loss_weight > 0.0:
            logits, updates = apply_fn(
                {"params": params}, xb, train=True, mutable=["intermediates"]
            )
            aux = _sown_aux_mean(updates.get("intermediates", {}))
            extra = aux_loss_weight * aux if aux is not None else 0.0
        else:
            logits = apply_fn({"params": params}, xb, train=True)
            extra = 0.0
        loss = losses.softmax_cross_entropy(logits, yb) + extra
        if prox_mu > 0.0:
            loss = loss + 0.5 * prox_mu * pytrees.tree_sq_norm(
                pytrees.tree_sub(factors, global_factors)
            )
        return loss

    grad_fn = jax.value_and_grad(loss_fn)

    def lora_update(base_params, factors, x, y, count, key, step_budget,
                    lr_scale=None):
        opt_state = optimizer.init(factors)
        safe_count = jnp.maximum(count, 1)

        def step(carry, t):
            f, opt_state = carry
            k = jax.random.fold_in(key, t)
            idx = jax.random.randint(k, (batch_size,), 0, safe_count)
            xb = jnp.take(x, idx, axis=0)
            yb = jnp.take(y, idx, axis=0)
            loss, grads = grad_fn(f, base_params, factors_in, xb, yb)
            updates, new_opt_state = optimizer.update(grads, opt_state, f)
            if lr_scale is not None:
                updates = pytrees.tree_scale(updates, lr_scale)
            new_f = optax.apply_updates(f, updates)
            active = t < step_budget
            f = _tree_where(active, new_f, f)
            opt_state = _tree_where(active, new_opt_state, opt_state)
            return (f, opt_state), loss * active

        factors_in = factors
        (f, _), step_losses = jax.lax.scan(
            step, (factors, opt_state), jnp.arange(num_steps)
        )
        executed = jnp.minimum(step_budget, num_steps).astype(jnp.float32)
        mean_loss = jnp.sum(step_losses) / jnp.maximum(executed, 1.0)
        return LocalResult(
            delta=pytrees.tree_sub(f, factors_in),
            num_examples=count.astype(jnp.int32),
            completed=step_budget >= min_steps,
            mean_loss=mean_loss,
            steps_run=executed,
        )

    return lora_update
