"""Cross-silo federation over files: `colearn train --role client` /
`colearn aggregate` (BASELINE.json north_star entrypoints).

This is the decoupled counterpart of the in-process engine: each silo trains
locally against a global-model file and writes a weighted update file; the
aggregator folds any number of update files into a new global model with the
same server strategies as the on-device path.  Payloads use
utils/serialization.py npz — identical to what the TCP transport (comm/)
streams, so a silo can switch between file-drop and socket federation
without retraining.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.data.sharding import pack_client_shards
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.utils import prng, pytrees
from colearn_federated_learning_tpu.utils.config import ExperimentConfig
from colearn_federated_learning_tpu.utils.serialization import (
    load_pytree_npz,
    save_pytree_npz,
)


def init_global_model(config: ExperimentConfig, path: str) -> None:
    """Initialize global params from the experiment seed and write them."""
    params = setup_lib.init_global_params(config)
    save_pytree_npz(path, jax.tree.map(np.asarray, params),
                    meta={"round": 0, "config": config.run.name})


def client_update(
    config: ExperimentConfig,
    client_id: int,
    global_path: str,
    out_path: str,
    round_idx: int = 0,
    dataset: Optional[data_registry.Dataset] = None,
) -> dict:
    """One silo's local round: load global params, train on the silo's
    partition, write a weighted delta update file.  Returns summary stats."""
    c = config
    setup_lib.require_stateless_strategy(c, "the file-based client flow")
    params, meta = load_pytree_npz(global_path)
    round_idx = int(meta.get("round", round_idx))

    ds = dataset or data_registry.get_dataset(c.data.dataset, seed=c.run.seed)
    labels = np.asarray(ds.y_train)
    parts = setup_lib.partition_for_config(c, labels)
    if not 0 <= client_id < len(parts):
        raise ValueError(f"client_id {client_id} out of range [0, {len(parts)})")
    shards = pack_client_shards(np.asarray(ds.x_train), labels,
                                [parts[client_id]],
                                capacity=c.data.max_examples_per_client)

    local_update, num_steps = setup_lib.local_trainer_for_config(
        c,
        model_registry.build_model(setup_lib.local_model_config(c.model)).apply,
        shards.capacity,
    )
    update_fn = jax.jit(local_update)
    key = prng.experiment_key(c.run.seed)
    result = update_fn(
        params,
        jnp.asarray(shards.x[0]),
        jnp.asarray(shards.y[0]),
        jnp.asarray(shards.counts[0]),
        prng.client_round_key(key, client_id, round_idx),
        jnp.asarray(num_steps, jnp.int32),
        strategies.lr_scale_for_round(c.fed, round_idx),
    )
    delta, weight = setup_lib.finalize_client_delta(c, result, client_id,
                                                    round_idx)

    from colearn_federated_learning_tpu.fed import compression

    wire, cmeta = compression.compress_delta(
        jax.tree.map(np.asarray, delta), c.fed.compress
    )
    save_pytree_npz(out_path, wire,
                    meta={"round": round_idx, "weight": weight,
                          "client_id": client_id,
                          "num_examples": int(result.num_examples),
                          "mean_loss": float(result.mean_loss), **cmeta})
    return {"client_id": client_id, "round": round_idx, "weight": weight,
            "mean_loss": float(result.mean_loss)}


def aggregate_updates(
    config: ExperimentConfig,
    global_path: str,
    update_paths: list[str],
    out_path: str,
) -> dict:
    """`colearn aggregate`: fold silo update files into a new global model
    using the configured server strategy (fed/strategies.py)."""
    if not update_paths:
        raise ValueError("aggregate_updates: no update files given")
    setup_lib.require_mean_aggregator(config, "the file-based aggregator")
    params, meta = load_pytree_npz(global_path)
    round_idx = int(meta.get("round", 0))

    from colearn_federated_learning_tpu.fed import compression

    wsum = None
    total_w = 0.0
    for p in update_paths:
        delta, umeta = load_pytree_npz(p)
        # Guard against silent model corruption: an update computed against
        # a different global round must not be folded in.
        if "round" in umeta and int(umeta["round"]) != round_idx:
            raise ValueError(
                f"stale update {p}: computed at round {umeta['round']}, "
                f"global model is at round {round_idx}"
            )
        delta = compression.decompress_delta(delta, umeta, shapes=params)
        w = float(umeta.get("weight", 1.0))
        contrib = pytrees.tree_scale(delta, w)
        wsum = contrib if wsum is None else pytrees.tree_add(wsum, contrib)
        total_w += w
    if total_w <= 0:
        raise ValueError("aggregate_updates: total weight is zero")
    mean_delta = pytrees.tree_scale(wsum, 1.0 / total_w)

    state = strategies.init_server_state(params, config.fed)
    state = strategies.server_update(state, mean_delta, config.fed)
    save_pytree_npz(out_path, jax.tree.map(np.asarray, state.params),
                    meta={"round": round_idx + 1, "config": config.run.name,
                          "num_updates": len(update_paths),
                          "total_weight": total_w})
    return {"round": round_idx + 1, "num_updates": len(update_paths),
            "total_weight": total_w}


def evaluate_global(config: ExperimentConfig, global_path: str,
                    dataset: Optional[data_registry.Dataset] = None,
                    detection: bool = False) -> dict:
    """Evaluator role (SURVEY.md §3d): score a global-model file.

    Builds only the model and the eval scan — no partitioning, no trainer,
    no client data placement.  ``detection=True`` adds the anomaly-
    detection view (per-class P/R/F1, alarm detection/false-alarm rates;
    fed/evaluation.detection_report, class 0 = benign)."""
    from colearn_federated_learning_tpu.fed.evaluation import (
        detection_report,
        make_confusion_eval_fn,
        make_eval_fn,
        sanitize_report,
    )

    params, meta = load_pytree_npz(global_path)
    ds = dataset or data_registry.get_dataset(config.data.dataset,
                                              seed=config.run.seed)
    model = model_registry.build_model(
        setup_lib.local_model_config(config.model)
    )
    params = jax.tree.map(jnp.asarray, params)
    eval_fn = make_eval_fn(model.apply, ds.x_test, ds.y_test,
                           batch=max(config.fed.batch_size, 64))
    loss, acc = eval_fn(params)
    out = {"round": int(meta.get("round", 0)), "eval_loss": float(loss),
           "eval_acc": float(acc)}
    if detection:
        conf_fn = make_confusion_eval_fn(
            model.apply, ds.x_test, ds.y_test,
            batch=max(config.fed.batch_size, 64),
            num_classes=config.model.num_classes,
        )
        rep = detection_report(np.asarray(conf_fn(params)))
        rep.pop("accuracy", None)       # eval_acc above is canonical
        out.update(sanitize_report(rep))
    return out
