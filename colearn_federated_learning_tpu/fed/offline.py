"""Cross-silo federation over files: `colearn train --role client` /
`colearn aggregate` (BASELINE.json north_star entrypoints).

This is the decoupled counterpart of the in-process engine: each silo trains
locally against a global-model file and writes a weighted update file; the
aggregator folds any number of update files into a new global model with the
same server strategies as the on-device path.  Payloads use
utils/serialization.py npz — identical to what the TCP transport (comm/)
streams, so a silo can switch between file-drop and socket federation
without retraining.
"""

from __future__ import annotations

import math
import zipfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.data.sharding import pack_client_shards
from colearn_federated_learning_tpu.faults import fileplane
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.telemetry import registry as _metrics
from colearn_federated_learning_tpu.utils import prng, pytrees
from colearn_federated_learning_tpu.utils.config import ExperimentConfig
from colearn_federated_learning_tpu.utils.serialization import (
    atomic_save_pytree_npz,
    load_pytree_npz,
)

# Everything a half-written / replayed / foreign update file can raise on
# load+decode — the aggregator skips (and counts) these, never crashes.
_BAD_UPDATE_ERRORS = (OSError, EOFError, KeyError, ValueError,
                      zipfile.BadZipFile)


def init_global_model(config: ExperimentConfig, path: str) -> None:
    """Initialize global params from the experiment seed and write them."""
    params = setup_lib.init_global_params(config)
    atomic_save_pytree_npz(path, jax.tree.map(np.asarray, params),
                           meta={"round": 0, "config": config.run.name})


def _load_residual(residual_path: str, round_idx: int):
    """Load the carried error-feedback residual for ``round_idx``.

    The residual file is only valid when it was produced at the
    IMMEDIATELY preceding round: a gap means the silo's last update was
    rejected (stale/torn at the aggregator) or rounds were skipped, and
    re-injecting that residual would smuggle a stale gradient into the
    new global model.  Invalid carries reset to None and are counted on
    ``fed.offline_residual_resets_total`` by reason."""
    reg = _metrics.get_registry()
    try:
        prev, rmeta = load_pytree_npz(residual_path)
    except FileNotFoundError:
        return None                    # first round: nothing carried yet
    except _BAD_UPDATE_ERRORS:
        reg.counter("fed.offline_residual_resets_total",
                    labels={"reason": "torn"}).inc()
        return None
    if int(rmeta.get("round", -1)) != round_idx - 1:
        reg.counter("fed.offline_residual_resets_total",
                    labels={"reason": "stale"}).inc()
        return None
    return prev


def client_update(
    config: ExperimentConfig,
    client_id: int,
    global_path: str,
    out_path: str,
    round_idx: int = 0,
    dataset: Optional[data_registry.Dataset] = None,
    residual_path: Optional[str] = None,
) -> dict:
    """One silo's local round: load global params, train on the silo's
    partition, write a weighted delta update file.  Returns summary stats.

    ``residual_path`` carries uplink error feedback across file-plane
    rounds (``fed.compress_feedback``): the compression residual is
    persisted next to the silo's state and folded into the next round's
    delta — the same EF-SGD loop the socket worker runs in memory."""
    c = config
    # Same rejection rule as the wire plane (comm/worker.py): a masked
    # update cannot carry a plaintext compression residual.
    if c.fed.secure_agg and c.fed.compress_feedback:
        raise ValueError(
            "secure_agg cannot carry uplink error feedback: masked "
            "updates leave no plaintext compression residual to feed back")
    setup_lib.require_stateless_strategy(c, "the file-based client flow")
    params, meta = load_pytree_npz(global_path)
    round_idx = int(meta.get("round", round_idx))

    silo = str(client_id)
    if fileplane.should_drop(silo, round_idx, fileplane.HOP_UPDATE):
        # Injected silo dropout: no update file is published this round.
        return {"client_id": client_id, "round": round_idx, "weight": 0.0,
                "dropped": True}

    ds = dataset or data_registry.get_dataset(c.data.dataset, seed=c.run.seed)
    labels = np.asarray(ds.y_train)
    parts = setup_lib.partition_for_config(c, labels)
    if not 0 <= client_id < len(parts):
        raise ValueError(f"client_id {client_id} out of range [0, {len(parts)})")
    shards = pack_client_shards(np.asarray(ds.x_train), labels,
                                [parts[client_id]],
                                capacity=c.data.max_examples_per_client)

    local_update, num_steps = setup_lib.local_trainer_for_config(
        c,
        model_registry.build_model(setup_lib.local_model_config(c.model)).apply,
        shards.capacity,
    )
    update_fn = jax.jit(local_update)
    key = prng.experiment_key(c.run.seed)
    result = update_fn(
        params,
        jnp.asarray(shards.x[0]),
        jnp.asarray(shards.y[0]),
        jnp.asarray(shards.counts[0]),
        prng.client_round_key(key, client_id, round_idx),
        jnp.asarray(num_steps, jnp.int32),
        strategies.lr_scale_for_round(c.fed, round_idx),
    )
    delta, weight = setup_lib.finalize_client_delta(c, result, client_id,
                                                    round_idx)

    from colearn_federated_learning_tpu.fed import compression

    delta_np = jax.tree.map(np.asarray, delta)
    feedback = (c.fed.compress_feedback and residual_path is not None
                and c.fed.compress != "none")
    if feedback:
        residual = _load_residual(residual_path, round_idx)
        try:
            wire, cmeta, new_residual = compression.feedback_compress(
                delta_np, residual, c.fed.compress,
                topk_fraction=c.fed.topk_fraction)
        except ValueError:
            # Carried tree no longer matches the model (config changed
            # between rounds): reset and compress uncompensated.
            _metrics.get_registry().counter(
                "fed.offline_residual_resets_total",
                labels={"reason": "shape"}).inc()
            wire, cmeta, new_residual = compression.feedback_compress(
                delta_np, None, c.fed.compress,
                topk_fraction=c.fed.topk_fraction)
        if new_residual is not None:
            atomic_save_pytree_npz(
                residual_path, new_residual,
                meta={"round": round_idx, "client_id": client_id})
    else:
        wire, cmeta = compression.compress_delta(
            delta_np, c.fed.compress, topk_fraction=c.fed.topk_fraction)
    umeta = fileplane.stale_meta(
        {"round": round_idx, "weight": weight, "client_id": client_id,
         "num_examples": int(result.num_examples),
         "mean_loss": float(result.mean_loss), **cmeta},
        silo, round_idx, fileplane.HOP_UPDATE)
    atomic_save_pytree_npz(out_path, wire, meta=umeta)
    fileplane.maybe_truncate(out_path, silo, round_idx, fileplane.HOP_UPDATE)
    return {"client_id": client_id, "round": round_idx, "weight": weight,
            "mean_loss": float(result.mean_loss)}


def aggregate_updates(
    config: ExperimentConfig,
    global_path: str,
    update_paths: list[str],
    out_path: str,
) -> dict:
    """`colearn aggregate`: fold silo update files into a new global model
    using the configured server strategy (fed/strategies.py).

    Skip-and-log semantics: a torn, stale, or undecodable update file is
    skipped (counted as ``fed.offline_updates_rejected_total``, reason in
    the returned ``rejected`` list) instead of crashing the aggregator.
    The round only commits when the accepted count reaches the quorum
    derived from ``fed.min_cohort_fraction``; a sub-quorum round raises
    with every skip reason embedded."""
    if not update_paths:
        raise ValueError("aggregate_updates: no update files given")
    setup_lib.require_mean_aggregator(config, "the file-based aggregator")
    params, meta = load_pytree_npz(global_path)
    round_idx = int(meta.get("round", 0))

    from colearn_federated_learning_tpu.fed import compression

    reg = _metrics.get_registry()
    wsum = None
    total_w = 0.0
    accepted = 0
    rejected: list[str] = []

    def _reject(why: str, reason: str) -> None:
        reg.counter("fed.offline_updates_rejected_total",
                    labels={"reason": reason}).inc()
        rejected.append(why)

    for p in update_paths:
        try:
            delta, umeta = load_pytree_npz(p)
        except _BAD_UPDATE_ERRORS as e:
            _reject(f"bad update {p}: {type(e).__name__}: {e}", "torn")
            continue
        # Guard against silent model corruption: an update computed against
        # a different global round must not be folded in.
        if "round" in umeta and int(umeta["round"]) != round_idx:
            _reject(f"stale update {p}: computed at round {umeta['round']}, "
                    f"global model is at round {round_idx}", "stale")
            continue
        try:
            delta = compression.decompress_delta(delta, umeta, shapes=params)
        except _BAD_UPDATE_ERRORS as e:
            _reject(f"bad update {p}: {type(e).__name__}: {e}", "decode")
            continue
        w = float(umeta.get("weight", 1.0))
        if w <= 0:
            _reject(f"bad update {p}: non-positive weight {w}", "weight")
            continue
        contrib = pytrees.tree_scale(delta, w)
        wsum = contrib if wsum is None else pytrees.tree_add(wsum, contrib)
        total_w += w
        accepted += 1

    quorum = max(1, math.ceil(config.fed.min_cohort_fraction
                              * len(update_paths)))
    if accepted < quorum:
        raise ValueError(
            f"aggregate_updates: only {accepted}/{len(update_paths)} updates "
            f"usable (quorum {quorum}); " + "; ".join(rejected))
    mean_delta = pytrees.tree_scale(wsum, 1.0 / total_w)

    state = strategies.init_server_state(params, config.fed)
    state = strategies.server_update(state, mean_delta, config.fed)
    atomic_save_pytree_npz(out_path, jax.tree.map(np.asarray, state.params),
                           meta={"round": round_idx + 1,
                                 "config": config.run.name,
                                 "num_updates": accepted,
                                 "total_weight": total_w})
    out = {"round": round_idx + 1, "num_updates": accepted,
           "num_rejected": len(rejected), "total_weight": total_w}
    if rejected:
        out["rejected"] = rejected
    return out


def evaluate_global(config: ExperimentConfig, global_path: str,
                    dataset: Optional[data_registry.Dataset] = None,
                    detection: bool = False) -> dict:
    """Evaluator role (SURVEY.md §3d): score a global-model file.

    Builds only the model and the eval scan — no partitioning, no trainer,
    no client data placement.  ``detection=True`` adds the anomaly-
    detection view (per-class P/R/F1, alarm detection/false-alarm rates;
    fed/evaluation.detection_report, class 0 = benign)."""
    from colearn_federated_learning_tpu.fed.evaluation import (
        detection_report,
        make_confusion_eval_fn,
        make_eval_fn,
        sanitize_report,
    )

    params, meta = load_pytree_npz(global_path)
    ds = dataset or data_registry.get_dataset(config.data.dataset,
                                              seed=config.run.seed)
    model = model_registry.build_model(
        setup_lib.local_model_config(config.model)
    )
    params = jax.tree.map(jnp.asarray, params)
    eval_fn = make_eval_fn(model.apply, ds.x_test, ds.y_test,
                           batch=max(config.fed.batch_size, 64))
    loss, acc = eval_fn(params)
    out = {"round": int(meta.get("round", 0)), "eval_loss": float(loss),
           "eval_acc": float(acc)}
    if detection:
        conf_fn = make_confusion_eval_fn(
            model.apply, ds.x_test, ds.y_test,
            batch=max(config.fed.batch_size, 64),
            num_classes=config.model.num_classes,
        )
        rep = detection_report(np.asarray(conf_fn(params)))
        rep.pop("accuracy", None)       # eval_acc above is canonical
        out.update(sanitize_report(rep))
    return out
