"""Rank-r LoRA adapter plane: parameter-efficient federation.

Every round of the dense planes moves a full model delta per client, so
the wire plane's best uplink reduction is whatever the codec squeezes
out of O(model) floats (topk8: 12.62x, PERF.md §7).  LoRA (Hu et al.,
arXiv 2106.09685 — pattern only) changes the OBJECT being federated:
each targeted weight W keeps a frozen base and trains a rank-r pair
``B (m, r)`` / ``A (r, n)`` whose product is the update,

    W_eff = W + (alpha / r) * reshape(B @ A, W.shape),

so clients train and ship ONLY the factors — uplink drops from O(model)
to O(r * d) per adapted matrix, and because the factors are small DENSE
tensors they stay maskable under the Bonawitz secure-aggregation
protocol and foldable per aggregator slice, unlike sparse topk frames.

Targeting is driven by :mod:`parallel/partition`'s regex rule tables —
the SAME single source of partition truth the sharded server uses: a
leaf is adapted iff its first-matching rule carries a non-``None``
shard spec (the attention qkv + MLP matmuls, embeddings, MoE banks) and
the leaf has rank >= 2.  Biases/norms that the rules replicate stay
frozen at the base value — the classic adapters-only regime.

Factorization picks the split that minimizes ``m + n`` over the leaf's
dims (``B`` absorbs the leading group, ``A`` the trailing group), so a
``(D, H, hd)`` attention kernel factors as ``(D, r) x (r, H*hd)`` —
O(r * D) — instead of pairing a tiny leading dim with a huge flattened
tail.  Factors inherit the base param's PartitionSpec on the sharded
axis: a base sharded on its leading dim shards ``B`` as ``P(axis,
None)``; a base sharded on the first trailing dim shards ``A`` as
``P(None, axis)`` (both correspond to contiguous row-major blocks of
the flattened factor dims); any other sharded dim replicates the
factors — numerics are placement-independent either way.

Everything here is pure-jax tree math; the client/server wiring lives
in fed/local.py (factor-only trainer), comm/worker.py and
comm/coordinator.py (factor uplink + shard-wise merge).
"""

from __future__ import annotations

import re
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from colearn_federated_learning_tpu.parallel import partition

# Factor leaves live under these keys at the adapted param's tree
# position; the pair dict replaces the base leaf in the factor tree.
A_KEY = "lora_a"
B_KEY = "lora_b"

# Default init scale for A (B starts at zero, so the initial delta is
# exactly zero and round 0 matches the base model bit-for-bit).
DEFAULT_SIGMA = 0.02


# ------------------------------------------------------------ targeting --
def _compile_rules(rules):
    out = []
    for rule in rules:
        pat, spec = rule[0], rule[1]
        ndim = rule[2] if len(rule) > 2 else None
        out.append((re.compile(pat), spec, ndim))
    return out


def _raw_spec(compiled, name: str, shape) -> Any:
    """First-match raw rule spec for a '/'-joined path — the same
    ordered ``re.search`` walk :func:`partition.match_partition_rules`
    resolves PartitionSpecs with, but BEFORE divisibility resolution:
    targeting must not depend on the mesh size of the current run."""
    if len(shape) == 0:
        return None
    for pat, spec, ndim in compiled:
        if ndim is not None and len(shape) != ndim:
            continue
        if pat.search(name):
            return spec
    return None


def target_paths(params: Any, model_name: str = "",
                 rules: Optional[tuple] = None) -> dict:
    """``{path: shape}`` of the adapted leaves: first-matching partition
    rule has a non-None spec AND the leaf has rank >= 2.

    Bias leaves are never adapted even when rank >= 2 (reshaped-head
    attention biases are (heads, head_dim)): rank-r factors on a bias
    cost ``r*(m+n)`` against an ``m*n`` original — MORE bytes, no
    low-rank structure to exploit."""
    compiled = _compile_rules(
        rules if rules is not None else partition.rules_for_model(model_name))
    out = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        shape = tuple(np.shape(leaf))
        name = partition.path_str(path)
        if name.rsplit("/", 1)[-1] == "bias":
            continue
        if len(shape) >= 2 and _raw_spec(compiled, name, shape) is not None:
            out[name] = shape
    return out


def split_point(shape) -> int:
    """Factorization split k minimizing prod(shape[:k]) + prod(shape[k:])
    (ties break low — deterministic, shape-only)."""
    best_k, best = 1, None
    for k in range(1, len(shape)):
        m = int(np.prod(shape[:k], dtype=np.int64))
        n = int(np.prod(shape[k:], dtype=np.int64))
        if best is None or m + n < best:
            best_k, best = k, m + n
    return best_k


def factor_dims(shape) -> tuple[int, int]:
    """(m, n) of the ``B (m, r) @ A (r, n)`` factorization for a leaf."""
    k = split_point(shape)
    return (int(np.prod(shape[:k], dtype=np.int64)),
            int(np.prod(shape[k:], dtype=np.int64)))


def _nested_set(tree: dict, path: str, value: Any) -> None:
    keys = path.split("/")
    node = tree
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


def init_factors(params: Any, rank: int, key: Optional[jax.Array] = None,
                 model_name: str = "", rules: Optional[tuple] = None,
                 sigma: float = DEFAULT_SIGMA) -> dict:
    """Factor tree for ``params``: at every adapted leaf position a
    ``{A_KEY: (r, n) f32, B_KEY: (m, r) f32}`` pair; non-adapted leaves
    are absent entirely (the uplink ships ONLY factors).

    ``A ~ N(0, sigma)`` per leaf (deterministically keyed by the leaf's
    index under ``key``), ``B = 0`` — so the initial adapter delta is
    exactly zero.  ``key=None`` zeros A too: the shape-template mode
    folder construction and wire pricing use (frame lengths depend only
    on shapes/dtypes)."""
    targets = target_paths(params, model_name=model_name, rules=rules)
    out: dict = {}
    for i, (path, shape) in enumerate(sorted(targets.items())):
        m, n = factor_dims(shape)
        if key is None:
            a = jnp.zeros((rank, n), jnp.float32)
        else:
            a = sigma * jax.random.normal(
                jax.random.fold_in(key, i), (rank, n), jnp.float32)
        _nested_set(out, path, {
            A_KEY: a,
            B_KEY: jnp.zeros((m, rank), jnp.float32),
        })
    return out


def factor_index(factors: Any) -> dict:
    """Flatten a factor tree to ``{path: (A, B)}`` (trace-time walk)."""
    out: dict = {}

    def walk(node, prefix):
        if isinstance(node, Mapping):
            keys = set(node.keys())
            if keys == {A_KEY, B_KEY}:
                out[prefix] = (node[A_KEY], node[B_KEY])
            else:
                for k in node:
                    walk(node[k], f"{prefix}/{k}" if prefix else str(k))

    walk(factors, "")
    return out


def count_factor_params(factors: Any) -> int:
    return sum(int(np.prod(np.shape(l), dtype=np.int64))
               for l in jax.tree.leaves(factors))


# ---------------------------------------------------------- apply / merge --
def _adapted_tree(params: Any, factors: Any, alpha: float, rank: int) -> Any:
    """params + (alpha/rank) * reshape(B @ A) at every factor position.

    Float32 accumulate, base dtype preserved (the downlink
    ``apply_dense_delta`` convention) — inside jit this differentiates
    w.r.t. the factors with the base frozen; eagerly it IS the merge."""
    idx = factor_index(factors)
    scale = alpha / float(rank)

    def f(path, w):
        ab = idx.get(partition.path_str(path))
        if ab is None:
            return w
        a, b = ab
        delta = (b @ a).reshape(np.shape(w)) * scale
        return (w.astype(jnp.float32) + delta).astype(jnp.dtype(w.dtype))

    return jax.tree_util.tree_map_with_path(f, params)


def apply_adapters(params: Any, factors: Any, alpha: float,
                   rank: int) -> Any:
    """Effective params for the forward pass (pure-jax; jit-safe)."""
    return _adapted_tree(params, factors, alpha, rank)


def merge_adapters(params: Any, factors: Any, alpha: float,
                   rank: int) -> Any:
    """Fold B·A·(alpha/r) INTO the base params — same math as
    :func:`apply_adapters`, named for the server's merge event.  On a
    tp-sharded params tree run it under jit: every op is elementwise in
    the base leaf (plus a small replicated ``B @ A`` contraction over
    r), so XLA keeps each leaf's output in its input sharding — no
    full-tree gather."""
    return _adapted_tree(params, factors, alpha, rank)


def reset_factors(factors: Any) -> Any:
    """Post-merge reset: B <- 0 (the merged delta is now in the base),
    A kept — the next cycle resumes from the same A basis, keeping one
    compile signature and exact oracle reproducibility."""

    def walk(node):
        if isinstance(node, Mapping):
            if set(node.keys()) == {A_KEY, B_KEY}:
                return {A_KEY: node[A_KEY],
                        B_KEY: jnp.zeros_like(node[B_KEY])}
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(factors)


# ------------------------------------------------------ sharding specs --
def factor_specs(params: Any, rank: int, axis: str = "model",
                 model_name: str = "", rules: Optional[tuple] = None,
                 sizes: Optional[Mapping[str, int]] = None) -> dict:
    """PartitionSpec tree for a factor tree — the base param's resolved
    spec inherited onto the factor whose flattened dim group contains
    the sharded base dim as its MAJOR (row-contiguous) component:

    - base sharded at dim 0        -> B: P(axis, None)
    - base sharded at dim split(k) -> A: P(None, axis)
    - anything else                -> replicated factors

    Divisibility follows :func:`partition._resolve_spec` semantics: an
    indivisible factor dim replicates (numerics-exact either way)."""
    rules = rules if rules is not None else partition.rules_for_model(
        model_name)
    sizes = dict(sizes or {})
    specs = partition.match_partition_rules(
        rules, params, axis=axis, sizes=sizes)
    spec_by_path = {
        partition.path_str(p): s
        for p, s in jax.tree_util.tree_leaves_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))
    }
    size = int(sizes.get(axis, 0))
    out: dict = {}
    for path, shape in sorted(target_paths(
            params, model_name=model_name, rules=rules).items()):
        spec = spec_by_path.get(path, P())
        sharded_dim = next(
            (d for d, name in enumerate(spec) if name == axis), None)
        k = split_point(shape)
        m, n = factor_dims(shape)
        a_spec, b_spec = P(), P()
        if sharded_dim == 0 and (not size or m % size == 0):
            b_spec = P(axis, None)
        elif sharded_dim == k and (not size or n % size == 0):
            a_spec = P(None, axis)
        _nested_set(out, path, {A_KEY: a_spec, B_KEY: b_spec})
    return out
