"""Federated round engine: local training, server strategies, orchestration."""

from colearn_federated_learning_tpu.fed.engine import FederatedLearner  # noqa: F401
from colearn_federated_learning_tpu.fed.hierarchical import (  # noqa: F401
    HierarchicalLearner,
)
from colearn_federated_learning_tpu.fed.clustered import (  # noqa: F401
    ClusteredLearner,
)
