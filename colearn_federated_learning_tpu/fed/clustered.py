"""Clustered federated learning: per-concept models from update similarity.

When client populations carry CONFLICTING concepts (e.g. the same traffic
pattern is benign on one fleet and an attack on another), no single global
model fits everyone — the classic failure FedAvg cannot see.  Clustered FL
(Sattler et al. 1910.01991 / IFCA lineage, pattern only) recovers the
latent grouping from the geometry of the clients' OWN updates and trains
one model per cluster:

1. warm up a global model a few rounds;
2. compute the (N, N) cosine-similarity matrix of per-client updates —
   one vmapped jit program + one MXU gram matmul
   (``FederatedLearner.client_update_similarity``);
3. cluster its rows (k-means on host; the matrix is tiny);
4. build one ``FederatedLearner`` per cluster over its members' packed
   shards, seeded from the warmed-up global model, and train them
   independently.

Evaluation is per-client on the members' OWN shards (the global holdout
carries only one concept, so it cannot score concept-shifted clusters).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from colearn_federated_learning_tpu.fed.engine import FederatedLearner


def kmeans_rows(X: np.ndarray, k: int, iters: int = 50,
                seed: int = 0) -> np.ndarray:
    """Tiny k-means (numpy, k-means++ init) over the rows of ``X``."""
    rng = np.random.default_rng(seed)
    n = X.shape[0]
    centers = [X[rng.integers(n)]]
    for _ in range(1, k):
        d2 = np.min(
            [np.sum((X - c) ** 2, axis=1) for c in centers], axis=0
        )
        total = d2.sum()
        if total <= 0.0:
            # Degenerate: all rows identical — any choice is equivalent.
            centers.append(X[rng.integers(n)])
            continue
        centers.append(X[rng.choice(n, p=d2 / total)])
    C = np.stack(centers)
    labels = np.zeros(n, np.int32)
    for _ in range(iters):
        d = ((X[:, None, :] - C[None]) ** 2).sum(-1)
        new = d.argmin(1).astype(np.int32)
        if (new == labels).all():
            break
        labels = new
        for j in range(k):
            if (labels == j).any():
                C[j] = X[labels == j].mean(0)
    return labels


class ClusteredLearner:
    """Warm up → cluster by update similarity → one learner per cluster.

    Built ON an existing ``FederatedLearner`` (its packed shards are the
    ground truth of who owns which examples, so tests can manipulate
    per-client data before clustering).  Works on both engine paths: on a
    mesh the similarity matrix is computed under shard_map (all_gather of
    the normalized deltas over the client axis), labels/members are kept
    in ORIGINAL client-id order, and each cluster learner trains over the
    same mesh.
    """

    def __init__(self, base: FederatedLearner, num_clusters: int = 2):
        if num_clusters < 2:
            raise ValueError(f"num_clusters must be >= 2, got {num_clusters}")
        self.base = base
        self.num_clusters = num_clusters
        self.labels: Optional[np.ndarray] = None
        self.clusters: list[FederatedLearner] = []
        self.members: list[np.ndarray] = []

    def _label_slots(self) -> np.ndarray:
        """Array-slot index of each LABELED client, in label order.

        The similarity matrix (and therefore ``labels``) is in ORIGINAL
        client-id order with mesh ghost padding dropped; the base
        learner's stacked arrays are in slot order (interleaved on a
        mesh).  ``_label_slots()[i]`` is the slot holding labeled client
        ``i``'s shard — the engine's own id-order mapping, so the filter
        can never diverge from ``client_update_similarity``'s."""
        return self.base.id_order_slots()

    def cluster_and_specialize(self, warmup_rounds: int = 2,
                               sim_steps: int = 3) -> np.ndarray:
        """Run the pipeline; returns the per-client cluster labels."""
        base = self.base
        for _ in range(warmup_rounds):
            base.run_round()
        sim = base.client_update_similarity(steps=sim_steps)
        labels = kmeans_rows(sim, self.num_clusters,
                             seed=base.config.run.seed)
        self._build_clusters(labels,
                             [base.server_state.params] * self.num_clusters)
        return self.labels

    def _build_clusters(self, labels: np.ndarray, init_params: list) -> None:
        """(Re)build the per-cluster learners for ``labels``, seeding
        cluster ``j`` from ``init_params[j]`` — the warm global model on
        first clustering, each cluster's own trained model on IFCA
        reassignment."""
        import dataclasses

        base = self.base
        self.labels = labels
        self.clusters, self.members = [], []   # re-clustering resets state

        # One learner per cluster over its members' EXACT shard rows:
        # examples concatenate per member in order and explicit contiguous
        # partitions are injected, so every member keeps its own shard
        # (and non-IID skew) inside its cluster learner.
        x = np.asarray(base._device_data[0])
        y = np.asarray(base._device_data[1])   # tests may have edited y
        counts = np.asarray(base.shards.counts)
        slots = self._label_slots()
        for j in range(self.num_clusters):
            members = np.where(labels == j)[0]
            self.members.append(members)
            if members.size == 0:
                self.clusters.append(None)
                continue
            m_slots = slots[members]
            xs = np.concatenate([x[i][: counts[i]] for i in m_slots])
            ys = np.concatenate([y[i][: counts[i]] for i in m_slots])
            offsets = np.cumsum([0] + [int(counts[i]) for i in m_slots])
            parts = [np.arange(offsets[m], offsets[m + 1])
                     for m in range(members.size)]
            ds = dataclasses.replace(
                base.dataset, x_train=xs, y_train=ys,
            )
            cfg = base.config.replace(
                data=dataclasses.replace(
                    base.config.data, num_clients=int(members.size),
                ),
                run=dataclasses.replace(
                    base.config.run,
                    name=f"{base.config.run.name}_cluster{j}",
                ),
            )
            # Cluster learners inherit the base's mesh: on a pod each
            # cluster trains sharded over the same client axis (small
            # clusters pad with ghosts, which never contribute).
            learner = FederatedLearner(cfg, dataset=ds, mesh=base.mesh,
                                       partitions=parts)
            learner.server_state = learner.server_state._replace(
                params=init_params[j]
            )
            self.clusters.append(learner)

    def reassign(self) -> np.ndarray:
        """IFCA step (Ghosh et al. 2006.04088, pattern only): every client
        picks the cluster whose CURRENT model has the lowest loss on its
        own shard — K vmapped per-client eval programs over the base
        learner's stacked shards, then an argmin on host."""
        base = self.base
        if not hasattr(base, "_client_eval_fn"):
            base._client_eval_fn = base._build_client_eval_fn()
        slots = self._label_slots()
        losses = []
        for learner in self.clusters:
            if learner is None:
                losses.append(np.full(slots.size, np.inf))
                continue
            l, _ = base._client_eval_fn(
                learner.server_state.params, *base._device_data[:3]
            )
            # Slot order -> label (original-id) order, ghosts dropped.
            losses.append(np.asarray(l)[slots])
        return np.argmin(np.stack(losses), axis=0).astype(np.int32)

    def refine(self, iters: int = 2, rounds_per_iter: int = 2) -> np.ndarray:
        """Alternate cluster training with IFCA reassignment.  Clients that
        move adopt the model of their new cluster; clusters keep their
        trained models across reassignments."""
        if self.labels is None:
            raise RuntimeError("call cluster_and_specialize() first")
        for _ in range(iters):
            self.fit(rounds=rounds_per_iter)
            new = self.reassign()
            if (new == self.labels).all():
                break
            params = [
                (c.server_state.params if c is not None
                 else self.base.server_state.params)
                for c in self.clusters
            ]
            self._build_clusters(new, params)
        return self.labels

    def fit(self, rounds: int) -> None:
        if self.labels is None:
            raise RuntimeError("call cluster_and_specialize() first")
        for learner in self.clusters:
            if learner is not None:
                learner.fit(rounds=rounds)

    def evaluate_per_client(self) -> dict:
        """Per-client accuracy of each cluster's model on its members'
        OWN shards, plus the weighted aggregate across all clusters."""
        from colearn_federated_learning_tpu.fed.evaluation import (
            summarize_per_client,
        )

        losses, accs, counts = [], [], []
        for learner in self.clusters:
            if learner is None:
                continue
            rep = learner.evaluate_per_client()
            losses.extend(rep["per_client_loss"])
            accs.extend(rep["per_client_acc"])
            counts.extend(rep["num_examples"])
        out = summarize_per_client(losses, accs, counts)
        out["num_clusters"] = sum(c is not None for c in self.clusters)
        out["cluster_sizes"] = [int(m.size) for m in self.members]
        return out
