"""The fleet-simulation hot path: chunked ``jax.vmap`` over local_update.

One simulated round is exactly the engine's round — same per-(client,
round) PRNG keys (utils/prng.py), same FedAvg weighting
(``num_examples * contrib``), same mean + ``strategies.server_update``
epilogue — but the cohort is processed in FIXED-SIZE chunks:

    cohort -> [chunk_0 | chunk_1 | ...]      (last chunk zero-padded)
    chunk_i: vmap(local_update) -> weighted partial sums (on device)
    fold:    partial sums add into the round accumulator (on device)

Memory is therefore O(chunk x model + chunk x shard) at ANY cohort
size: a million-client round is ~250 chunk dispatches, not a million-
row vmap.  Chunk partial sums fold with the same ``tree_weighted_sum``
semantics the engine aggregates with, so a one-chunk round reproduces
the engine bit-for-bit (tests/test_fleetsim.py parity tests).

Faults reuse the FaultPlan key space ``(device, round, op)`` with
``op="train"`` (faults/plan.py):

- ``drop_request``    — the device never trains or reports (no uplink);
- ``delay``           — straggle: the device loses ``ms`` of its
  simulated round deadline, its ``step_budget`` shrinks proportionally
  (fed/local.py masks the lost steps; below the completion threshold
  its FedAvg weight zeroes exactly like an engine straggler);
- ``corrupt_payload`` — the update arrives corrupted and is discarded
  (uplink bytes spent, weight zeroed — the CRC-reject analog).

NOTE on plan authoring: ``FaultSpec.count`` defaults to 1 (one firing
TOTAL); fleet-wide schedules want explicit ``count=0`` (unlimited) or a
budget sized to the cohort.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.fed import compression
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.fed.programs import rank_cohort
from colearn_federated_learning_tpu.utils import prng, pytrees
from colearn_federated_learning_tpu.utils.config import ExperimentConfig
from colearn_federated_learning_tpu.utils.serialization import (
    wire_frame_length,
)

_FLEET_FAULT_KINDS = ("drop_request", "delay", "corrupt_payload")


def _validate_fleet_config(config: ExperimentConfig) -> None:
    """The fleet path is the engine's plain weighted-mean FedAvg family;
    the stateful/privacy variants keep their engine-only homes."""
    setup_lib.require_stateless_strategy(config, "fleetsim")
    setup_lib.require_mean_aggregator(config, "fleetsim")
    c = config.fed
    if c.dp_clip > 0.0 or c.secure_agg:
        raise NotImplementedError(
            "fleetsim does not support dp/secure-agg hooks yet: their "
            "noise accounting and mask pairing assume the engine's "
            "single-program cohort; run the on-device engine")


def _count_fault(kind: str) -> None:
    """Fault-plane telemetry, aggregate only: the comm injector labels
    ``fault.injected_total`` per device, but at fleet scale per-device
    label children would grow the registry O(cohort) per round."""
    reg = telemetry.get_registry()
    reg.counter("fault.injected_total", labels={"kind": kind}).inc()
    reg.counter(f"fault.injected.{kind}").inc()


class FleetSim:
    """Chunked-vmap fleet simulator.

    Build with :meth:`from_population` (synthetic fleet + traffic model,
    the 1k->1M workload) or :meth:`from_learner` (wrap an existing
    :class:`~fed.engine.FederatedLearner`'s data/trainer/keys — the
    parity harness the tests trust the vmapped path against).
    """

    def __init__(
        self,
        *,
        config: ExperimentConfig,
        local_update: Callable,
        num_steps: int,
        base_key,
        server_state,
        shard_fn: Callable[[np.ndarray], tuple],
        budget_fn: Callable[[np.ndarray], np.ndarray],
        select_fn: Callable[[int], np.ndarray],
        num_devices: int,
        cohort_size: int,
        chunk_size: int = 1024,
        fault_plan=None,
        round_deadline_ms: float = 1000.0,
        available_fraction_fn: Optional[Callable[[int], float]] = None,
    ):
        _validate_fleet_config(config)
        self.config = config
        self.local_update = local_update
        self.num_steps = int(num_steps)
        self.base_key = base_key
        self.server_state = server_state
        self._shard_fn = shard_fn
        self._budget_fn = budget_fn
        self._select_fn = select_fn
        self.num_devices = int(num_devices)
        self.cohort_size = int(min(cohort_size, num_devices))
        self.chunk_size = int(min(chunk_size, max(1, self.cohort_size)))
        self.fault_plan = fault_plan
        self.round_deadline_ms = float(round_deadline_ms)
        self._available_fraction_fn = available_fraction_fn
        # Set by from_population; fit_async needs per-device arrival
        # rates, not just the fleet-mean fraction.
        self._traffic = None
        self.history: list[dict] = []
        self.tracer = telemetry.Tracer(process="fleetsim", enabled=False)
        # Per-device health feed (telemetry/health.py): the simulated
        # fleet attributes its injected faults to devices exactly like
        # the socket planes attribute real ones.  Off by default.
        self.health = None
        if config.run.health_dir:
            self.health = telemetry.HealthLedger(config.run.health_dir,
                                                 "fleetsim")
        # Convergence observatory (telemetry/convergence.py): updates are
        # simulation-local, so this plane legitimately sees per-device
        # norms and per-cohort centroids — the attribution secure
        # aggregation denies the socket planes.  Off by default: no
        # observatory, no obs program, round records byte-identical.
        self._learn = None
        self._obs_chunk_fn = None
        self._population = None           # set by from_population
        if config.run.learn_observe:
            self._learn = telemetry.ConvergenceObservatory()

        # CompileTracker on every jitted program makes the "one compile
        # per sweep shape" claim a measurable invariant (compile_counts
        # below; test-pinned): zero-padding to a fixed chunk width means
        # the chunk fn must hold exactly ONE signature per sweep.
        self._chunk_fn = telemetry.CompileTracker(
            self._build_chunk_fn(), name="fleetsim.chunk")
        self._finish_fn = telemetry.CompileTracker(
            self._build_finish_fn(), name="fleetsim.finish")
        # One fused add per fold: the 4 partial sums are one pytree.
        self._fold_fn = telemetry.CompileTracker(
            jax.jit(lambda a, b: jax.tree.map(jnp.add, a, b)),
            name="fleetsim.fold")

        # Wire-cost model (comm codecs, shape-only so computed ONCE):
        # frame lengths depend on leaf shapes/dtypes, not values.
        params_np = jax.tree.map(np.asarray, server_state.params)
        zeros = jax.tree.map(np.zeros_like, params_np)
        self.down_full_bytes = int(wire_frame_length(
            params_np, {"round": 0, "down": "full"}))
        scheme_down = config.fed.compress_down
        if scheme_down == "none":
            self.down_frame_bytes = self.down_full_bytes
        else:
            wire, meta = compression.compress_delta(zeros, scheme_down)
            self.down_frame_bytes = int(wire_frame_length(
                wire, {"round": 0, "down": "delta", **meta}))
        # LoRA pricing (fed/lora.py): with fed.lora_rank > 0 the real
        # wire planes ship FACTOR frames on the uplink, so the byte
        # estimator prices those.  The simulated training dynamics stay
        # dense (the chunked vmap trainer is unchanged) — only the
        # wire-cost model is adapter-aware, the same shape-only
        # decoupling as the codec pricing above.
        if config.fed.lora_rank > 0:
            from colearn_federated_learning_tpu.fed import lora as lora_lib

            up_zeros = jax.tree.map(np.asarray, lora_lib.init_factors(
                params_np, config.fed.lora_rank,
                model_name=config.model.name))
        else:
            up_zeros = zeros
        wire_up, meta_up = compression.compress_delta(
            up_zeros, config.fed.compress,
            topk_fraction=config.fed.topk_fraction)
        self.up_frame_bytes = int(wire_frame_length(
            wire_up, {"round": 0, "op": "train", **meta_up}))
        # Uplink fast-path savings (PR 10): per-update bytes a compressed
        # (or factor-only) uplink saves vs the dense train frame — same
        # shape-only pricing the coordinator's comm.bytes_saved_uplink
        # counter uses.
        if config.fed.compress == "none" and config.fed.lora_rank == 0:
            self.up_saved_bytes = 0
        else:
            dense_up = int(wire_frame_length(
                zeros, {"round": 0, "op": "train", "compress": "none"}))
            self.up_saved_bytes = max(0, dense_up - self.up_frame_bytes)
        # Sharded-downlink shape (PR 9): with run.tp_size > 1 the server
        # encodes each broadcast from per-device shards, never
        # materializing a replicated copy.  The frame bytes are identical
        # (same payload); what the estimator learns is the per-encode
        # gather bytes AVOIDED — pure shape math from the partition rules,
        # so 1M-cohort sweeps reflect the sharded wire cost without a mesh.
        tp = config.run.tp_size
        if tp > 1:
            from colearn_federated_learning_tpu.parallel import partition
            self.gather_avoided_bytes = int(partition.estimate_gather_avoided(
                params_np, partition.rules_for_model(config.model.name),
                config.run.tp_axis, tp))
        else:
            self.gather_avoided_bytes = 0

        reg = telemetry.get_registry()
        reg.gauge("fleetsim.devices").set(self.num_devices)
        reg.gauge("fleetsim.chunk_size").set(self.chunk_size)

    # ------------------------------------------------------ constructors --
    @classmethod
    def from_population(
        cls,
        config: ExperimentConfig,
        population,
        traffic,
        cohort_size: int,
        chunk_size: int = 1024,
        fault_plan=None,
        round_deadline_ms: float = 1000.0,
    ) -> "FleetSim":
        """Synthetic fleet: shards materialize on demand from per-device
        keys (fleetsim/population.py); the traffic model picks each
        round's cohort among currently-available devices."""
        from colearn_federated_learning_tpu.models import (
            registry as model_registry,
        )

        spec = population.spec
        model = model_registry.build_model(
            setup_lib.local_model_config(config.model))
        example_x = jnp.asarray(
            population.example_batch(config.fed.batch_size))
        base_key = prng.experiment_key(config.run.seed)
        params = model_registry.init_params(
            model, example_x, prng.init_key(base_key))
        local_update, num_steps = setup_lib.local_trainer_for_config(
            config, model.apply, spec.shard_capacity, lora_dense_ok=True)
        sim = cls(
            config=config,
            local_update=local_update,
            num_steps=num_steps,
            base_key=base_key,
            server_state=strategies.init_server_state(params, config.fed),
            shard_fn=population.materialize,
            budget_fn=lambda ids: population.step_budgets(ids, num_steps),
            select_fn=lambda r: traffic.sample_cohort(r, cohort_size),
            num_devices=spec.num_devices,
            cohort_size=cohort_size,
            chunk_size=chunk_size,
            fault_plan=fault_plan,
            round_deadline_ms=round_deadline_ms,
            available_fraction_fn=lambda r: float(
                traffic.available_mask(r).mean()),
        )
        sim._traffic = traffic
        # Cohort drift attribution needs each device's seeded home class
        # (population.home_classes) — only this constructor has one.
        sim._population = population
        return sim

    @classmethod
    def from_learner(cls, learner, chunk_size: int = 1024,
                     fault_plan=None,
                     round_deadline_ms: float = 1000.0) -> "FleetSim":
        """Wrap a vmap-path :class:`FederatedLearner`: same shards, same
        trainer closure, same base key, same host cohort ranking — the
        ONLY difference from ``learner.run_round()`` is the chunked
        dispatch, which is exactly what the parity tests pin down."""
        if learner.mesh is not None:
            raise NotImplementedError(
                "from_learner wraps the single-device vmap path; shard "
                "the fleet over a mesh via the engine instead")
        shards = learner.shards
        counts_dev = jnp.asarray(shards.counts)
        num_clients = learner.num_clients
        cohort = learner.cohort_size
        base_key = learner.base_key

        def select(round_idx: int) -> np.ndarray:
            # Mirrors fed/engine._host_sample_cohort (vmap branch): same
            # key, same ranking function, eager.
            if cohort < num_clients:
                skey = prng.sampling_key(
                    base_key, jnp.asarray(round_idx, jnp.int32))
                return np.asarray(
                    rank_cohort(skey, counts_dev, cohort)).astype(np.int64)
            return np.arange(num_clients, dtype=np.int64)

        def shard_slices(ids: np.ndarray) -> tuple:
            return shards.x[ids], shards.y[ids], shards.counts[ids]

        num_steps = learner.num_steps
        return cls(
            config=learner.config,
            local_update=learner.local_update,
            num_steps=num_steps,
            base_key=base_key,
            server_state=learner.server_state,
            shard_fn=shard_slices,
            budget_fn=lambda ids: np.full(
                ids.shape[0], num_steps, np.int32),
            select_fn=select,
            num_devices=num_clients,
            cohort_size=cohort,
            chunk_size=chunk_size,
            fault_plan=fault_plan,
            round_deadline_ms=round_deadline_ms,
        )

    # -------------------------------------------------- compiled pieces --
    def _build_chunk_fn(self, observe: bool = False, num_classes: int = 1):
        """One chunk's training + weighting, jit-compiled once (static
        chunk shape): vmap(local_update) -> weighted partial sums.  The
        engine's cohort_step semantics, minus the engine-only hooks the
        config validator excluded.

        ``observe=True`` builds the convergence-observatory variant
        (telemetry/convergence.py): same training, plus per-device
        update norms and per-home-class weighted delta sums (``classes``
        carries each device's seeded non-IID cluster) — the raw material
        for cohort drift attribution.  A separate jitted program, so the
        default plane's ``compile_counts`` contract is untouched.
        """
        update = self.local_update
        fed = self.config.fed
        num_steps = self.num_steps

        def core(key, params, x, y, counts, ids, round_idx, budgets,
                 keep):
            # Per-(client, round) keys off the GLOBAL device id:
            # placement/chunking-independent determinism (utils/prng.py).
            keys = jax.vmap(
                lambda i: prng.client_round_key(key, i, round_idx))(ids)
            if fed.straggler_prob > 0.0:
                # The engine's simulated stragglers, same derivation
                # (fed/programs.cohort_step); the fleet's own budget
                # (speed class / delay fault) caps from below.
                skey = prng.straggler_key(key, round_idx)

                def budget_for(i):
                    k = jax.random.fold_in(skey, i)
                    slow = jax.random.bernoulli(k, fed.straggler_prob)
                    frac = jax.random.uniform(jax.random.fold_in(k, 1))
                    return jnp.where(
                        slow, (frac * num_steps).astype(jnp.int32),
                        num_steps)

                budgets = jnp.minimum(budgets, jax.vmap(budget_for)(ids))
            lr_scale = strategies.lr_scale_for_round(fed, round_idx)
            res = jax.vmap(
                update, in_axes=(None, 0, 0, 0, 0, 0, None)
            )(params, x, y, counts, keys, budgets, lr_scale)
            contrib = res.completed & (res.num_examples > 0) & keep
            weights = res.num_examples.astype(jnp.float32) * contrib
            return res, contrib, weights

        def chunk_fn(key, params, x, y, counts, ids, round_idx, budgets,
                     keep):
            res, contrib, weights = core(key, params, x, y, counts, ids,
                                         round_idx, budgets, keep)
            wsum = pytrees.tree_weighted_sum(res.delta, weights)
            total_w = jnp.sum(weights)
            loss_sum = jnp.sum(res.mean_loss * weights)
            n_comp = jnp.sum(contrib.astype(jnp.int32))
            return wsum, total_w, loss_sum, n_comp

        if not observe:
            return jax.jit(chunk_fn)

        def obs_chunk_fn(key, params, x, y, counts, ids, round_idx,
                         budgets, keep, classes):
            res, contrib, weights = core(key, params, x, y, counts, ids,
                                         round_idx, budgets, keep)
            wsum = pytrees.tree_weighted_sum(res.delta, weights)
            total_w = jnp.sum(weights)
            loss_sum = jnp.sum(res.mean_loss * weights)
            n_comp = jnp.sum(contrib.astype(jnp.int32))
            # Per-device update norm, zeroed for non-contributors (and
            # for padding lanes, whose keep mask is False).
            sq = sum(jnp.sum(jnp.square(leaf),
                             axis=tuple(range(1, leaf.ndim)))
                     for leaf in jax.tree.leaves(res.delta))
            dev_norms = jnp.sqrt(sq) * contrib
            # Per-home-class weighted delta sums: the cohort-attribution
            # numerators (num_classes is static — one extra signature).
            class_w = jax.ops.segment_sum(weights, classes, num_classes)
            class_wsum = jax.tree.map(
                lambda leaf: jax.ops.segment_sum(
                    leaf * weights.reshape((-1,) + (1,) * (leaf.ndim - 1)),
                    classes, num_classes),
                res.delta)
            return ((wsum, total_w, loss_sum, n_comp),
                    dev_norms, (class_wsum, class_w))

        return jax.jit(obs_chunk_fn)

    def _build_finish_fn(self):
        """The engine's round epilogue (fed/programs.finish_round, plain
        path): zero-contributor rounds are a no-op server update."""
        fed = self.config.fed

        def finish(server_state, wsum, total_w, loss_sum, n_comp):
            denom = jnp.where(total_w > 0, total_w, 1.0)
            mean_delta = pytrees.tree_scale(
                wsum, jnp.where(total_w > 0, 1.0 / denom, 0.0))
            new_state = strategies.server_update(server_state, mean_delta,
                                                 fed)
            metrics = {
                "train_loss": loss_sum / denom,
                "completed": n_comp,
                "total_weight": total_w,
            }
            # mean_delta rides along for the convergence observatory —
            # already materialized, so exposing it costs nothing on the
            # default plane (it is simply never fetched).
            return new_state, mean_delta, metrics

        return jax.jit(finish)

    def _zero_acc(self):
        wsum = jax.tree.map(
            lambda l: jnp.zeros(l.shape, jnp.float32),
            self.server_state.params)
        return (wsum, jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))

    # ------------------------------------------------------------ faults --
    def _resolve_faults(self, ids: np.ndarray, round_idx: int):
        """Host-side fault resolution for the round cohort: one
        ``plan.match`` per cohort device on the ``(device, round,
        op="train")`` key — the same key space the transport injector
        consumes (faults/inject.py), so one plan drives every plane.
        Returns ``(keep_weight, budget_scale_ms, uplink_ok, stats)``."""
        n = ids.shape[0]
        keep = np.ones(n, bool)          # contributes to the aggregate
        uplink = np.ones(n, bool)        # spends uplink bytes
        trains = np.ones(n, bool)        # runs local training at all
        lost_ms = np.zeros(n, np.float64)
        plan = self.fault_plan
        if plan is None:
            from colearn_federated_learning_tpu.faults import inject

            plan = inject.active_plan()
        stats = {"dropped": 0, "straggled": 0, "corrupted": 0}
        if plan is None:
            return keep, trains, uplink, lost_ms, stats
        for j in range(n):
            did = str(int(ids[j]))
            fired = plan.match(did, round_idx, "train",
                               kinds=_FLEET_FAULT_KINDS, site="server")
            for f in fired:
                _count_fault(f.kind)
                if f.kind == "drop_request":
                    keep[j] = uplink[j] = trains[j] = False
                    stats["dropped"] += 1
                    if self.health is not None:
                        self.health.record(did, round=round_idx,
                                           deadline_miss=1)
                elif f.kind == "delay":
                    lost_ms[j] += f.ms
                    stats["straggled"] += 1
                    if self.health is not None:
                        # The injected delay IS this device's observed
                        # extra latency in the simulated plane.
                        self.health.record(did, round=round_idx,
                                           latency_s=f.ms / 1000.0)
                elif f.kind == "corrupt_payload":
                    keep[j] = False
                    stats["corrupted"] += 1
                    if self.health is not None:
                        self.health.record(did, round=round_idx,
                                           corrupt_frame=1)
        return keep, trains, uplink, lost_ms, stats

    # ------------------------------------------------------------- round --
    def run_round(self) -> dict:
        """One simulated federated round over a traffic-sampled cohort."""
        r = len(self.history)
        t0 = time.perf_counter()
        reg = telemetry.get_registry()
        with self.tracer.span("fleet_round", round=r):
            with self.tracer.span("cohort_sample", round=r):
                ids = np.asarray(self._select_fn(r), np.int64)
            keep_w, trains, uplink, lost_ms, fstats = self._resolve_faults(
                ids, r)
            budgets = self._budget_fn(ids).astype(np.int32)
            if np.any(lost_ms > 0):
                frac = np.clip(1.0 - lost_ms / self.round_deadline_ms,
                               0.0, 1.0)
                budgets = np.minimum(
                    budgets, np.floor(frac * self.num_steps)).astype(
                        np.int32)
            # Dropped devices never train: zero budget AND zero weight
            # (the masked scan still runs their lane — shapes are
            # static — but no step executes and nothing aggregates).
            budgets = np.where(trains, budgets, 0)

            n = ids.shape[0]
            chunk = self.chunk_size
            padded = max(chunk, ((n + chunk - 1) // chunk) * chunk)
            ids_pad = np.zeros(padded, np.int64)
            ids_pad[:n] = ids
            keep_pad = np.zeros(padded, bool)
            keep_pad[:n] = keep_w
            bud_pad = np.zeros(padded, np.int32)
            bud_pad[:n] = budgets

            params = self.server_state.params
            acc = self._zero_acc()
            r_dev = jnp.asarray(r, jnp.int32)
            observing = self._learn is not None
            if observing:
                cls_pad = np.zeros(padded, np.int32)
                if self._population is not None:
                    cls_pad[:n] = self._population.home_classes(ids)
                dev_norm_parts: list = []
                class_acc = None
            with self.tracer.span("train_chunks", round=r, cohort=n,
                                  chunks=padded // chunk):
                if n:
                    for lo in range(0, padded, chunk):  # colearn: hot
                        # Child span per chunk: trace-summary renders the
                        # sweep's phase mix instead of one opaque block
                        # (recording is gated on tracer.enabled; timing
                        # costs two clock reads).
                        with self.tracer.span("train_chunk", round=r,
                                              chunk=lo // chunk):
                            sl = slice(lo, lo + chunk)
                            cx, cy, cc = self._shard_fn(ids_pad[sl])
                            if observing:
                                part, dn, cpart = self._obs_program()(
                                    self.base_key, params, cx, cy, cc,
                                    ids_pad[sl], r_dev, bud_pad[sl],
                                    keep_pad[sl], cls_pad[sl])
                                dev_norm_parts.append(dn)
                                class_acc = (cpart if class_acc is None
                                             else jax.tree.map(
                                                 jnp.add, class_acc, cpart))
                            else:
                                part = self._chunk_fn(
                                    self.base_key, params, cx, cy, cc,
                                    ids_pad[sl], r_dev, bud_pad[sl],
                                    keep_pad[sl])
                            acc = self._fold_fn(acc, part)
            with self.tracer.span("server_update", round=r) as up_sp:
                self.server_state, mean_delta, metrics = self._finish_fn(
                    self.server_state, *acc)
                out = {k: float(v)
                       for k, v in jax.device_get(metrics).items()}
                conv_sig = None
                if observing:
                    conv_sig = self._learn_round_feed(
                        r, ids, mean_delta, up_sp,
                        dev_norm_parts if n else [],
                        class_acc)

        n_trained = int(trains.sum())
        n_reporting = int(uplink.sum())
        bytes_down = n_trained * self.down_frame_bytes
        bytes_up = n_reporting * self.up_frame_bytes
        out.update(
            round=r,
            cohort=n,
            cohort_requested=self.cohort_size,
            clients_trained=n_trained,
            bytes_down_est=bytes_down,
            bytes_up_est=bytes_up,
            **fstats,
        )
        if conv_sig:
            # conv_* learning-health keys only under --learn-observe —
            # default round records stay byte-identical (pinned by test).
            out.update(conv_sig)
        if self.gather_avoided_bytes:
            # Key present only under a sharded server (tp_size > 1), so
            # default round records stay byte-identical.  One broadcast
            # encode per round → one per-encode avoidance charge.
            out["bytes_gather_avoided_est"] = self.gather_avoided_bytes
            reg.counter("fleetsim.bytes_gather_avoided_est_total").inc(
                self.gather_avoided_bytes)
        if self.up_saved_bytes:
            # Uplink codec on (fed.compress != "none"): same conditional-
            # key convention as above.
            bytes_up_saved = n_reporting * self.up_saved_bytes
            out["bytes_up_saved_est"] = bytes_up_saved
            reg.counter("fleetsim.bytes_up_saved_est_total").inc(
                bytes_up_saved)
        if self._available_fraction_fn is not None:
            frac = self._available_fraction_fn(r)
            out["available_fraction"] = frac
            reg.gauge("fleetsim.available_fraction").set(frac)
        out["round_time_s"] = time.perf_counter() - t0
        if self.health is not None:
            # Durable once per round; health_* keys only when the plane
            # is on (default records stay byte-identical).
            self.health.flush()
            out.update(telemetry.health_record_keys(self.health.devices()))
        reg.counter("fleetsim.rounds_total").inc()
        reg.counter("fleetsim.clients_trained_total").inc(n_trained)
        reg.counter("fleetsim.bytes_down_est_total").inc(bytes_down)
        reg.counter("fleetsim.bytes_up_est_total").inc(bytes_up)
        reg.histogram("fleetsim.round_time_s").observe(out["round_time_s"])
        self.history.append(out)
        return out

    def _obs_program(self):
        """Lazily-built observatory chunk program: it needs the
        population's ``num_classes`` (from_learner planes lack one and
        fall back to a single bucket), and building it only on first use
        keeps the default plane's program set untouched."""
        if self._obs_chunk_fn is None:
            ncls = (self._population.spec.num_classes
                    if self._population is not None else 1)
            self._obs_chunk_fn = telemetry.CompileTracker(
                self._build_chunk_fn(observe=True, num_classes=ncls),
                name="fleetsim.obs_chunk")
        return self._obs_chunk_fn

    def _learn_round_feed(self, r: int, ids: np.ndarray, mean_delta,
                          span, dev_norm_parts: list, class_acc):
        """Fold the round's learning signals: aggregate norm/cos/trend
        from the observatory, per-device skew (anomalous norms feed the
        health ledger — a diverging device is a health event, same as a
        straggler), per-cohort drift attribution, span attrs, and the
        learn.* metric export.  Returns the record's conv_* dict."""
        from colearn_federated_learning_tpu.telemetry import convergence

        sig = self._learn.observe(mean_delta,
                                  lr=self.config.fed.server_lr)
        if sig is None:
            return None
        n = ids.shape[0]
        if dev_norm_parts:
            norms = np.concatenate(
                [np.asarray(p) for p in dev_norm_parts])[:n]
            contributors = norms > 0.0
            if contributors.any():
                sk = convergence.device_skew(norms[contributors])
                sig["conv_norm_median"] = round(sk["median"], 8)
                sig["conv_norm_p90"] = round(sk["p90"], 8)
                sig["conv_norm_anomalies"] = len(sk["anomalies"])
                if self.health is not None and sk["anomalies"]:
                    cids = ids[contributors]
                    for idx in sk["anomalies"]:
                        self.health.record(str(int(cids[idx])), round=r,
                                           norm_anomaly=1)
        if class_acc is not None and self._population is not None:
            class_wsum, class_w = class_acc
            sig.update(convergence.cohort_skew(
                class_wsum, np.asarray(class_w), mean_delta))
        span.attrs["conv_update_norm"] = sig["conv_update_norm"]
        span.attrs["conv_trend"] = sig["conv_trend"]
        if "conv_cos_prev" in sig:
            span.attrs["conv_cos_prev"] = sig["conv_cos_prev"]
        self._learn.export_metrics(telemetry.get_registry(), sig)
        return sig

    @property
    def compile_counts(self) -> dict:
        """Distinct XLA signatures per jitted program.  The chunked-vmap
        invariant — zero-padding makes every chunk the same shape — holds
        exactly when ``chunk`` stays at 1 across a whole sweep."""
        out = {
            "chunk": self._chunk_fn.compiles,
            "finish": self._finish_fn.compiles,
            "fold": self._fold_fn.compiles,
        }
        if self._obs_chunk_fn is not None:
            # Observatory program, present only under --learn-observe —
            # the default trio above is contract-pinned.
            out["obs_chunk"] = self._obs_chunk_fn.compiles
        return out

    def fit(self, rounds: int, log_fn=None) -> list[dict]:
        for _ in range(rounds):
            rec = self.run_round()
            if log_fn is not None:
                log_fn(rec)
        return self.history

    # ------------------------------------------------------------- async --
    def _async_arrival_wait(self, rng, ids: np.ndarray,
                            now_min: float) -> np.ndarray:
        """Minutes until each device's NEXT check-in, drawn from the
        diurnal-Poisson traffic model at sim time ``now_min``: the
        per-device arrival rate is recovered from the model's window
        probability (p = 1 - exp(-rate * window)), so the async plane
        consumes the exact rates the sync cohort sampler does."""
        spec = self._traffic.spec
        rnd = int(now_min / spec.round_minutes)
        p = np.clip(self._traffic.availability_probability(rnd, ids),
                    1e-6, 1.0 - 1e-9)
        rate_per_min = -np.log1p(-p) / spec.round_minutes
        return rng.exponential(1.0, size=ids.shape[0]) / rate_per_min

    def fit_async(
        self,
        aggregations: int,
        buffer_size=32,
        *,
        staleness_exponent: float = 0.5,
        max_staleness: int = 10,
        prune_after: int = 0,
        probation: int = 8,
        straggler_fraction: float = 0.05,
        straggler_multiplier: float = 20.0,
        observe: bool = False,
        auto_interval_min: Optional[float] = None,
        aggregators: int = 0,
        log_fn=None,
    ) -> list[dict]:
        """Buffered-asynchronous simulation (FedBuff semantics over the
        chunked-vmap hot path): devices check in on the diurnal-Poisson
        traffic model, train against the model version current at
        dispatch, and the server folds every ``buffer_size`` completions
        with staleness weights ``(1 + tau)^(-staleness_exponent)``,
        discarding updates staler than ``max_staleness``.

        The event clock is virtual (sim minutes): per-device service
        time is lognormal around the traffic model's round window, with
        a seeded ``straggler_fraction`` of chronic stragglers at
        ``straggler_multiplier`` x — the tail that bounds a SYNC round
        but not async throughput, which tracks the arrival rate
        (``arrival_rate_per_min`` vs ``agg_rate_per_min`` in the
        records; scripts/bench_fleet.py --async-sweep scales the same
        model analytically to 1M devices).

        ``prune_after`` > 0 arms the coordinator's straggler-pruning
        policy in the sim: a device whose updates are discarded
        too-stale ``prune_after`` times consecutively stops being
        re-dispatched for ``probation`` aggregations — pruned runs must
        waste measurably fewer updates at equal final loss (the
        ``fleet_async_prune`` bench gate).  Groups the buffer by
        dispatch version and reuses the round-path chunk/fold/finish
        programs, so the compile-once invariant holds (chunk shapes stay
        ``chunk_size``-padded).

        ``buffer_size="auto"`` sizes K from the seeded-EWMA arrival-rate
        estimator before every aggregation (K = observed rate × fold
        fraction × ``auto_interval_min``, the target fold cadence;
        default ``round_minutes``; resizes slew-limited to ±50%) — the
        diurnal traffic model makes the rate swing, and auto-K keeps the
        fold cadence in band instead of letting a fixed K's cadence
        (and the stragglers' realized τ) swing with it.  ``observe`` stamps observatory keys (staleness
        tail, contribution mass, EWMA arrival rate) into records;
        implied by auto-K, off by default so default async records stay
        byte-identical.

        ``aggregators`` > 0 switches to the TWO-TIER tree-async plane
        (:meth:`_fit_async_tree`): per-slice buffers with their own
        auto-K, partials folded unscaled at the edge and staleness-
        discounted at the root against the partial's OLDEST constituent
        version.  Default (0) records stay byte-identical."""
        import heapq

        if aggregators:
            return self._fit_async_tree(
                aggregations, aggregators, buffer_size,
                staleness_exponent=staleness_exponent,
                max_staleness=max_staleness, prune_after=prune_after,
                probation=probation,
                straggler_fraction=straggler_fraction,
                straggler_multiplier=straggler_multiplier,
                observe=observe, auto_interval_min=auto_interval_min,
                log_fn=log_fn)
        if self._traffic is None:
            raise NotImplementedError(
                "fit_async needs the traffic model: build the sim with "
                "FleetSim.from_population")
        n_dev = self.num_devices
        auto_buffer = isinstance(buffer_size, str)
        if auto_buffer:
            if buffer_size != "auto":
                raise ValueError(
                    f"buffer_size must be an int >= 1 or 'auto', "
                    f"got {buffer_size!r}")
            buffer_size = min(8, n_dev)   # warm-start K
        elif buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        if buffer_size > n_dev:
            raise ValueError(
                f"buffer_size {buffer_size} exceeds the {n_dev}-device "
                "fleet — the buffer could never fill")
        if buffer_size > self.chunk_size:
            raise ValueError(
                f"buffer_size {buffer_size} exceeds chunk_size "
                f"{self.chunk_size} — the version-grouped fold pads "
                "each group to one compiled chunk dispatch")
        observe = bool(observe) or auto_buffer
        spec = self._traffic.spec
        if auto_interval_min is None:
            auto_interval_min = spec.round_minutes
        # Arrival-rate estimator on the VIRTUAL clock (sim minutes) —
        # rates come out per sim-minute, the same unit as the records.
        est = telemetry.ArrivalEstimator()
        rng = np.random.default_rng(
            np.random.SeedSequence([self.config.run.seed, 0xA51C]))
        # Per-device service time (sim minutes): lognormal around the
        # traffic window, chronic stragglers seeded at the head of a
        # permutation so the set is deterministic per (seed, fleet).
        service = spec.round_minutes * rng.lognormal(
            0.0, 0.5, size=n_dev)
        n_slow = int(round(straggler_fraction * n_dev))
        slow_ids = rng.permutation(n_dev)[:n_slow]
        service[slow_ids] *= straggler_multiplier
        reg = telemetry.get_registry()
        reg.gauge("fleetsim.async_buffer_size").set(buffer_size)

        version = 0
        ring: dict[int, object] = {0: self.server_state.params}
        heap: list = []          # (t_done, seq, device_id, version)
        seq = 0
        all_ids = np.arange(n_dev, dtype=np.int64)
        wait0 = self._async_arrival_wait(rng, all_ids, 0.0)
        for d in range(n_dev):
            heapq.heappush(heap, (wait0[d] + service[d], seq, d, 0))
            seq += 1
        now = 0.0
        arrivals = 0
        wasted = 0
        stale_streak: dict[int, int] = {}
        pruned: dict[int, int] = {}   # device -> aggregation to re-admit
        pruned_total = 0
        base_len = len(self.history)
        start = time.perf_counter()

        def redispatch(d: int, t: float) -> None:
            nonlocal seq
            wait = float(self._async_arrival_wait(
                rng, np.asarray([d], np.int64), t)[0])
            heapq.heappush(heap, (t + wait + service[d], seq, d, version))
            seq += 1

        for agg in range(aggregations):
            t0 = time.perf_counter()
            # Probation re-admission first: a re-admitted device rejoins
            # the arrival stream at the current version, clean streak.
            for d in [d for d, until in pruned.items() if until <= agg]:
                del pruned[d]
                stale_streak.pop(d, None)
                redispatch(d, now)
            if auto_buffer:
                # Retune K to the observed arrival rate: one fold per
                # auto_interval_min, clamped to the active (un-pruned)
                # fleet — only that many updates can be in flight while
                # the buffer fills.  Only FOLDED arrivals fill the
                # buffer, so the target interval is scaled by the
                # observed fold fraction — sizing K off raw arrivals
                # overshoots exactly when staleness discards bite, and
                # the realized cadence drifts out of the band.
                # K is also clamped to chunk_size: the version-grouped
                # fold pads each group to ONE compiled chunk dispatch,
                # so a buffer wider than the chunk could overflow a
                # group.
                fold_frac = 1.0 - wasted / arrivals if arrivals else 1.0
                k = est.recommend_buffer(
                    auto_interval_min * max(fold_frac, 0.05), lo=1,
                    hi=max(1, min(self.chunk_size, n_dev - len(pruned))),
                    current=buffer_size)
                # Slew-limit the resize: the rate estimate trails the
                # diurnal swing by one fill, so jumping straight to the
                # recommendation overshoots the band it is chasing.
                k = int(np.clip(k, max(1, buffer_size // 2),
                                max(2, buffer_size * 3 // 2)))
                if k != buffer_size:
                    reg.counter(
                        "fleetsim.async_buffer_resizes_total").inc()
                    buffer_size = k
                reg.gauge("fleetsim.async_buffer_size").set(buffer_size)
            buffered: list[tuple[int, int]] = []   # (device, version)
            discarded = 0
            mass_folded = 0.0
            mass_discarded = 0.0
            while len(buffered) < buffer_size:
                t_done, _, d, v = heapq.heappop(heap)
                now = max(now, t_done)
                arrivals += 1
                est.observe(str(d), now=now)
                tau = version - v
                if tau > max_staleness:
                    # Too stale: wasted compute + uplink.  The chronic
                    # stragglers this counts are what pruning exists to
                    # stop paying for.
                    discarded += 1
                    wasted += 1
                    s_w = float((1.0 + tau) ** -staleness_exponent)
                    mass_discarded += s_w
                    reg.counter(
                        "fleetsim.async_contribution_mass",
                        labels={"outcome": "discarded"}).inc(s_w)
                    reg.histogram(
                        "fleetsim.async_staleness",
                        labels={"outcome": "discarded"}).observe(
                            float(tau))
                    reg.counter(
                        "fleetsim.async_updates_discarded_total").inc()
                    streak = stale_streak.get(d, 0) + 1
                    stale_streak[d] = streak
                    if (prune_after > 0 and streak >= prune_after
                            and n_dev - len(pruned) - 1 >= buffer_size):
                        pruned[d] = agg + probation
                        pruned_total += 1
                        reg.counter(
                            "fleetsim.async_devices_pruned_total").inc()
                    else:
                        redispatch(d, now)
                    continue
                stale_streak.pop(d, None)
                s_w = float((1.0 + tau) ** -staleness_exponent)
                mass_folded += s_w
                reg.counter("fleetsim.async_contribution_mass",
                            labels={"outcome": "folded"}).inc(s_w)
                reg.histogram("fleetsim.async_staleness",
                              labels={"outcome": "folded"}).observe(
                                  float(tau))
                buffered.append((d, v))

            # Fold the buffer grouped by dispatch version: every update
            # in a group trained against the same ring snapshot, so one
            # chunk dispatch per group reuses the compiled round
            # programs on chunk_size-padded shapes.
            acc = self._zero_acc()
            stalenesses = [version - v for _, v in buffered]
            for v in sorted({v for _, v in buffered}):
                ids = np.asarray([d for d, dv in buffered if dv == v],
                                 np.int64)
                s_w = float((1.0 + (version - v)) ** -staleness_exponent)
                padded = np.zeros(self.chunk_size, np.int64)
                padded[:ids.shape[0]] = ids
                keep = np.zeros(self.chunk_size, bool)
                keep[:ids.shape[0]] = True
                budgets = np.zeros(self.chunk_size, np.int32)
                budgets[:ids.shape[0]] = self._budget_fn(ids).astype(
                    np.int32)
                cx, cy, cc = self._shard_fn(padded)
                part = self._chunk_fn(
                    self.base_key, ring[v], cx, cy, cc, padded,
                    jnp.asarray(v, jnp.int32), budgets, keep)
                wsum, total_w, loss_sum, n_comp = part
                part = (pytrees.tree_scale(wsum, s_w), total_w * s_w,
                        loss_sum * s_w, n_comp)
                acc = self._fold_fn(acc, part)
            self.server_state, mean_delta, metrics = self._finish_fn(
                self.server_state, *acc)
            out = {k: float(v) for k, v in jax.device_get(metrics).items()}
            conv_sig = None
            if self._learn is not None:
                conv_sig = self._learn.observe(
                    mean_delta, lr=self.config.fed.server_lr)
                if conv_sig:
                    self._learn.export_metrics(reg, conv_sig)
            version += 1
            ring[version] = self.server_state.params
            for v in [v for v in ring if v < version - max_staleness]:
                del ring[v]
            for d, _ in buffered:
                redispatch(d, now)

            rec = {
                "aggregation": base_len + agg,
                "model_version": version,
                "buffer_size": buffer_size,
                "staleness_mean": float(np.mean(stalenesses)),
                "staleness_max": int(np.max(stalenesses)),
                "discarded": discarded,
                "contributors": len(buffered),
                "train_loss": out["train_loss"],
                "total_weight": out["total_weight"],
                "sim_time_min": now,
                "arrival_rate_per_min": arrivals / max(now, 1e-9),
                "agg_rate_per_min": (agg + 1) / max(now, 1e-9),
                "wasted_updates_total": wasted,
                "agg_time_s": time.perf_counter() - t0,
            }
            reg.gauge("fleetsim.async_arrival_rate_per_min").set(
                est.rate())
            if observe:
                # Observatory keys — only when observe/auto-K is on, so
                # default async records stay byte-identical.
                rec["arrival_rate_ewma_per_min"] = round(est.rate(), 6)
                rec["mass_folded"] = round(mass_folded, 6)
                rec["mass_discarded"] = round(mass_discarded, 6)
                hs = reg.histogram(
                    "fleetsim.async_staleness",
                    labels={"outcome": "folded"}).summary()
                if hs.get("count"):
                    rec["staleness_p50"] = hs["p50"]
                    rec["staleness_p90"] = hs["p90"]
                    rec["staleness_p99"] = hs["p99"]
            if prune_after > 0:
                # Conditional keys, same convention as the socket plane:
                # default async records stay byte-identical with the
                # feature off.
                rec["pruned"] = len(pruned)
                rec["pruned_total"] = pruned_total
            if conv_sig:
                # conv_* learning-health keys only under --learn-observe.
                rec.update(conv_sig)
            reg.counter("fleetsim.async_aggregations_total").inc()
            self.history.append(rec)
            if log_fn is not None:
                log_fn(rec)
        reg.gauge("fleetsim.async_sim_minutes").set(now)
        reg.histogram("fleetsim.round_time_s").observe(
            time.perf_counter() - start)
        return self.history

    def _fit_async_tree(
        self,
        aggregations: int,
        aggregators: int,
        buffer_size,
        *,
        staleness_exponent: float,
        max_staleness: int,
        prune_after: int,
        probation: int,
        straggler_fraction: float,
        straggler_multiplier: float,
        observe: bool,
        auto_interval_min: Optional[float],
        log_fn,
    ) -> list[dict]:
        """Two-tier buffered-async: per-slice aggregator buffers over the
        same virtual event clock as :meth:`fit_async`.

        Devices are sliced across ``aggregators`` by SERVICE TIME
        (sorted, contiguous divmod) — the health-driven assignment the
        socket plane computes from ledger scores, which concentrates
        chronic stragglers in the last slice so their deep buffer
        absorbs the tail instead of every buffer carrying a piece of it.
        Each slice runs its own :class:`~.telemetry.ArrivalEstimator`
        and auto-K buffer (slew-limited to ±50% per retune, the same
        band as the flat auto-K): one partial per ``auto_interval_min``
        of that slice's measured arrival rate.

        A full slice buffer ships a PARTIAL: its version groups fold
        UNSCALED at the edge (the aggregator cannot know the root's
        version when contributions keep arriving), and the root scales
        the whole partial by ``(1 + tau)^-exp`` where ``tau`` is
        measured against the partial's OLDEST constituent version —
        exactly the socket tree-async plane's semantics.  A partial
        whose oldest constituent exceeds ``max_staleness`` is discarded
        WHOLE (``fleetsim.async_partials_discarded_total``); one root
        aggregation applies one surviving partial.

        Per-slice fold-cadence tracking: ``agg_fold_tracking_min`` is
        the worst slice's ``min(r, 1/r)`` for ``r = realized mean ship
        interval / target interval`` — 1.0 when every buffer folds on
        cadence, sagging toward 0 when a slice folds far too rarely
        (starved) OR far too often (K undersized).  The
        ``fleet_tree_async`` bench sentinel holds the floor."""
        import heapq

        if self._traffic is None:
            raise NotImplementedError(
                "fit_async needs the traffic model: build the sim with "
                "FleetSim.from_population")
        n_dev = self.num_devices
        if aggregators < 2:
            raise ValueError(
                f"tree-async needs >= 2 aggregators, got {aggregators}")
        if aggregators > n_dev:
            raise ValueError(
                f"{aggregators} aggregators exceed the {n_dev}-device "
                "fleet — a slice would be empty")
        warm = 8 if isinstance(buffer_size, str) else int(buffer_size)
        if not isinstance(buffer_size, str) and buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        observe = True      # tree mode is always auto-K (implies observe)
        spec = self._traffic.spec
        if auto_interval_min is None:
            auto_interval_min = spec.round_minutes
        rng = np.random.default_rng(
            np.random.SeedSequence([self.config.run.seed, 0xA51C]))
        service = spec.round_minutes * rng.lognormal(
            0.0, 0.5, size=n_dev)
        n_slow = int(round(straggler_fraction * n_dev))
        slow_ids = rng.permutation(n_dev)[:n_slow]
        service[slow_ids] *= straggler_multiplier
        reg = telemetry.get_registry()

        # Service-time-sorted contiguous slices: slice 0 gets the fast
        # devices, the last slice the stragglers (deep buffer).
        order = np.argsort(service, kind="stable")
        base, extra = divmod(n_dev, aggregators)
        slice_of = np.empty(n_dev, np.int64)
        slice_ids: list[np.ndarray] = []
        pos = 0
        for a in range(aggregators):
            size = base + (1 if a < extra else 0)
            members = order[pos:pos + size]
            slice_of[members] = a
            slice_ids.append(members)
            pos += size

        ests = [telemetry.ArrivalEstimator() for _ in range(aggregators)]
        ks = [max(1, min(warm, len(slice_ids[a]), self.chunk_size))
              for a in range(aggregators)]
        buffers: list[list[tuple[int, int]]] = [[] for _ in
                                                range(aggregators)]
        ship_times: list[list[float]] = [[] for _ in range(aggregators)]
        partials_folded = [0] * aggregators

        version = 0
        ring: dict[int, object] = {0: self.server_state.params}
        heap: list = []          # (t_done, seq, device_id, version)
        seq = 0
        all_ids = np.arange(n_dev, dtype=np.int64)
        wait0 = self._async_arrival_wait(rng, all_ids, 0.0)
        for d in range(n_dev):
            heapq.heappush(heap, (wait0[d] + service[d], seq, d, 0))
            seq += 1
        now = 0.0
        arrivals = 0
        wasted = 0
        stale_streak: dict[int, int] = {}
        pruned: dict[int, int] = {}   # device -> aggregation to re-admit
        pruned_total = 0
        base_len = len(self.history)
        start = time.perf_counter()

        def redispatch(d: int, t: float) -> None:
            nonlocal seq
            wait = float(self._async_arrival_wait(
                rng, np.asarray([d], np.int64), t)[0])
            heapq.heappush(heap, (t + wait + service[d], seq, d, version))
            seq += 1

        def retune(a: int) -> None:
            # Auto-K on the slice's OWN arrival rate, slew-limited so
            # the resize trails the diurnal swing instead of chasing it.
            cur = ks[a]
            active = sum(1 for d in slice_ids[a] if int(d) not in pruned)
            hi = max(1, min(self.chunk_size, active))
            k = ests[a].recommend_buffer(auto_interval_min, lo=1, hi=hi,
                                         current=cur)
            k = int(np.clip(k, max(1, cur // 2), max(2, cur * 3 // 2)))
            k = max(1, min(k, hi))
            if k != cur:
                reg.counter("fleetsim.async_buffer_resizes_total").inc()
            ks[a] = k

        def tracking_min() -> float:
            # Per-slice cadence tracking: realized mean ship interval vs
            # the interval auto-K can actually DELIVER for this slice —
            # the target clipped into the achievable band [1/rate,
            # hi/rate] (K is an integer in [1, hi]; a slice whose
            # arrival rate over- or under-shoots the band is capacity-
            # limited, not mistracking).  Trailing window (last 5
            # intervals) so the warm-start transient ages out; ``min(r,
            # 1/r)`` sags on a buffer folding far off its own band —
            # starved, stuck, or thrashing — which is what the
            # ``fleet_tree_async`` sentinel floors.
            vals = []
            for a in range(aggregators):
                rate = ests[a].rate()
                active = sum(1 for d in slice_ids[a]
                             if int(d) not in pruned)
                hi = max(1, min(self.chunk_size, active))
                t_eff = auto_interval_min
                if rate > 0:
                    t_eff = float(np.clip(auto_interval_min,
                                          1.0 / rate, hi / rate))
                ts = ship_times[a][-6:]
                if len(ts) >= 2:
                    realized = (ts[-1] - ts[0]) / (len(ts) - 1)
                    r = realized / max(t_eff, 1e-9)
                    vals.append(min(r, 1.0 / r) if r > 0 else 0.0)
                elif len(ts) == 1:
                    vals.append(1.0)   # one ship — no interval yet
                else:
                    # Never shipped: on cadence only while younger than
                    # two achievable intervals.
                    vals.append(1.0 if now <= 2 * t_eff else 0.0)
            return round(min(vals), 6)

        for agg in range(aggregations):
            t0 = time.perf_counter()
            for d in [d for d, until in pruned.items() if until <= agg]:
                del pruned[d]
                stale_streak.pop(d, None)
                redispatch(d, now)
            discarded_partials = 0
            mass_folded = 0.0
            mass_discarded = 0.0
            while True:
                # Pump arrivals into slice buffers until one fills.
                while True:
                    t_done, _, d, v = heapq.heappop(heap)
                    now = max(now, t_done)
                    arrivals += 1
                    a = int(slice_of[d])
                    ests[a].observe(str(d), now=now)
                    buffers[a].append((int(d), int(v)))
                    if len(buffers[a]) >= ks[a]:
                        break
                batch, buffers[a] = buffers[a], []
                k_ship = ks[a]
                ship_times[a].append(now)
                retune(a)
                oldest = min(v for _, v in batch)
                tau = version - oldest
                s_w = float((1.0 + tau) ** -staleness_exponent)
                if tau > max_staleness:
                    # Whole-partial discard: the root cannot unpick one
                    # constituent out of a pre-folded sum.
                    discarded_partials += 1
                    wasted += len(batch)
                    reg.counter(
                        "fleetsim.async_partials_discarded_total").inc()
                    for dd, dv in batch:
                        dtau = version - dv
                        dw = float((1.0 + dtau) ** -staleness_exponent)
                        mass_discarded += dw
                        reg.counter(
                            "fleetsim.async_contribution_mass",
                            labels={"outcome": "discarded"}).inc(dw)
                        reg.histogram(
                            "fleetsim.async_staleness",
                            labels={"outcome": "discarded"}).observe(
                                float(dtau))
                        reg.counter(
                            "fleetsim.async_updates_discarded_total").inc()
                        # Prune streaks accrue only to devices whose OWN
                        # contribution was too stale — fresh constituents
                        # batched with a stale one are collateral of the
                        # whole-partial discard, not stragglers.
                        if dtau > max_staleness:
                            streak = stale_streak.get(dd, 0) + 1
                            stale_streak[dd] = streak
                        else:
                            streak = 0
                        active = sum(1 for x in slice_ids[a]
                                     if int(x) not in pruned)
                        if (prune_after > 0 and streak >= prune_after
                                and active > 1):
                            pruned[dd] = agg + probation
                            pruned_total += 1
                            reg.counter(
                                "fleetsim.async_devices_pruned_total"
                            ).inc()
                        else:
                            redispatch(dd, now)
                    continue
                break

            # Fold the partial: version groups UNSCALED at the edge,
            # then one root-side staleness discount for the whole
            # partial keyed off its oldest constituent.
            stalenesses = [version - v for _, v in batch]
            acc = self._zero_acc()
            for v in sorted({v for _, v in batch}):
                ids = np.asarray([dd for dd, dv in batch if dv == v],
                                 np.int64)
                padded = np.zeros(self.chunk_size, np.int64)
                padded[:ids.shape[0]] = ids
                keep = np.zeros(self.chunk_size, bool)
                keep[:ids.shape[0]] = True
                budgets = np.zeros(self.chunk_size, np.int32)
                budgets[:ids.shape[0]] = self._budget_fn(ids).astype(
                    np.int32)
                cx, cy, cc = self._shard_fn(padded)
                part = self._chunk_fn(
                    self.base_key, ring[v], cx, cy, cc, padded,
                    jnp.asarray(v, jnp.int32), budgets, keep)
                acc = self._fold_fn(acc, part)
            wsum, total_w, loss_sum, n_comp = acc
            acc = (pytrees.tree_scale(wsum, s_w), total_w * s_w,
                   loss_sum * s_w, n_comp)
            self.server_state, mean_delta, metrics = self._finish_fn(
                self.server_state, *acc)
            out = {k: float(x) for k, x in jax.device_get(metrics).items()}
            conv_sig = None
            if self._learn is not None:
                conv_sig = self._learn.observe(
                    mean_delta, lr=self.config.fed.server_lr)
                if conv_sig:
                    self._learn.export_metrics(reg, conv_sig)
            for dd, dv in batch:
                stale_streak.pop(dd, None)
                dtau = version - dv
                dw = float((1.0 + dtau) ** -staleness_exponent)
                mass_folded += dw
                reg.counter("fleetsim.async_contribution_mass",
                            labels={"outcome": "folded"}).inc(dw)
                reg.histogram("fleetsim.async_staleness",
                              labels={"outcome": "folded"}).observe(
                                  float(dtau))
            partials_folded[a] += 1
            reg.counter("fleetsim.async_partials_folded_total").inc()
            version += 1
            ring[version] = self.server_state.params
            for v in [v for v in ring if v < version - max_staleness]:
                del ring[v]
            for dd, _ in batch:
                redispatch(dd, now)

            rec = {
                "aggregation": base_len + agg,
                "model_version": version,
                "buffer_size": k_ship,
                "staleness_mean": float(np.mean(stalenesses)),
                "staleness_max": int(np.max(stalenesses)),
                "discarded": discarded_partials,
                "contributors": len(batch),
                "train_loss": out["train_loss"],
                "total_weight": out["total_weight"],
                "sim_time_min": now,
                "arrival_rate_per_min": arrivals / max(now, 1e-9),
                "agg_rate_per_min": (agg + 1) / max(now, 1e-9),
                "wasted_updates_total": wasted,
                "agg_time_s": time.perf_counter() - t0,
                # Tree keys (absent from flat async records).
                "aggregators": aggregators,
                "agg_id": int(a),
                "agg_buffer_k": int(ks[a]),
                "agg_fold_tracking_min": tracking_min(),
            }
            reg.gauge("fleetsim.async_buffer_size").set(ks[a])
            reg.gauge("fleetsim.async_arrival_rate_per_min").set(
                sum(e.rate() for e in ests))
            if observe:
                rec["arrival_rate_ewma_per_min"] = round(
                    sum(e.rate() for e in ests), 6)
                rec["mass_folded"] = round(mass_folded, 6)
                rec["mass_discarded"] = round(mass_discarded, 6)
                hs = reg.histogram(
                    "fleetsim.async_staleness",
                    labels={"outcome": "folded"}).summary()
                if hs.get("count"):
                    rec["staleness_p50"] = hs["p50"]
                    rec["staleness_p90"] = hs["p90"]
                    rec["staleness_p99"] = hs["p99"]
            if prune_after > 0:
                rec["pruned"] = len(pruned)
                rec["pruned_total"] = pruned_total
            if conv_sig:
                rec.update(conv_sig)
            reg.counter("fleetsim.async_aggregations_total").inc()
            self.history.append(rec)
            if log_fn is not None:
                log_fn(rec)
        reg.gauge("fleetsim.async_sim_minutes").set(now)
        reg.histogram("fleetsim.round_time_s").observe(
            time.perf_counter() - start)
        return self.history
