"""Arrival-process availability model driving fleet cohort sampling.

Real fleets are never all online: devices check in following their
owners' days (CoLearn's MUD-gated IoT fleets announce when powered;
CLIP/DisAgg in PAPERS.md study exactly this straggler/availability
regime).  The model here is the standard non-homogeneous Poisson
arrival process:

- each device has an arrival rate ``base_rate`` (expected check-ins per
  simulated hour) modulated by a diurnal sinusoid with a per-device
  phase (its timezone / usage habit, hashed from the device id);
- a device is AVAILABLE for a round iff it has >= 1 arrival inside the
  round's simulated window: ``p = 1 - exp(-rate * window)``;
- availability draws are keyed on ``(seed, device, round)`` with the
  same vectorized hash as the population, so a schedule replays
  byte-identically — the FaultPlan determinism contract extended to
  traffic.

``sample_cohort`` ranks the currently-available devices by a per-round
hashed score and takes the first ``cohort_size`` — uniform sampling
without replacement among available devices, the host-side analog of
the engine's ``_rank_cohort``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from colearn_federated_learning_tpu.fleetsim.population import hash_u01

_S_PHASE = 101
_S_ARRIVE = 111
_S_RANK = 131

_MINUTES_PER_DAY = 24.0 * 60.0


@dataclasses.dataclass(frozen=True)
class TrafficSpec:
    """Arrival-process parameters; everything derives from ``seed``."""

    base_rate: float = 2.0            # mean check-ins per device-hour
    diurnal_amplitude: float = 0.8    # 0 = flat; 1 = full day/night swing
    phase_spread: float = 0.25        # per-device phase scatter, in days:
                                      # 0 = one timezone (full fleet-level
                                      # rhythm); 1 = uniform phases (the
                                      # fleet mean flattens out)
    round_minutes: float = 10.0       # simulated wall time per round
    seed: int = 0

    def __post_init__(self):
        if self.base_rate < 0:
            raise ValueError(f"base_rate must be >= 0, got {self.base_rate}")
        if not 0.0 <= self.diurnal_amplitude <= 1.0:
            raise ValueError("diurnal_amplitude must be in [0, 1], got "
                             f"{self.diurnal_amplitude}")
        if not 0.0 <= self.phase_spread <= 1.0:
            raise ValueError("phase_spread must be in [0, 1], got "
                             f"{self.phase_spread}")
        if self.round_minutes <= 0:
            raise ValueError("round_minutes must be > 0, got "
                             f"{self.round_minutes}")


class TrafficModel:
    """Deterministic availability + cohort sampling over ``num_devices``."""

    def __init__(self, spec: TrafficSpec, num_devices: int):
        if num_devices < 1:
            raise ValueError("num_devices must be >= 1")
        self.spec = spec
        self.num_devices = int(num_devices)

    # ----------------------------------------------------------- rates --
    def availability_probability(self, round_idx: int,
                                 ids: np.ndarray) -> np.ndarray:
        """P(device has >= 1 arrival in this round's window)."""
        s = self.spec
        ids = np.asarray(ids, np.int64)
        t_min = round_idx * s.round_minutes
        # Per-device phase (its usage habit), scattered over phase_spread
        # of a day: fleets cluster in timezones, so the FLEET-level
        # rhythm survives unless spread -> 1 washes it out.
        phase = s.phase_spread * hash_u01(s.seed, _S_PHASE, ids)
        diurnal = 1.0 + s.diurnal_amplitude * np.sin(
            2.0 * np.pi * (t_min / _MINUTES_PER_DAY + phase))
        rate_per_min = s.base_rate / 60.0 * diurnal
        return -np.expm1(-rate_per_min * s.round_minutes)

    def available_mask(self, round_idx: int,
                       ids: np.ndarray | None = None) -> np.ndarray:
        """Boolean availability of ``ids`` (default: the whole fleet) for
        one round — deterministic in ``(seed, device, round)``."""
        if ids is None:
            ids = np.arange(self.num_devices, dtype=np.int64)
        ids = np.asarray(ids, np.int64)
        p = self.availability_probability(round_idx, ids)
        u = hash_u01(self.spec.seed, _S_ARRIVE + 7919 * (round_idx + 1), ids)
        return u < p

    def expected_available(self, round_idx: int) -> float:
        """Fleet-mean availability probability (capacity-planning view)."""
        ids = np.arange(self.num_devices, dtype=np.int64)
        return float(self.availability_probability(round_idx, ids).mean())

    # --------------------------------------------------------- sampling --
    def sample_cohort(self, round_idx: int, cohort_size: int) -> np.ndarray:
        """Uniform sample WITHOUT replacement among currently-available
        devices: rank by a per-(round, device) hashed score, take the
        first ``cohort_size``.  Returns fewer ids when fewer devices are
        available (the realized cohort — callers record the shortfall)."""
        avail = np.flatnonzero(self.available_mask(round_idx))
        if avail.size <= cohort_size:
            return avail.astype(np.int64)
        scores = hash_u01(self.spec.seed, _S_RANK + 7919 * (round_idx + 1),
                          avail)
        take = np.argpartition(scores, cohort_size)[:cohort_size]
        return np.sort(avail[take]).astype(np.int64)
