"""Million-device fleet simulation (ROADMAP "Million-device fleet
simulation as a first-class workload").

Real sockets cap the mp chaos soak at a handful of workers; this package
simulates 1k -> 1M clients per host by making clients a ``jax.vmap``
axis over fixed-size chunks of ``fed/local.py``'s ``local_update``:

- :mod:`.population` — seeded synthetic device population; non-IID data
  shards are materialized on demand from per-device keys (memory stays
  O(chunk), never O(fleet));
- :mod:`.traffic` — arrival-process availability (Poisson base rate x
  diurnal modulation) driving cohort sampling from available devices;
- :mod:`.sim` — the chunked-vmap round loop, reusing the engine's
  aggregation semantics and FaultPlan keys ``(device, round, op)`` for
  per-simulated-device drop/straggle/corrupt faults.
"""

from colearn_federated_learning_tpu.fleetsim.population import (
    DevicePopulation,
    PopulationSpec,
    SpeedClass,
)
from colearn_federated_learning_tpu.fleetsim.sim import FleetSim
from colearn_federated_learning_tpu.fleetsim.traffic import (
    TrafficModel,
    TrafficSpec,
)

__all__ = [
    "DevicePopulation",
    "PopulationSpec",
    "SpeedClass",
    "FleetSim",
    "TrafficModel",
    "TrafficSpec",
]
