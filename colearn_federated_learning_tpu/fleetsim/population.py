"""Seeded synthetic device population with on-demand shard materialization.

A fleet of a million devices cannot hold its data resident: at the
default shard shape that is ~4 GB of features alone.  Instead every
device's shard is a PURE FUNCTION of ``(spec.seed, device_id)`` — the
simulator materializes only the chunk of devices currently being
trained, and the same device always regenerates byte-identical data no
matter which chunk (or process) asks for it.

The per-device stream is a vectorized splitmix64 hash (the same
counter-based-key idea as ``utils/prng.py``, but numpy-side so a 4096-
device chunk materializes in one shot with no per-device Python loop):

- non-IID-ness: each device has a "home" class; ``label_skew`` of its
  labels come from it, the rest uniform — a pathological-partition
  analog with a smooth knob (data/partition.py has the exact protocols);
- features: class prototype + Gaussian noise, the ``data/synthetic.py``
  recipe;
- heterogeneous compute: every device belongs to a speed class
  (fast/standard/slow by population fraction) whose ``step_fraction``
  maps to the engine's per-client ``step_budget`` — slow devices run
  fewer of the static ``num_steps`` and fall out of the FedAvg weight
  exactly like the engine's stragglers (fed/local.py masking).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import numpy as np

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix64(z: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized over uint64 arrays."""
    with np.errstate(over="ignore"):    # mod-2^64 wraparound is the point
        z = (z + _GOLDEN)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def hash_u01(seed: int, stream: int, ids: np.ndarray) -> np.ndarray:
    """Deterministic uniforms in [0, 1): one independent draw per entry of
    ``ids``, keyed on ``(seed, stream, id)``.  53-bit mantissa precision;
    identical across processes and Python hash seeds (the same contract
    as faults/plan._hash_unit, vectorized)."""
    with np.errstate(over="ignore"):
        base = _mix64(np.uint64(seed % (1 << 63))
                      ^ (_GOLDEN * np.uint64(stream % (1 << 32))))
        h = _mix64(np.asarray(ids, np.uint64) * _GOLDEN + base)
    return (h >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))


def _hash_normal(seed: int, stream: int, ids: np.ndarray) -> np.ndarray:
    """Standard normals via Box-Muller on two hashed uniform streams."""
    u1 = hash_u01(seed, stream, ids)
    u2 = hash_u01(seed, stream + 1, ids)
    r = np.sqrt(-2.0 * np.log1p(-u1))           # log1p: u1=0 stays finite
    return r * np.cos(2.0 * np.pi * u2)


class SpeedClass(NamedTuple):
    """One compute-speed tier: ``fraction`` of the population runs
    ``step_fraction`` of the static local step budget."""

    name: str
    fraction: float
    step_fraction: float


DEFAULT_SPEED_CLASSES = (
    SpeedClass("fast", 0.50, 1.0),
    SpeedClass("standard", 0.35, 0.5),
    SpeedClass("slow", 0.15, 0.25),
)


@dataclasses.dataclass(frozen=True)
class PopulationSpec:
    """Shape of the synthetic fleet; everything derives from ``seed``."""

    num_devices: int
    num_classes: int = 10
    feature_dim: int = 32
    shard_capacity: int = 32          # padded per-device examples (static)
    min_examples: int = 8             # true count in [min, capacity]
    label_skew: float = 0.7           # P(label == home class)
    noise_scale: float = 0.3          # feature noise around the prototype
    seed: int = 0
    speed_classes: tuple = DEFAULT_SPEED_CLASSES

    def __post_init__(self):
        if self.num_devices < 1:
            raise ValueError("num_devices must be >= 1")
        if not 1 <= self.min_examples <= self.shard_capacity:
            raise ValueError(
                f"need 1 <= min_examples <= shard_capacity, got "
                f"{self.min_examples} / {self.shard_capacity}")
        if not 0.0 <= self.label_skew <= 1.0:
            raise ValueError(f"label_skew must be in [0, 1], got "
                             f"{self.label_skew}")
        total = sum(c[1] for c in self.speed_classes)
        if abs(total - 1.0) > 1e-6:
            raise ValueError(
                f"speed class fractions must sum to 1, got {total}")


# Stream tags (the population's analog of utils/prng's purpose tags).
_S_PROTO = 11
_S_COUNT = 21
_S_HOME = 31
_S_LABEL = 41
_S_NOISE = 61          # consumes 2 streams (Box-Muller)
_S_SPEED = 81


class DevicePopulation:
    """Materialize any slice of the fleet on demand.

    All methods take a vector of device ids and return arrays aligned
    with it; nothing is cached per device, so memory is bounded by the
    largest chunk ever requested.
    """

    def __init__(self, spec: PopulationSpec):
        self.spec = spec
        s = spec
        # Class prototypes: the only O(classes x features) resident state.
        grid = (np.arange(s.num_classes, dtype=np.uint64)[:, None]
                * np.uint64(s.feature_dim)
                + np.arange(s.feature_dim, dtype=np.uint64)[None, :])
        self._prototypes = _hash_normal(s.seed, _S_PROTO, grid).astype(
            np.float32)
        fracs = np.array([c[2] for c in s.speed_classes], np.float64)
        self._speed_cum = np.cumsum(
            [c[1] for c in s.speed_classes])        # class boundaries
        self._speed_step_fraction = fracs

    # ------------------------------------------------------ attributes --
    def _check(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.spec.num_devices):
            raise ValueError(
                f"device ids out of range [0, {self.spec.num_devices})")
        return ids

    def counts(self, ids: np.ndarray) -> np.ndarray:
        """True shard size per device, in [min_examples, capacity]."""
        s = self.spec
        u = hash_u01(s.seed, _S_COUNT, self._check(ids))
        span = s.shard_capacity - s.min_examples + 1
        return (s.min_examples + np.floor(u * span)).astype(np.int32)

    def home_classes(self, ids: np.ndarray) -> np.ndarray:
        s = self.spec
        u = hash_u01(s.seed, _S_HOME, self._check(ids))
        return np.floor(u * s.num_classes).astype(np.int32)

    def speed_class_index(self, ids: np.ndarray) -> np.ndarray:
        """Index into ``spec.speed_classes`` per device."""
        u = hash_u01(self.spec.seed, _S_SPEED, self._check(ids))
        return np.searchsorted(self._speed_cum, u, side="right").clip(
            0, len(self.spec.speed_classes) - 1).astype(np.int32)

    def step_budgets(self, ids: np.ndarray, num_steps: int) -> np.ndarray:
        """Per-device step budget: the speed class' fraction of the static
        per-round budget, floored at one step (matching the engine's
        convention that even the slowest client makes progress)."""
        frac = self._speed_step_fraction[self.speed_class_index(ids)]
        return np.maximum(1, np.floor(frac * num_steps)).astype(np.int32)

    # ----------------------------------------------------------- shards --
    def materialize(self, ids: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """``(x, y, counts)`` for a chunk of devices: x is
        ``(n, capacity, feature_dim)`` float32, y ``(n, capacity)`` int32,
        counts ``(n,)`` int32 — the same padded-shard layout as
        ``data/sharding.ClientShards``, rows past ``count`` zeroed."""
        s = self.spec
        ids = self._check(ids)
        n = ids.shape[0]
        cap, fdim = s.shard_capacity, s.feature_dim
        counts = self.counts(ids)
        home = self.home_classes(ids)

        # Per-(device, slot) keys: device_id * capacity + slot is unique
        # within a stream, so the same device regenerates the same rows
        # in any chunking.
        slot_ids = (ids[:, None].astype(np.uint64) * np.uint64(cap)
                    + np.arange(cap, dtype=np.uint64)[None, :])
        u_skew = hash_u01(s.seed, _S_LABEL, slot_ids)
        u_cls = hash_u01(s.seed, _S_LABEL + 1, slot_ids)
        uniform = np.floor(u_cls * s.num_classes).astype(np.int32)
        y = np.where(u_skew < s.label_skew, home[:, None], uniform)

        feat_ids = (slot_ids[..., None] * np.uint64(fdim)
                    + np.arange(fdim, dtype=np.uint64)[None, None, :])
        noise = _hash_normal(s.seed, _S_NOISE, feat_ids)
        x = (self._prototypes[y] + s.noise_scale * noise).astype(np.float32)

        valid = (np.arange(cap, dtype=np.int32)[None, :] < counts[:, None])
        x *= valid[..., None]
        y = np.where(valid, y, 0).astype(np.int32)
        return x, y, counts

    def example_batch(self, batch_size: int) -> np.ndarray:
        """A representative feature batch for model initialization."""
        x, _, _ = self.materialize(np.zeros((1,), np.int64))
        reps = int(np.ceil(batch_size / x.shape[1]))
        flat = np.tile(x[0], (reps, 1))[:batch_size]
        return flat
