"""Headline benchmark: FedAvg rounds/sec, recorded by the driver.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured workload is BASELINE.json's headline metric ("FedAvg rounds/sec
and client-samples/sec/chip; CIFAR-10 acc@round"): a federated round —
cohort of clients, each running jit-compiled local SGD on-device, FedAvg
aggregation in-XLA (psum over a mesh when >1 device).

Two workload shapes, both from BASELINE.json ``configs``:

- accelerator present → config #2's shape (CIFAR-10 CNN, bf16, width 64);
- CPU fallback (tunnel flake) → config #1's shape, the spec's DESIGNATED
  CPU baseline ("FedAvg 2-layer MLP on MNIST, 10 simulated clients (CPU
  baseline)").  An MLP is matmul-dominated, so the comparison measures the
  framework (one jit scan over vmapped clients vs sequential per-client
  Python), not XLA:CPU-vs-MKLDNN convolution codegen — round 3's CNN-shaped
  fallback lost 2.5x on exactly that backend mismatch.

``vs_baseline`` compares against a faithful reference-style implementation
run in-process (SURVEY.md §3a: sequential per-client PyTorch-CPU local
training + host-side state_dict weighted averaging — the reference's
PySyft-worker architecture minus the network, which only makes the baseline
FASTER than the real thing).  There are no published reference numbers
(BASELINE.json "published" is {}), so this measured stand-in is the baseline.

On a CPU fallback the emitted record also carries a ``last_tpu`` block —
the most recent accelerator-measured result with provenance — so a flaky
tunnel can never erase the TPU evidence from the round's artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


# Accelerator workload: scaled CIFAR-10 CNN FedAvg (BASELINE config #2).
TPU_WORKLOAD = dict(model="cnn", dataset="cifar10", cohort=16, local_steps=8,
                    batch=32, width=64, num_clients=64,
                    examples_per_client=256, dtype="bfloat16")

# CPU fallback: BASELINE config #1's shape (the designated CPU baseline).
# local_steps is raised from the config's 10 to 20 so each round amortizes
# dispatch overhead; both sides run the identical shape.
CPU_WORKLOAD = dict(model="mlp", dataset="mnist", cohort=10, local_steps=20,
                    batch=32, hidden=200, depth=2, num_clients=10,
                    examples_per_client=640, dtype="float32")

# Committed record of the last accelerator-measured bench (regenerated
# whenever the bench runs on a real accelerator): the CPU fallback embeds
# it so the driver artifact keeps the TPU evidence across tunnel flakes.
LAST_TPU_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "results", "bench_tpu.json")


def probe_platform(timeout_s: float = 90.0, budget_s: float = 0.0) -> str | None:
    """Which platform does a fresh ``jax.devices()`` resolve to — answered
    from a SUBPROCESS so a hung/flaky TPU plugin cannot hang the bench.

    ``budget_s`` > ``timeout_s`` enables bounded RETRY: the tunnel flaps,
    and a couple of minutes of re-probing is cheap next to a round-long
    CPU-fallback record.  Returns the platform string, or None if every
    probe inside the budget errored or timed out (callers should then
    force CPU without touching the default backend)."""
    single_attempt = budget_s <= timeout_s
    deadline = time.monotonic() + max(budget_s, timeout_s)
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True,
                timeout=min(timeout_s, max(remaining, 5.0)),
            )
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip().splitlines()[-1]
        except Exception:
            pass
        if single_attempt or time.monotonic() + 15.0 >= deadline:
            return None
        print(f"[bench] probe attempt {attempt} failed; retrying "
              f"({deadline - time.monotonic():.0f}s of budget left)",
              file=sys.stderr)
        time.sleep(15.0)


def force_cpu() -> None:
    """Switch this process to the CPU backend WITHOUT initializing (or
    waiting on) the default one — safe to call after ``import jax``."""
    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        import jax.extend.backend as jeb

        jeb.clear_backends()
    except Exception:
        pass


def _make_config(w: dict):
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, RunConfig,
    )

    if w["model"] == "cnn":
        model = ModelConfig(name="cnn", num_classes=10, width=w["width"],
                            dtype=w["dtype"])
        data = DataConfig(dataset=w["dataset"], num_clients=w["num_clients"],
                          partition="dirichlet", dirichlet_alpha=0.5,
                          max_examples_per_client=w["examples_per_client"])
    else:
        model = ModelConfig(name="mlp", num_classes=10,
                            hidden_dim=w["hidden"], depth=w["depth"],
                            dtype=w["dtype"])
        data = DataConfig(dataset=w["dataset"], num_clients=w["num_clients"],
                          partition="iid",
                          max_examples_per_client=w["examples_per_client"])
    return ExperimentConfig(
        data=data, model=model,
        fed=FedConfig(strategy="fedavg", cohort_size=w["cohort"],
                      local_steps=w["local_steps"], batch_size=w["batch"],
                      lr=0.05, momentum=0.9),
        run=RunConfig(name="bench", backend="auto"),
    )


def run_tpu_native(rounds: int, warmup: int, workload: dict | None = None,
                   min_time_s: float = 0.0) -> dict:
    """Time ``rounds`` federated rounds; with ``min_time_s`` > 0, keep timing
    additional chunks of rounds until at least that much wall-time has been
    measured (the CPU fallback uses this so its record is never a ~1.5 s
    noise-dominated window — VERDICT r4 weak #2)."""
    import jax

    from colearn_federated_learning_tpu.data import registry as data_registry
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner

    w = workload or TPU_WORKLOAD
    config = _make_config(w)
    dataset = data_registry.get_dataset(
        w["dataset"], seed=0,
        max_train=w["num_clients"] * w["examples_per_client"], max_test=512,
    )
    learner = FederatedLearner.from_config(config, dataset=dataset)
    n_devices = learner.mesh.devices.size if learner.mesh is not None else 1
    # Actual per-round work (cohort may be adjusted to the mesh size).
    samples_per_round = learner.cohort_size * learner.num_steps * w["batch"]

    for _ in range(warmup):
        learner.run_round()
    learner.finalize_history()                      # true device sync

    # sync=False: no host round-trip between rounds (the per-round float()
    # conversion costs a full RPC on remote-tunnel platforms); the closing
    # finalize reads the last round's metrics and is the real barrier.
    total_rounds, dt = 0, 0.0
    chunk = rounds
    t0 = time.perf_counter()
    while True:
        for _ in range(chunk):
            learner.run_round(sync=False)
        # Per-chunk barrier: the last round's params, NOT finalize_history —
        # finalizing re-converts the whole growing history each pass
        # (quadratic in total rounds, and it would sit inside the timed
        # window deflating the reported rate).
        jax.block_until_ready(learner.server_state.params)
        dt = time.perf_counter() - t0
        total_rounds += chunk
        if dt >= min_time_s:
            break
        # Size the next chunk from the observed rate to land just past the
        # floor (at least one round so progress is guaranteed).
        rate = total_rounds / max(dt, 1e-9)
        chunk = max(1, int(rate * (min_time_s - dt) + 1))
    learner.finalize_history()

    rps = total_rounds / dt
    return {
        "rounds_per_sec": rps,
        "client_samples_per_sec_per_chip": rps * samples_per_round / n_devices,
        "n_devices": n_devices,
        "rounds_timed": total_rounds,
        "seconds_timed": round(dt, 3),
        "platform": jax.devices()[0].platform,
    }


def run_reference_style(rounds: int, workload: dict | None = None) -> dict:
    """Reference architecture stand-in: sequential per-client torch-CPU SGD +
    host-side numpy weighted averaging of state_dicts (SURVEY.md §3a/§3c).
    ``workload`` must match the measured run's (same model family and
    shapes) for ``vs_baseline`` to be a like-for-like ratio."""
    import numpy as np
    import torch
    import torch.nn as tnn

    w = workload or TPU_WORKLOAD
    cohort, local_steps = w["cohort"], w["local_steps"]
    batch = w["batch"]
    torch.manual_seed(0)

    if w["model"] == "cnn":
        width = w["width"]

        class TorchModel(tnn.Module):
            # Same op graph as colearn_federated_learning_tpu/models/cnn.py.
            def __init__(self, width=width, num_classes=10):
                super().__init__()
                layers, in_ch = [], 3
                for mult in (1, 2, 4):
                    ch = width * mult
                    layers += [
                        tnn.Conv2d(in_ch, ch, 3, padding=1),
                        tnn.GroupNorm(min(32, ch), ch), tnn.ReLU(),
                        tnn.Conv2d(ch, ch, 3, padding=1),
                        tnn.GroupNorm(min(32, ch), ch), tnn.ReLU(),
                        tnn.MaxPool2d(2),
                    ]
                    in_ch = ch
                self.features = tnn.Sequential(*layers)
                self.head = tnn.Linear(in_ch, num_classes)

            def forward(self, x):
                h = self.features(x)
                return self.head(h.mean(dim=(2, 3)))

        xshape = (3, 32, 32)
    else:
        hidden, depth = w["hidden"], w["depth"]

        class TorchModel(tnn.Module):
            # Same op graph as colearn_federated_learning_tpu/models/mlp.py.
            def __init__(self, hidden=hidden, depth=depth, num_classes=10):
                super().__init__()
                layers, d_in = [], 28 * 28
                for _ in range(depth):
                    layers += [tnn.Linear(d_in, hidden), tnn.ReLU()]
                    d_in = hidden
                layers.append(tnn.Linear(d_in, num_classes))
                self.net = tnn.Sequential(*layers)

            def forward(self, x):
                return self.net(x.reshape(x.shape[0], -1))

        xshape = (28, 28)

    rng = np.random.default_rng(0)
    data = [
        (torch.randn(local_steps, batch, *xshape),
         torch.from_numpy(rng.integers(0, 10, (local_steps, batch))).long())
        for _ in range(cohort)
    ]
    global_model = TorchModel()
    global_sd = {k: v.clone() for k, v in global_model.state_dict().items()}
    loss_fn = tnn.CrossEntropyLoss()

    t0 = time.perf_counter()
    for _ in range(rounds):
        updates, weights = [], []
        for cx, cy in data:  # sequential workers, as in the reference
            model = TorchModel()
            model.load_state_dict(global_sd)  # "broadcast"
            opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9)
            for s in range(local_steps):
                opt.zero_grad()
                loss_fn(model(cx[s]), cy[s]).backward()
                opt.step()
            # "websocket return": state_dict to host numpy
            updates.append({k: v.detach().numpy() for k, v in model.state_dict().items()})
            weights.append(local_steps * batch)
        # host-side fed_avg(weights, sizes)
        total = float(sum(weights))
        global_sd = {
            k: torch.from_numpy(
                sum(w * u[k] for w, u in zip(weights, updates)) / total
            )
            for k in updates[0]
        }
    dt = time.perf_counter() - t0
    return {"rounds_per_sec": rounds / dt}


def _metric_name(w: dict) -> str:
    return (f"fedavg_{w['dataset']}_{w['model']}_rounds_per_sec")


def _load_last_tpu() -> dict | None:
    try:
        with open(LAST_TPU_PATH) as f:
            return json.load(f)
    except Exception:
        return None


def _save_last_tpu(out: dict) -> None:
    """Persist an accelerator-measured record (with provenance) so later
    CPU-fallback runs can embed it.  Best-effort: the bench never fails
    over bookkeeping."""
    try:
        os.makedirs(os.path.dirname(LAST_TPU_PATH), exist_ok=True)
        rec = dict(out)
        rec["recorded_unix"] = int(time.time())
        rec["provenance"] = "measured live by bench.py on the real accelerator"
        with open(LAST_TPU_PATH, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    except Exception as e:  # noqa: BLE001
        print(f"[bench] could not save last-tpu record: {e}", file=sys.stderr)


def main(argv: list[str] | None = None) -> None:
    """``argv=None`` parses ``sys.argv``; pass an explicit list when calling
    from another CLI (e.g. ``colearn bench`` passes its remaining args).

    Robustness contract (the driver records this output unconditionally):
    the ONE JSON line is always printed, with a ``platform`` field —
    ``tpu``-class when the accelerator answers a bounded-budget probe (with
    retries: the tunnel flaps), ``cpu`` with the matmul-shaped BASELINE
    config #1 workload when it doesn't (plus a ``last_tpu`` block carrying
    the most recent accelerator measurement), ``error`` only if even the
    CPU fallback failed."""
    p = argparse.ArgumentParser(prog="colearn bench")
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--baseline-rounds", type=int, default=1)
    p.add_argument("--skip-baseline", action="store_true")
    p.add_argument("--probe-timeout", type=float, default=90.0)
    p.add_argument("--probe-budget", type=float, default=210.0,
                   help="total seconds to spend re-probing a flaky "
                        "accelerator before falling back to CPU")
    p.add_argument("--force-cpu", action="store_true")
    p.add_argument("--min-time", type=float, default=15.0,
                   help="CPU fallback only: minimum seconds of measured "
                        "wall-time (rounds_timed is chosen to meet this)")
    args = p.parse_args(argv)

    platform = None if args.force_cpu else probe_platform(
        args.probe_timeout, args.probe_budget)
    if platform is None or platform == "cpu":
        print(f"[bench] accelerator probe -> {platform!r}; forcing CPU "
              "fallback workload", file=sys.stderr)
        force_cpu()
        attempts = [("cpu", CPU_WORKLOAD)]
    else:
        print(f"[bench] accelerator probe -> {platform!r}", file=sys.stderr)
        attempts = [(platform, TPU_WORKLOAD), ("cpu", CPU_WORKLOAD)]

    ours, used_workload, err = None, None, None
    for plat, workload in attempts:
        try:
            # CPU fallback: choose the timed-round count by WALL-TIME (>= a
            # 15 s floor), not a fixed cap — a 10-round window at ~6.5
            # rounds/sec was a ~1.5 s measurement, too noisy for a perf
            # record.  Start from a small chunk; run_tpu_native keeps timing
            # until the floor is met.
            if plat == "cpu":
                rounds, floor = min(args.rounds, 10), args.min_time
                print(f"[bench] cpu fallback: timing >= {floor:.0f}s of "
                      "rounds (wall-time floor)", file=sys.stderr)
            else:
                rounds, floor = args.rounds, 0.0
            ours = run_tpu_native(rounds, args.warmup, workload,
                                  min_time_s=floor)
            used_workload = workload
            print(f"[bench] tpu-native: {ours}", file=sys.stderr)
            break
        except Exception as e:  # noqa: BLE001 — always fall through to JSON
            err = f"{type(e).__name__}: {e}"
            print(f"[bench] {plat} run failed: {err}", file=sys.stderr)
            if plat != "cpu":
                force_cpu()

    vs = 0.0
    if ours is not None and not args.skip_baseline:
        try:
            base = run_reference_style(args.baseline_rounds, used_workload)
            print(f"[bench] reference-style torch-cpu: {base}", file=sys.stderr)
            vs = ours["rounds_per_sec"] / base["rounds_per_sec"]
        except Exception as e:  # noqa: BLE001
            print(f"[bench] baseline failed: {e}", file=sys.stderr)

    if ours is None:
        print(json.dumps({
            "metric": _metric_name(TPU_WORKLOAD),
            "value": 0.0,
            "unit": "rounds/sec",
            "vs_baseline": 0.0,
            "platform": "error",
            "error": err,
        }))
        return
    out = {
        "metric": _metric_name(used_workload),
        "value": round(ours["rounds_per_sec"], 4),
        "unit": "rounds/sec",
        "vs_baseline": round(vs, 4),
        "platform": ours["platform"],
        "n_devices": ours["n_devices"],
        "rounds_timed": ours.get("rounds_timed", args.rounds),
        "seconds_timed": ours.get("seconds_timed", 0.0),
        "client_samples_per_sec_per_chip": round(
            ours["client_samples_per_sec_per_chip"], 1),
    }
    if ours["platform"] != "cpu":
        # Only persist records that carry the headline ratio: a
        # --skip-baseline (or failed-baseline) run must not clobber the
        # preserved evidence with vs_baseline 0.0.
        if vs > 0.0:
            _save_last_tpu(out)
    else:
        if args.force_cpu:
            why = "--force-cpu"
        elif platform is not None and platform != "cpu":
            # The probe SAW an accelerator but the run on it failed —
            # record the real failure, don't misattribute it to the tunnel.
            why = "accelerator run failed"
            out["accelerator_error"] = err
        else:
            why = "accelerator unreachable"
        out["note"] = (
            f"cpu fallback ({why}): BASELINE config #1 workload (MNIST MLP, "
            "10 clients — the spec's designated CPU baseline); both sides "
            "run the identical shape on the same host CPU")
        last = _load_last_tpu()
        if last is not None:
            out["last_tpu"] = last
    print(json.dumps(out))


if __name__ == "__main__":
    main()
