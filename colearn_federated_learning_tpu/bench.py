"""Headline benchmark: FedAvg rounds/sec on the CIFAR-10 CNN config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured workload is BASELINE.json's headline metric ("FedAvg rounds/sec
and client-samples/sec/chip; CIFAR-10 acc@round"): a federated round of the
CIFAR-10 CNN config — cohort of clients, each running jit-compiled local SGD
on-device, FedAvg aggregation in-XLA (psum over a mesh when >1 device).

``vs_baseline`` compares against a faithful reference-style implementation
run in-process (SURVEY.md §3a: sequential per-client PyTorch-CPU local
training + host-side state_dict weighted averaging — the reference's
PySyft-worker architecture minus the network, which only makes the baseline
FASTER than the real thing).  There are no published reference numbers
(BASELINE.json "published" is {}), so this measured stand-in is the baseline.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


# Workload: scaled CIFAR-10 CNN FedAvg (BASELINE config #2 shape).
COHORT = 16
LOCAL_STEPS = 8
BATCH = 32
WIDTH = 64
NUM_CLIENTS = 64


def run_tpu_native(rounds: int, warmup: int) -> dict:
    import jax

    from colearn_federated_learning_tpu.data import registry as data_registry
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, RunConfig,
    )

    config = ExperimentConfig(
        data=DataConfig(dataset="cifar10", num_clients=NUM_CLIENTS,
                        partition="dirichlet", dirichlet_alpha=0.5,
                        max_examples_per_client=256),
        model=ModelConfig(name="cnn", num_classes=10, width=WIDTH,
                          dtype="bfloat16"),
        fed=FedConfig(strategy="fedavg", cohort_size=COHORT,
                      local_steps=LOCAL_STEPS, batch_size=BATCH,
                      lr=0.05, momentum=0.9),
        run=RunConfig(name="bench", backend="auto"),
    )
    dataset = data_registry.get_dataset("cifar10", seed=0,
                                        max_train=NUM_CLIENTS * 256,
                                        max_test=512)
    learner = FederatedLearner.from_config(config, dataset=dataset)
    n_devices = learner.mesh.devices.size if learner.mesh is not None else 1
    # Actual per-round work (cohort may be adjusted to the mesh size).
    samples_per_round = learner.cohort_size * learner.num_steps * BATCH

    for _ in range(warmup):
        learner.run_round()
    jax.block_until_ready(learner.server_state.params)

    t0 = time.perf_counter()
    for _ in range(rounds):
        learner.run_round()
    jax.block_until_ready(learner.server_state.params)
    dt = time.perf_counter() - t0

    rps = rounds / dt
    return {
        "rounds_per_sec": rps,
        "client_samples_per_sec_per_chip": rps * samples_per_round / n_devices,
        "n_devices": n_devices,
        "platform": jax.devices()[0].platform,
    }


def run_reference_style(rounds: int) -> dict:
    """Reference architecture stand-in: sequential per-client torch-CPU SGD +
    host-side numpy weighted averaging of state_dicts (SURVEY.md §3a/§3c)."""
    import numpy as np
    import torch
    import torch.nn as tnn

    torch.manual_seed(0)

    class TorchCNN(tnn.Module):
        # Same op graph as colearn_federated_learning_tpu/models/cnn.py.
        def __init__(self, width=WIDTH, num_classes=10):
            super().__init__()
            layers, in_ch = [], 3
            for mult in (1, 2, 4):
                ch = width * mult
                layers += [
                    tnn.Conv2d(in_ch, ch, 3, padding=1),
                    tnn.GroupNorm(min(32, ch), ch), tnn.ReLU(),
                    tnn.Conv2d(ch, ch, 3, padding=1),
                    tnn.GroupNorm(min(32, ch), ch), tnn.ReLU(),
                    tnn.MaxPool2d(2),
                ]
                in_ch = ch
            self.features = tnn.Sequential(*layers)
            self.head = tnn.Linear(in_ch, num_classes)

        def forward(self, x):
            h = self.features(x)
            return self.head(h.mean(dim=(2, 3)))

    rng = np.random.default_rng(0)
    data = [
        (torch.randn(LOCAL_STEPS, BATCH, 3, 32, 32),
         torch.from_numpy(rng.integers(0, 10, (LOCAL_STEPS, BATCH))).long())
        for _ in range(COHORT)
    ]
    global_model = TorchCNN()
    global_sd = {k: v.clone() for k, v in global_model.state_dict().items()}
    loss_fn = tnn.CrossEntropyLoss()

    t0 = time.perf_counter()
    for _ in range(rounds):
        updates, weights = [], []
        for cx, cy in data:  # sequential workers, as in the reference
            model = TorchCNN()
            model.load_state_dict(global_sd)  # "broadcast"
            opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9)
            for s in range(LOCAL_STEPS):
                opt.zero_grad()
                loss_fn(model(cx[s]), cy[s]).backward()
                opt.step()
            # "websocket return": state_dict to host numpy
            updates.append({k: v.detach().numpy() for k, v in model.state_dict().items()})
            weights.append(LOCAL_STEPS * BATCH)
        # host-side fed_avg(weights, sizes)
        total = float(sum(weights))
        global_sd = {
            k: torch.from_numpy(
                sum(w * u[k] for w, u in zip(weights, updates)) / total
            )
            for k in updates[0]
        }
    dt = time.perf_counter() - t0
    return {"rounds_per_sec": rounds / dt}


def main(argv: list[str] | None = None) -> None:
    """``argv=None`` parses ``sys.argv``; pass an explicit list when calling
    from another CLI (e.g. ``colearn bench`` passes its remaining args)."""
    p = argparse.ArgumentParser(prog="colearn bench")
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--baseline-rounds", type=int, default=1)
    p.add_argument("--skip-baseline", action="store_true")
    args = p.parse_args(argv)

    ours = run_tpu_native(args.rounds, args.warmup)
    print(f"[bench] tpu-native: {ours}", file=sys.stderr)

    vs = 0.0
    if not args.skip_baseline:
        base = run_reference_style(args.baseline_rounds)
        print(f"[bench] reference-style torch-cpu: {base}", file=sys.stderr)
        vs = ours["rounds_per_sec"] / base["rounds_per_sec"]

    print(json.dumps({
        "metric": "fedavg_cifar10_cnn_rounds_per_sec",
        "value": round(ours["rounds_per_sec"], 4),
        "unit": "rounds/sec",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
