"""Headline benchmark: FedAvg rounds/sec on the CIFAR-10 CNN config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured workload is BASELINE.json's headline metric ("FedAvg rounds/sec
and client-samples/sec/chip; CIFAR-10 acc@round"): a federated round of the
CIFAR-10 CNN config — cohort of clients, each running jit-compiled local SGD
on-device, FedAvg aggregation in-XLA (psum over a mesh when >1 device).

``vs_baseline`` compares against a faithful reference-style implementation
run in-process (SURVEY.md §3a: sequential per-client PyTorch-CPU local
training + host-side state_dict weighted averaging — the reference's
PySyft-worker architecture minus the network, which only makes the baseline
FASTER than the real thing).  There are no published reference numbers
(BASELINE.json "published" is {}), so this measured stand-in is the baseline.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time


# Workload: scaled CIFAR-10 CNN FedAvg (BASELINE config #2 shape).
COHORT = 16
LOCAL_STEPS = 8
BATCH = 32
WIDTH = 64
NUM_CLIENTS = 64

# Fallback workload for a CPU run (backend flake / no accelerator): same
# program structure, sized so the XLA:CPU compile finishes in seconds —
# round-1's forced-CPU bench died compiling the width-64 scan.
CPU_WORKLOAD = dict(cohort=8, local_steps=2, batch=8, width=16,
                    num_clients=32, examples_per_client=64,
                    dtype="float32")  # XLA:CPU emulates bf16 ~10x slower
TPU_WORKLOAD = dict(cohort=COHORT, local_steps=LOCAL_STEPS, batch=BATCH,
                    width=WIDTH, num_clients=NUM_CLIENTS,
                    examples_per_client=256, dtype="bfloat16")


def probe_platform(timeout_s: float = 90.0) -> str | None:
    """Which platform does a fresh ``jax.devices()`` resolve to — answered
    from a SUBPROCESS so a hung/flaky TPU plugin cannot hang the bench.
    Returns the platform string, or None if the probe errored or timed out
    (callers should then force CPU without touching the default backend)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip().splitlines()[-1]
    except Exception:
        pass
    return None


def force_cpu() -> None:
    """Switch this process to the CPU backend WITHOUT initializing (or
    waiting on) the default one — safe to call after ``import jax``."""
    import os

    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        import jax.extend.backend as jeb

        jeb.clear_backends()
    except Exception:
        pass


def run_tpu_native(rounds: int, warmup: int, workload: dict | None = None) -> dict:
    import jax

    from colearn_federated_learning_tpu.data import registry as data_registry
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, RunConfig,
    )

    w = workload or TPU_WORKLOAD
    config = ExperimentConfig(
        data=DataConfig(dataset="cifar10", num_clients=w["num_clients"],
                        partition="dirichlet", dirichlet_alpha=0.5,
                        max_examples_per_client=w["examples_per_client"]),
        model=ModelConfig(name="cnn", num_classes=10, width=w["width"],
                          dtype=w["dtype"]),
        fed=FedConfig(strategy="fedavg", cohort_size=w["cohort"],
                      local_steps=w["local_steps"], batch_size=w["batch"],
                      lr=0.05, momentum=0.9),
        run=RunConfig(name="bench", backend="auto"),
    )
    dataset = data_registry.get_dataset(
        "cifar10", seed=0,
        max_train=w["num_clients"] * w["examples_per_client"], max_test=512,
    )
    learner = FederatedLearner.from_config(config, dataset=dataset)
    n_devices = learner.mesh.devices.size if learner.mesh is not None else 1
    # Actual per-round work (cohort may be adjusted to the mesh size).
    samples_per_round = learner.cohort_size * learner.num_steps * w["batch"]

    for _ in range(warmup):
        learner.run_round()
    learner.finalize_history()                      # true device sync

    # sync=False: no host round-trip between rounds (the per-round float()
    # conversion costs a full RPC on remote-tunnel platforms); the closing
    # finalize reads the last round's metrics and is the real barrier.
    t0 = time.perf_counter()
    for _ in range(rounds):
        learner.run_round(sync=False)
    learner.finalize_history()
    dt = time.perf_counter() - t0

    rps = rounds / dt
    return {
        "rounds_per_sec": rps,
        "client_samples_per_sec_per_chip": rps * samples_per_round / n_devices,
        "n_devices": n_devices,
        "platform": jax.devices()[0].platform,
    }


def run_reference_style(rounds: int, workload: dict | None = None) -> dict:
    """Reference architecture stand-in: sequential per-client torch-CPU SGD +
    host-side numpy weighted averaging of state_dicts (SURVEY.md §3a/§3c).
    ``workload`` must match the measured run's (same model width, cohort,
    steps, batch) for ``vs_baseline`` to be a like-for-like ratio."""
    import numpy as np
    import torch
    import torch.nn as tnn

    w = workload or TPU_WORKLOAD
    cohort, local_steps = w["cohort"], w["local_steps"]
    batch, width = w["batch"], w["width"]
    torch.manual_seed(0)

    class TorchCNN(tnn.Module):
        # Same op graph as colearn_federated_learning_tpu/models/cnn.py.
        def __init__(self, width=width, num_classes=10):
            super().__init__()
            layers, in_ch = [], 3
            for mult in (1, 2, 4):
                ch = width * mult
                layers += [
                    tnn.Conv2d(in_ch, ch, 3, padding=1),
                    tnn.GroupNorm(min(32, ch), ch), tnn.ReLU(),
                    tnn.Conv2d(ch, ch, 3, padding=1),
                    tnn.GroupNorm(min(32, ch), ch), tnn.ReLU(),
                    tnn.MaxPool2d(2),
                ]
                in_ch = ch
            self.features = tnn.Sequential(*layers)
            self.head = tnn.Linear(in_ch, num_classes)

        def forward(self, x):
            h = self.features(x)
            return self.head(h.mean(dim=(2, 3)))

    rng = np.random.default_rng(0)
    data = [
        (torch.randn(local_steps, batch, 3, 32, 32),
         torch.from_numpy(rng.integers(0, 10, (local_steps, batch))).long())
        for _ in range(cohort)
    ]
    global_model = TorchCNN()
    global_sd = {k: v.clone() for k, v in global_model.state_dict().items()}
    loss_fn = tnn.CrossEntropyLoss()

    t0 = time.perf_counter()
    for _ in range(rounds):
        updates, weights = [], []
        for cx, cy in data:  # sequential workers, as in the reference
            model = TorchCNN()
            model.load_state_dict(global_sd)  # "broadcast"
            opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9)
            for s in range(local_steps):
                opt.zero_grad()
                loss_fn(model(cx[s]), cy[s]).backward()
                opt.step()
            # "websocket return": state_dict to host numpy
            updates.append({k: v.detach().numpy() for k, v in model.state_dict().items()})
            weights.append(local_steps * batch)
        # host-side fed_avg(weights, sizes)
        total = float(sum(weights))
        global_sd = {
            k: torch.from_numpy(
                sum(w * u[k] for w, u in zip(weights, updates)) / total
            )
            for k in updates[0]
        }
    dt = time.perf_counter() - t0
    return {"rounds_per_sec": rounds / dt}


def main(argv: list[str] | None = None) -> None:
    """``argv=None`` parses ``sys.argv``; pass an explicit list when calling
    from another CLI (e.g. ``colearn bench`` passes its remaining args).

    Robustness contract (the driver records this output unconditionally):
    the ONE JSON line is always printed, with a ``platform`` field —
    ``tpu``-class when the accelerator answers a bounded-time probe, ``cpu``
    with a small fast-compile workload when it doesn't, ``error`` only if
    even the CPU fallback failed."""
    p = argparse.ArgumentParser(prog="colearn bench")
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--baseline-rounds", type=int, default=1)
    p.add_argument("--skip-baseline", action="store_true")
    p.add_argument("--probe-timeout", type=float, default=90.0)
    p.add_argument("--force-cpu", action="store_true")
    args = p.parse_args(argv)

    platform = None if args.force_cpu else probe_platform(args.probe_timeout)
    if platform is None or platform == "cpu":
        print(f"[bench] accelerator probe -> {platform!r}; forcing CPU "
              "fallback workload", file=sys.stderr)
        force_cpu()
        attempts = [("cpu", CPU_WORKLOAD)]
    else:
        print(f"[bench] accelerator probe -> {platform!r}", file=sys.stderr)
        attempts = [(platform, TPU_WORKLOAD), ("cpu", CPU_WORKLOAD)]

    ours, used_workload, err = None, None, None
    for plat, workload in attempts:
        try:
            # The sandbox CPU is a single core (~5s/round even on the small
            # workload); cap the timed rounds so a fallback still finishes
            # well inside the driver's window.
            rounds = args.rounds if plat != "cpu" else min(args.rounds, 5)
            if rounds != args.rounds:
                print(f"[bench] cpu fallback: capping --rounds "
                      f"{args.rounds} -> {rounds}", file=sys.stderr)
            ours = run_tpu_native(rounds, args.warmup, workload)
            ours["rounds_timed"] = rounds
            used_workload = workload
            print(f"[bench] tpu-native: {ours}", file=sys.stderr)
            break
        except Exception as e:  # noqa: BLE001 — always fall through to JSON
            err = f"{type(e).__name__}: {e}"
            print(f"[bench] {plat} run failed: {err}", file=sys.stderr)
            if plat != "cpu":
                force_cpu()

    vs = 0.0
    if ours is not None and not args.skip_baseline:
        try:
            base = run_reference_style(args.baseline_rounds, used_workload)
            print(f"[bench] reference-style torch-cpu: {base}", file=sys.stderr)
            vs = ours["rounds_per_sec"] / base["rounds_per_sec"]
        except Exception as e:  # noqa: BLE001
            print(f"[bench] baseline failed: {e}", file=sys.stderr)

    if ours is None:
        print(json.dumps({
            "metric": "fedavg_cifar10_cnn_rounds_per_sec",
            "value": 0.0,
            "unit": "rounds/sec",
            "vs_baseline": 0.0,
            "platform": "error",
            "error": err,
        }))
        return
    out = {
        "metric": "fedavg_cifar10_cnn_rounds_per_sec",
        "value": round(ours["rounds_per_sec"], 4),
        "unit": "rounds/sec",
        "vs_baseline": round(vs, 4),
        "platform": ours["platform"],
        "n_devices": ours["n_devices"],
        "rounds_timed": ours.get("rounds_timed", args.rounds),
        "client_samples_per_sec_per_chip": round(
            ours["client_samples_per_sec_per_chip"], 1),
    }
    if ours["platform"] == "cpu":
        # The fallback exists so a dead accelerator still yields a record;
        # its ratio reflects XLA:CPU vs torch-MKLDNN conv throughput, not
        # the framework (the TPU number is the headline — PERF.md §3:
        # 14.78 rounds/sec, ~1300x the reference-style baseline).
        why = ("--force-cpu" if args.force_cpu
               else "accelerator unreachable")
        out["note"] = (f"cpu fallback ({why}): ratio is "
                       "XLA:CPU-vs-MKLDNN backend throughput; see PERF.md "
                       "for the measured TPU numbers")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
