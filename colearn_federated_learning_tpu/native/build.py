"""Build the native library with the system toolchain, cached by mtime.

``python -m colearn_federated_learning_tpu.native.build`` forces a build;
normally ``native.load()`` triggers it lazily on first use and callers fall
back to numpy when no toolchain is available.
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess
import sys

_ROOT = pathlib.Path(__file__).resolve().parent
SOURCES = [_ROOT / "src" / "gather.cpp", _ROOT / "src" / "topk.cpp",
           _ROOT / "src" / "fold.cpp"]
# The ABI version is part of the FILENAME: a checkout upgrade can never
# dlopen a stale cached binary under the new name, and a rebuild after a
# runtime version mismatch loads from a fresh path (re-dlopening the same
# path would return the stale handle already held by the process).
ABI_VERSION = 3  # v3: + cl_fold_sparse_i8 / cl_fold_sparse_f32
LIB = _ROOT / "_build" / f"libcolearn_native_v{ABI_VERSION}.so"


def needs_build() -> bool:
    if not LIB.exists():
        return True
    lib_mtime = LIB.stat().st_mtime
    return any(s.stat().st_mtime > lib_mtime for s in SOURCES)


def build(verbose: bool = False) -> pathlib.Path:
    cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found")
    LIB.parent.mkdir(parents=True, exist_ok=True)
    for stale in LIB.parent.glob("*.so"):
        if stale.name != LIB.name:     # older ABI / pre-versioning binaries
            try:
                stale.unlink()
            except OSError:
                pass
    # -ffp-contract=off: the fold kernel's (value * scale) * weight pair
    # must round twice, exactly like the host oracle's two numpy
    # multiplies — a contracted FMA would change bits and break the
    # device-vs-host parity pins.
    cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           "-ffp-contract=off", *map(str, SOURCES), "-o", str(LIB)]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True, capture_output=not verbose)
    return LIB


if __name__ == "__main__":
    print(build(verbose=True))
