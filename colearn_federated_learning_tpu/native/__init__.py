"""ctypes loader + numpy-compatible wrappers for the native library.

``gather_rows(src, indices)`` is the public entry: a thread-parallel
``src[indices]`` for 2-D row-major arrays, used by data/sharding.py to pack
client shards.  Everything degrades to numpy when the library can't be
built (no toolchain) or is disabled via ``COLEARN_NO_NATIVE=1``.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("COLEARN_NO_NATIVE"):
            return None
        try:
            import shutil

            from colearn_federated_learning_tpu.native import build as build_mod

            if build_mod.needs_build():
                build_mod.build()
            lib = ctypes.CDLL(str(build_mod.LIB))
            lib.cl_abi_version.restype = ctypes.c_int
            if lib.cl_abi_version() != build_mod.ABI_VERSION:
                # The versioned filename makes this near-impossible (a new
                # ABI gets a new name), but if a same-name binary still
                # mismatches, rebuild and dlopen a process-unique COPY —
                # re-opening the original path would hand back the stale
                # handle this process already holds.
                build_mod.build()
                fresh = build_mod.LIB.with_name(
                    f"{build_mod.LIB.stem}.pid{os.getpid()}.so"
                )
                shutil.copy2(build_mod.LIB, fresh)
                lib = ctypes.CDLL(str(fresh))
                # The dlopen handle keeps the inode alive; unlink so the
                # per-process copies never accumulate in _build.
                try:
                    fresh.unlink()
                except OSError:
                    pass
                lib.cl_abi_version.restype = ctypes.c_int
                if lib.cl_abi_version() != build_mod.ABI_VERSION:
                    _lib = None
                    return _lib
            lib.cl_gather_rows.restype = ctypes.c_int
            lib.cl_gather_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int32,
            ]
            lib.cl_topk_abs.restype = ctypes.c_int
            lib.cl_topk_abs.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ]
            for fold_fn in (lib.cl_fold_sparse_i8, lib.cl_fold_sparse_f32):
                fold_fn.restype = ctypes.c_int
                fold_fn.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                    ctypes.c_float, ctypes.c_float, ctypes.c_int32,
                ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def topk_abs(flat: np.ndarray, k: int,
             n_threads: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Indices (ascending) and values of the ``k`` largest-|x| entries of a
    1-D float32 array — thread-parallel nth_element when the native
    library is present, numpy argpartition otherwise.  The top-k update
    sparsifier's host-side hot op (fed/compression.py)."""
    flat = np.ascontiguousarray(flat, dtype=np.float32)
    k = int(k)
    if not 0 < k <= flat.size:
        raise ValueError(f"k={k} out of range for size {flat.size}")
    lib = load()
    if lib is not None and flat.size > 0:
        idx = np.empty(k, np.int32)
        val = np.empty(k, np.float32)
        if n_threads <= 0:
            n_threads = min(16, os.cpu_count() or 1)
        rc = lib.cl_topk_abs(flat.ctypes.data, flat.size, k,
                             idx.ctypes.data, val.ctypes.data, n_threads)
        if rc == 0:
            return idx, val
    idx = np.argpartition(np.abs(flat), flat.size - k)[-k:]
    idx = np.sort(idx).astype(np.int32)
    return idx, flat[idx]


def fold_sparse(acc: np.ndarray, idx: np.ndarray, vals: np.ndarray,
                scale: float, w: float, set_mode: bool) -> bool:
    """Fused ``acc.reshape(-1)[idx] (=|+=) (vals * scale) * w`` — the
    ops/fold_kernel.py native lowering (dequant + weight + scatter in one
    pass, fold.cpp).  ``acc`` must be a writable C-contiguous flat float32
    array, ``idx`` int64, ``vals`` int8 (topk8 raw) or float32 (topk).
    Returns False when the native library is unavailable — the caller
    falls back to the equivalent numpy expression."""
    lib = load()
    if lib is None:
        return False
    if not (isinstance(acc, np.ndarray) and acc.dtype == np.float32
            and acc.flags.c_contiguous and acc.flags.writeable):
        raise ValueError("fold_sparse needs a writable C-contiguous "
                         "float32 accumulator")
    idx = np.ascontiguousarray(idx, np.int64)
    if vals.dtype == np.int8:
        fn = lib.cl_fold_sparse_i8
        vals = np.ascontiguousarray(vals)
    else:
        fn = lib.cl_fold_sparse_f32
        vals = np.ascontiguousarray(vals, np.float32)
    rc = fn(acc.ctypes.data, acc.size, idx.ctypes.data, vals.ctypes.data,
            idx.size, float(scale), float(w), 1 if set_mode else 0)
    if rc != 0:
        raise IndexError("fold_sparse: index out of range")
    return True


def gather_rows(src: np.ndarray, indices: np.ndarray,
                n_threads: int = 0) -> np.ndarray:
    """``src[indices]`` over the leading axis, thread-parallel when the
    native library is present; plain numpy take otherwise.  ``src`` may be
    any-dimensional; rows are its trailing dims."""
    lib = load()
    if lib is None:
        return np.take(src, indices, axis=0)
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    out = np.empty((idx.shape[0],) + src.shape[1:], dtype=src.dtype)
    row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.itemsize
    if row_bytes == 0 or idx.size == 0:
        return out
    if n_threads <= 0:
        n_threads = min(16, os.cpu_count() or 1)
    rc = lib.cl_gather_rows(
        src.ctypes.data, src.shape[0], row_bytes,
        idx.ctypes.data, idx.shape[0],
        out.ctypes.data, n_threads,
    )
    if rc != 0:
        raise IndexError("gather_rows: index out of range")
    return out
