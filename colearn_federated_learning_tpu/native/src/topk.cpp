// Thread-parallel top-k-by-magnitude selection for update sparsification
// (fed/compression.py "topk" scheme).  The Python fallback is numpy
// argpartition — single-threaded introselect over an |x| temporary.
//
// Algorithm: parallel radix-select on the float magnitude bits.  For
// non-negative floats the IEEE-754 bit pattern is monotonic, so
// (bits & 0x7FFFFFFF) orders |x| without computing fabs.  Two O(n) passes:
//   1. per-thread 65536-bin histogram of the top magnitude bits; merge;
//      walk from the top to find the boundary bin b* where the cumulative
//      count crosses k.
//   2. per-thread scan: indices in bins above b* are selected outright;
//      boundary-bin candidates are collected and the exact remainder is
//      chosen by nth_element over (mag_bits, idx) — only the boundary bin
//      ever needs a selection pass, so the temporaries stay tiny.
// No O(n) pair copies, both passes stream sequentially (HW prefetch),
// and the only global sort is over the k selected indices.
//
// Exported C ABI (ctypes, native/__init__.py):
//   cl_topk_abs(src, n, k, out_idx, out_val, n_threads) -> 0 on success
// out_idx: k int32 indices in ASCENDING index order; out_val: src[idx].

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kBinBits = 16;
constexpr int kBins = 1 << kBinBits;
constexpr uint32_t kMagMask = 0x7FFFFFFFu;

inline uint32_t mag_bits(float f) {
  uint32_t b;
  std::memcpy(&b, &f, sizeof(b));
  return b & kMagMask;
}

inline uint32_t bin_of(uint32_t mb) { return mb >> (31 - kBinBits); }

void hist_chunk(const float* src, int64_t lo, int64_t hi,
                std::vector<int64_t>* hist) {
  hist->assign(kBins, 0);
  for (int64_t i = lo; i < hi; ++i) {
    ++(*hist)[bin_of(mag_bits(src[i]))];
  }
}

struct Boundary {
  uint32_t mb;
  int32_t idx;
};

void collect_chunk(const float* src, int64_t lo, int64_t hi, uint32_t bstar,
                   std::vector<int32_t>* above, std::vector<Boundary>* bound) {
  for (int64_t i = lo; i < hi; ++i) {
    const uint32_t mb = mag_bits(src[i]);
    const uint32_t b = bin_of(mb);
    if (b > bstar) {
      above->push_back(static_cast<int32_t>(i));
    } else if (b == bstar) {
      bound->push_back({mb, static_cast<int32_t>(i)});
    }
  }
}

}  // namespace

extern "C" {

int cl_topk_abs(const float* src, int64_t n, int64_t k, int32_t* out_idx,
                float* out_val, int32_t n_threads) {
  if (n <= 0 || k <= 0 || k > n) return 1;
  if (n_threads <= 0) {
    n_threads = static_cast<int32_t>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 1;
  }
  const int64_t kMinPerThread = 1 << 16;
  int64_t t = std::min<int64_t>(
      n_threads, (n + kMinPerThread - 1) / kMinPerThread);
  if (t < 1) t = 1;
  const int64_t step = (n + t - 1) / t;

  // Pass 1: magnitude-bit histograms.
  std::vector<std::vector<int64_t>> hists(static_cast<size_t>(t));
  {
    std::vector<std::thread> threads;
    for (int64_t i = 0; i < t; ++i) {
      threads.emplace_back(hist_chunk, src, i * step,
                           std::min(n, (i + 1) * step),
                           &hists[static_cast<size_t>(i)]);
    }
    for (auto& th : threads) th.join();
  }
  int64_t cum = 0;
  int bstar = 0;
  for (int b = kBins - 1; b >= 0; --b) {
    int64_t c = 0;
    for (const auto& h : hists) c += h[static_cast<size_t>(b)];
    if (cum + c >= k) {
      bstar = b;
      break;
    }
    cum += c;
  }
  const int64_t need = k - cum;  // entries to take from the boundary bin

  // Pass 2: gather indices above the boundary + boundary candidates.
  std::vector<std::vector<int32_t>> aboves(static_cast<size_t>(t));
  std::vector<std::vector<Boundary>> bounds(static_cast<size_t>(t));
  {
    std::vector<std::thread> threads;
    for (int64_t i = 0; i < t; ++i) {
      threads.emplace_back(collect_chunk, src, i * step,
                           std::min(n, (i + 1) * step),
                           static_cast<uint32_t>(bstar),
                           &aboves[static_cast<size_t>(i)],
                           &bounds[static_cast<size_t>(i)]);
    }
    for (auto& th : threads) th.join();
  }

  std::vector<int32_t> sel;
  sel.reserve(static_cast<size_t>(k));
  for (const auto& a : aboves) sel.insert(sel.end(), a.begin(), a.end());
  if (need > 0) {
    std::vector<Boundary> bound;
    size_t bn = 0;
    for (const auto& b : bounds) bn += b.size();
    bound.reserve(bn);
    for (const auto& b : bounds) bound.insert(bound.end(), b.begin(), b.end());
    // Exact remainder: largest magnitudes in the boundary bin, index
    // tiebreak for determinism.
    std::nth_element(bound.begin(), bound.begin() + need, bound.end(),
                     [](const Boundary& a, const Boundary& b) {
                       if (a.mb != b.mb) return a.mb > b.mb;
                       return a.idx < b.idx;
                     });
    for (int64_t i = 0; i < need; ++i) {
      sel.push_back(bound[static_cast<size_t>(i)].idx);
    }
  }
  if (static_cast<int64_t>(sel.size()) != k) return 2;  // unreachable

  std::sort(sel.begin(), sel.end());
  for (int64_t i = 0; i < k; ++i) {
    out_idx[i] = sel[static_cast<size_t>(i)];
    out_val[i] = src[sel[static_cast<size_t>(i)]];
  }
  return 0;
}

}  // extern "C"
