// Fused sparse fold: dequant + weight scale + scatter-add into the dense
// float32 accumulator in ONE pass over the staged (indices, values) pair.
//
// This is the wire-speed lowering of ops/fold_kernel.py for a CPU-backend
// server: one read of the staged sparse contribution, one scattered
// read-modify-write of the accumulator, no intermediate dense or scaled
// temporaries.  The multiply ORDER is load-bearing — the host oracle
// computes (value * scale) first (topk_leaf_arrays' dequant) and applies
// the aggregation weight second (_stage_topk), two separate float32
// roundings — so the fused loop does exactly that, and the build pins
// -ffp-contract=off so the compiler cannot re-associate the pair into an
// FMA with different bits.
//
// SET mode covers the fold's first contribution, which the host path
// densifies by ASSIGNMENT into fresh zeros (not by adding to them);
// untouched entries keep the accumulator's exact zero bytes either way.
//
// Single-threaded on purpose: within one contribution the top-k indices
// are unique (threads over disjoint ranges would never collide), but the
// fold already overlaps the transport threads, and the scatter is
// memory-bound — the win here is the fusion + the prefetch, not cores.

namespace {

// Prefetch distance tuned on the bench box: far enough to cover the
// random-access load latency of a ~100 MB accumulator, near enough that
// the prefetched line is still resident when the write lands.
constexpr long long kPrefetch = 24;

template <typename V, bool SET>
inline void fold_loop(float* acc, const long long* idx, const V* vals,
                      long long k, float scale, float w) {
    long long j = 0;
    for (; j + kPrefetch < k; ++j) {
        __builtin_prefetch(&acc[idx[j + kPrefetch]], 1, 1);
        const float v = (static_cast<float>(vals[j]) * scale) * w;
        if (SET) acc[idx[j]] = v; else acc[idx[j]] += v;
    }
    for (; j < k; ++j) {
        const float v = (static_cast<float>(vals[j]) * scale) * w;
        if (SET) acc[idx[j]] = v; else acc[idx[j]] += v;
    }
}

template <typename V>
int fold_impl(float* acc, long long n, const long long* idx, const V* vals,
              long long k, float scale, float w, int set_mode) {
    // Validate before touching acc: a partially applied scatter after a
    // bad index would leave the accumulator corrupted.
    for (long long j = 0; j < k; ++j)
        if (idx[j] < 0 || idx[j] >= n) return 1;
    if (set_mode) fold_loop<V, true>(acc, idx, vals, k, scale, w);
    else fold_loop<V, false>(acc, idx, vals, k, scale, w);
    return 0;
}

}  // namespace

extern "C" {

// topk8 frame: int8 values, per-leaf dequant scale.
int cl_fold_sparse_i8(float* acc, long long n, const long long* idx,
                      const signed char* vals, long long k,
                      float scale, float w, int set_mode) {
    return fold_impl(acc, n, idx, vals, k, scale, w, set_mode);
}

// topk frame: float32 values (scale rides along as 1.0f).
int cl_fold_sparse_f32(float* acc, long long n, const long long* idx,
                       const float* vals, long long k,
                       float scale, float w, int set_mode) {
    return fold_impl(acc, n, idx, vals, k, scale, w, set_mode);
}

}  // extern "C"
