// Native host-side data plane for the federated runtime.
//
// The reference's runtime is pure Python (SURVEY.md §2: "Native components:
// NONE expected") — the rebuild still ships this small C++ layer because the
// cross-silo configs (3400-client FEMNIST, BASELINE config #5) gather
// multi-GB client shard tensors on the host before device placement, and
// numpy's fancy-index gather is single-threaded.  cl_gather_rows is a
// thread-parallel row gather: dst[i] = src[indices[i]] for row_bytes-sized
// rows.  Loaded via ctypes (native/__init__.py) with a numpy fallback when
// the toolchain is absent.
//
// Build: native/build.py (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// Returns 0 on success, -1 on a bad index (bounds-checked up front so no
// partial writes from bad input).
int cl_gather_rows(const uint8_t* src, int64_t n_src_rows, int64_t row_bytes,
                   const int64_t* indices, int64_t n_out_rows,
                   uint8_t* dst, int32_t n_threads) {
  for (int64_t i = 0; i < n_out_rows; ++i) {
    if (indices[i] < 0 || indices[i] >= n_src_rows) return -1;
  }
  if (n_threads < 1) n_threads = 1;
  int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  n_threads = static_cast<int32_t>(
      std::min<int64_t>(n_threads, std::max<int64_t>(1, hw)));
  // Small jobs: threading overhead dominates, run inline.
  if (n_out_rows * row_bytes < (int64_t)1 << 22 || n_threads == 1) {
    for (int64_t i = 0; i < n_out_rows; ++i) {
      std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
    return 0;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n_out_rows + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(lo + chunk, n_out_rows);
    if (lo >= hi) break;
    workers.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                    static_cast<size_t>(row_bytes));
      }
    });
  }
  for (auto& w : workers) w.join();
  return 0;
}

// Version marker so a stale cached .so is detected and rebuilt.
int cl_abi_version() { return 3; }  // v3: + cl_fold_sparse_* (fold.cpp)

}  // extern "C"
