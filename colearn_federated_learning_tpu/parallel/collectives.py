"""Collectives with gradient conventions for sequence parallelism.

Under SP a model has two parameter regions:
- the TOKEN path (embeddings, transformer blocks): forward consumes
  sequence SHARDS, so each device's grad is a PARTIAL sum that must be
  summed across the axis;
- the REPLICATED path (anything after the pooling psum, e.g. the
  classifier head): forward is identical on every device, so each
  device's grad is already the FULL grad and summing would scale it by S.

Rather than classifying params, we fix the convention at the single choke
point where the two regions meet: ``psum_for_grad_pmean`` is a psum whose
backward multiplies the cotangent by the axis size S.  Pair it with a
plain ``lax.pmean`` over ALL grads:

  token path:  (partial · S)  --pmean-->  Σ partial      = full ✓
  replicated:  full           --pmean-->  full           = full ✓

fed/local.py and parallel/sp.py apply the pmean; models insert this psum
at their pooling/reduction boundary (models/bert.py).
"""

from __future__ import annotations

import functools

import jax
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_for_grad_pmean(x, axis_name: str):
    """``lax.psum(x, axis_name)`` whose backward is also a psum (=S·cot)."""
    return lax.psum(x, axis_name)


def _fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _bwd(axis_name, _, g):
    # g is replicated across the axis, so psum(g) == S * g.
    return (lax.psum(g, axis_name),)


psum_for_grad_pmean.defvjp(_fwd, _bwd)
