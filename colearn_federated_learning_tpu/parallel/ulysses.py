"""Ulysses-style sequence parallelism: all-to-all over attention heads.

The second of the two standard long-context layouts (DeepSpeed-Ulysses,
Jacobs et al. 2309.14509 — pattern only; ring attention in
parallel/ring.py is the first):

- inputs arrive SEQUENCE-sharded: each device holds (B, L/S, H, D);
- one ``all_to_all`` re-shards to HEAD-sharded (B, L, H/S, D) — every
  device now sees the FULL sequence for its head group;
- plain dense attention runs locally (no cross-device softmax state at
  all, unlike the ring's rotating online-softmax recurrence);
- a second ``all_to_all`` restores sequence sharding.

Trade-offs vs the ring: two all-to-alls of the whole activation per
attention call instead of S ppermutes of K/V — cheaper when S is large
and ICI all-to-all bandwidth is good, but it requires ``H % S == 0``
(heads must split across the axis) while the ring has no head
constraint.  Both compose with the same grad-pmean trainer convention
(params replicated over ``seq``; fed/local.py).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from colearn_federated_learning_tpu.parallel.ring import dense_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: Optional[jax.Array] = None,
    *,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Attention with the sequence axis sharded over ``axis_name``.

    Args/returns match :func:`parallel.ring.ring_attention`: local blocks
    ``(B, L_local, H, D)`` in and out, optional ``(B, L_local)`` key
    padding mask.  Must run inside ``shard_map`` with ``axis_name`` a
    mesh axis of size S where ``H % S == 0``.
    """
    import jax.numpy as jnp

    s = lax.psum(1, axis_name)
    H = q.shape[2]
    if H % s != 0:
        raise ValueError(
            f"ulysses attention needs heads ({H}) divisible by the "
            f"{axis_name!r} axis size ({s}); use attn_impl='ring' otherwise"
        )

    # ONE stacked collective for q/k/v instead of three — collective
    # launch latency is per-call, and this runs every layer of every
    # local step.  Stacked layout: (3, B, L/S, H, D).
    qkv = jnp.stack([q, k, v])
    qkv = lax.all_to_all(qkv, axis_name, split_axis=3, concat_axis=2,
                         tiled=True)                 # (3, B, L, H/S, D)
    mask_full = (
        lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
        if kv_mask is not None else None
    )
    out = dense_attention(qkv[0], qkv[1], qkv[2], mask_full, causal=causal)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)                # (B, L/S, H, D)
