"""Tensor parallelism: parameter partition rules over a ``model`` mesh axis.

The reference has no model parallelism at all (SURVEY.md §2: "TP / PP / SP /
EP ... absent — models are tiny"); this module is part of the rebuild's
distributed superset.  The design is the idiomatic XLA/GSPMD one (the
scaling-book recipe), NOT hand-written Megatron collectives:

- every parameter leaf gets a :class:`jax.sharding.PartitionSpec` assigning
  its "wide" dimension to the ``model`` axis (attention heads, MLP hidden,
  MoE experts),
- the federated round program runs under ``shard_map`` that is MANUAL over
  the ``clients`` (and ``seq``) axes but leaves ``model`` to the automatic
  partitioner (``axis_names={...}``, jax 0.9), so XLA inserts the TP
  all-reduces itself and fuses them into the matmul epilogues.

Because partitioning is semantic-preserving, the SAME flax modules run
unmodified — no sharded-bias double counting, no twin model definitions, and
the logical param pytree (checkpoints, wire payloads) is identical to the
single-chip one.

Rules are keyed on flax param paths:

==========================================  =======================  ==========
leaf (path suffix, shape)                   role                     spec
==========================================  =======================  ==========
``{query,key,value}/kernel`` (D, H, hd)     column (head) parallel   (·, model, ·)
``{query,key,value}/bias``   (H, hd)        column bias              (model, ·)
``out/kernel``               (H, hd, D)     row parallel             (model, ·, ·)
``Dense_0/kernel`` in a block (D, F)        MLP up projection        (·, model)
``Dense_0/bias``             (F,)           MLP up bias              (model,)
``Dense_1/kernel`` in a block (F, D)        MLP down projection      (model, ·)
``experts*`` leading dim E                  expert parallel          (model, ···)
everything else                             replicated               ()
==========================================  =======================  ==========

A dimension that does not divide by the ``model`` axis size is replicated
instead (GSPMD would otherwise pad; replication keeps numerics exact).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from colearn_federated_learning_tpu.parallel import partition


def param_specs(params: Any, axis: str, size: int) -> Any:
    """Pytree of :class:`PartitionSpec` matching ``params``' structure.

    Since PR 9 this is the regex rule engine in parallel/partition.py
    (``TRANSFORMER_RULES`` encodes exactly the module table above) —
    one source of partition truth shared with the sharded server plane.
    """
    return partition.match_partition_rules(
        partition.TRANSFORMER_RULES, params, axis=axis, sizes={axis: size}
    )


def shard_params(params: Any, mesh: Mesh, axis: str) -> Any:
    """Place ``params`` on ``mesh`` with the TP partition rules applied.

    Leaves become :class:`jax.Array`\\ s sharded over the ``axis`` mesh axis
    (replicated over all other axes); downstream jit programs inherit these
    shardings, and ``zeros_like``-style state init preserves them.
    """
    size = mesh.shape[axis]
    specs = param_specs(params, axis, size)
    return partition.shard_tree(params, specs, mesh)


def sharded_fraction(params: Any, axis: str, size: int) -> float:
    """Fraction of parameter COUNT whose leaves are sharded over ``axis`` —
    a quick sanity metric for tests and logs (a transformer should be well
    above 0.5; 0.0 means the rules matched nothing)."""
    specs = jax.tree.leaves(
        param_specs(params, axis, size), is_leaf=lambda x: isinstance(x, P)
    )
    leaves = jax.tree.leaves(params)
    tot = sharded = 0
    for w, s in zip(leaves, specs):
        n = int(np.prod(np.shape(w))) if np.shape(w) else 1
        tot += n
        if any(e == axis for e in s):
            sharded += n
    return sharded / max(tot, 1)
