"""Tensor parallelism: parameter partition rules over a ``model`` mesh axis.

The reference has no model parallelism at all (SURVEY.md §2: "TP / PP / SP /
EP ... absent — models are tiny"); this module is part of the rebuild's
distributed superset.  The design is the idiomatic XLA/GSPMD one (the
scaling-book recipe), NOT hand-written Megatron collectives:

- every parameter leaf gets a :class:`jax.sharding.PartitionSpec` assigning
  its "wide" dimension to the ``model`` axis (attention heads, MLP hidden,
  MoE experts),
- the federated round program runs under ``shard_map`` that is MANUAL over
  the ``clients`` (and ``seq``) axes but leaves ``model`` to the automatic
  partitioner (``axis_names={...}``, jax 0.9), so XLA inserts the TP
  all-reduces itself and fuses them into the matmul epilogues.

Because partitioning is semantic-preserving, the SAME flax modules run
unmodified — no sharded-bias double counting, no twin model definitions, and
the logical param pytree (checkpoints, wire payloads) is identical to the
single-chip one.

Rules are keyed on flax param paths:

==========================================  =======================  ==========
leaf (path suffix, shape)                   role                     spec
==========================================  =======================  ==========
``{query,key,value}/kernel`` (D, H, hd)     column (head) parallel   (·, model, ·)
``{query,key,value}/bias``   (H, hd)        column bias              (model, ·)
``out/kernel``               (H, hd, D)     row parallel             (model, ·, ·)
``Dense_0/kernel`` in a block (D, F)        MLP up projection        (·, model)
``Dense_0/bias``             (F,)           MLP up bias              (model,)
``Dense_1/kernel`` in a block (F, D)        MLP down projection      (model, ·)
``experts*`` leading dim E                  expert parallel          (model, ···)
everything else                             replicated               ()
==========================================  =======================  ==========

A dimension that does not divide by the ``model`` axis size is replicated
instead (GSPMD would otherwise pad; replication keeps numerics exact).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _spec_for(path: str, shape: tuple[int, ...], axis: str, size: int):
    """PartitionSpec for one param leaf (see module table)."""

    def shard(dim: int):
        if shape[dim] % size:
            return P()  # not divisible → replicate, keep numerics exact
        spec = [None] * len(shape)
        spec[dim] = axis
        return P(*spec)

    leaf = path.rsplit("/", 1)[-1]
    parent = path.rsplit("/", 2)[-2] if path.count("/") else ""

    # MoE expert banks: stacked (E, ...) leaves under an "experts" module.
    if "experts" in path:
        return shard(0)
    # Token embedding table: vocab-sharded (Megatron-style).  GSPMD turns
    # the gather into a masked local lookup + all-reduce, keeping the
    # biggest single leaf of the text models off every chip.
    if leaf == "embedding" and len(shape) == 2:
        return shard(0)
    # Attention projections (models/attention.py DenseGeneral layout).
    if parent in ("query", "key", "value"):
        return shard(len(shape) - 2) if leaf == "kernel" else shard(0)
    if parent == "out" and leaf == "kernel" and len(shape) == 3:
        return shard(0)
    # Transformer-block MLP (models/bert.py, models/vit.py: Dense_0 up,
    # Dense_1 down inside each block).
    if "Block" in path and parent == "Dense_0":
        return shard(1) if leaf == "kernel" else shard(0)
    if "Block" in path and parent == "Dense_1" and leaf == "kernel":
        return shard(0)
    return P()


def param_specs(params: Any, axis: str, size: int) -> Any:
    """Pytree of :class:`PartitionSpec` matching ``params``' structure."""
    return jax.tree_util.tree_map_with_path(
        lambda path, w: _spec_for(_path_str(path), np.shape(w), axis, size),
        params,
    )


def shard_params(params: Any, mesh: Mesh, axis: str) -> Any:
    """Place ``params`` on ``mesh`` with the TP partition rules applied.

    Leaves become :class:`jax.Array`\\ s sharded over the ``axis`` mesh axis
    (replicated over all other axes); downstream jit programs inherit these
    shardings, and ``zeros_like``-style state init preserves them.
    """
    size = mesh.shape[axis]
    specs = param_specs(params, axis, size)
    return jax.tree.map(
        lambda w, s: jax.device_put(w, NamedSharding(mesh, s)), params, specs
    )


def sharded_fraction(params: Any, axis: str, size: int) -> float:
    """Fraction of parameter COUNT whose leaves are sharded over ``axis`` —
    a quick sanity metric for tests and logs (a transformer should be well
    above 0.5; 0.0 means the rules matched nothing)."""
    specs = jax.tree.leaves(
        param_specs(params, axis, size), is_leaf=lambda x: isinstance(x, P)
    )
    leaves = jax.tree.leaves(params)
    tot = sharded = 0
    for w, s in zip(leaves, specs):
        n = int(np.prod(np.shape(w))) if np.shape(w) else 1
        tot += n
        if any(e == axis for e in s):
            sharded += n
    return sharded / max(tot, 1)
