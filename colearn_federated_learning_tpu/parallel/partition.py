"""Regex-driven parameter partitioning: the sharded-server foundation.

PR 9's refactor: the global model, optimizer state, and aggregation live
SHARDED over a ``model`` mesh axis instead of replicated on every chip.
This module is the single source of partition truth for all of it:

- :func:`match_partition_rules` — an ordered ``(regex, spec)`` rule list
  over '/'-joined flax param paths → a pytree of
  :class:`~jax.sharding.PartitionSpec`.  First match wins (regex
  precedence); scalars fall back to replicated; a dimension that does not
  divide by its mesh-axis size replicates the whole leaf (GSPMD would
  pad — replication keeps numerics exact, the parallel/tp.py contract).
- :func:`make_shard_and_gather_fns` — per-leaf ``shard``/``gather``
  closures for moving a pytree onto and off a mesh.
- per-model rule sets: :data:`TRANSFORMER_RULES` (BERT/ViT qkv, MLP
  up/down, vocab-sharded embedding, MoE expert banks — the same table
  parallel/tp.py documents), :data:`CNN_RULES` (stem conv + dense head,
  output-channel sharded), and :func:`rules_for_model` to pick one.
- :class:`ServerPlacement` — the server-plane object the socket
  coordinator holds: shard/scatter/assemble a params-shaped tree over a
  1-D ``(model,)`` mesh so the streaming fold accumulates per-shard
  slices (no replicated device intermediate) and the downlink encoder
  reads device shards directly instead of ``jax.device_get`` of the
  full tree.
- :func:`host_tree` / :func:`leaf_gather_avoided` — per-shard host reads
  (the multi-host-legal alternative to a full-tree gather) and the
  bytes-of-replication-avoided accounting behind
  ``comm.gather_bytes_avoided_total``.

The rule grammar: each rule is ``(regex, spec)`` or ``(regex, spec,
ndim)``.  ``spec`` is ``None`` (replicate), an ``int`` dimension
(possibly negative) to shard over the default axis, or an explicit
:class:`PartitionSpec` right-aligned to the leaf rank.  An optional
``ndim`` restricts the rule to leaves of that exact rank (e.g. the
vocab-sharded ``embedding`` rule must not grab 1-D norm params that
happen to share the name).
"""

from __future__ import annotations

import re
from typing import Any, Iterable, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def path_str(path) -> str:
    """'/'-joined flax key path (``tree_map_with_path`` entries)."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


# ---------------------------------------------------------------- rules --

# Transformer models (models/bert.py, models/vit.py, MoE banks) — exactly
# the parallel/tp.py table, expressed as ordered regex rules.
TRANSFORMER_RULES: tuple = (
    (r"experts", 0),                               # MoE bank: (E, ...)
    (r"(^|/)embedding$", 0, 2),                    # vocab-sharded table
    (r"(^|/)(query|key|value)/kernel$", -2),       # (D, H, hd) head dim
    (r"(^|/)(query|key|value)/[^/]+$", 0),         # qkv bias (H, hd)
    (r"(^|/)out/kernel$", 0, 3),                   # row parallel (H, hd, D)
    (r"Block.*/Dense_0/kernel$", 1),               # MLP up (D, F)
    (r"Block.*/Dense_0/[^/]+$", 0),                # MLP up bias (F,)
    (r"Block.*/Dense_1/kernel$", 0),               # MLP down (F, D)
    (r"", None),                                   # everything else
)
BERT_RULES = TRANSFORMER_RULES

# CNN stem + dense head (models/cnn.py): shard the output-channel dim.
# The server plane only ever runs ELEMENTWISE math on these (fold, server
# optimizer), so any consistent sharding is numerics-exact.
CNN_RULES: tuple = (
    (r"Conv[^/]*/kernel$", -1),                    # HWIO: out channels
    (r"Conv[^/]*/bias$", 0),
    (r"Dense[^/]*/kernel$", -1),
    (r"Dense[^/]*/bias$", 0),
    (r"", None),
)

# Unknown models: try the transformer rules first, then the CNN ones.
DEFAULT_RULES: tuple = TRANSFORMER_RULES[:-1] + CNN_RULES

_TRANSFORMER_NAMES = ("bert", "vit", "transformer", "moe", "gpt")
_CNN_NAMES = ("cnn", "conv", "mlp", "dense", "logreg", "linear")


def rules_for_model(model_name: str) -> tuple:
    """Pick the rule set for a registered model name."""
    name = (model_name or "").lower()
    if any(k in name for k in _TRANSFORMER_NAMES):
        return TRANSFORMER_RULES
    if any(k in name for k in _CNN_NAMES):
        return CNN_RULES
    return DEFAULT_RULES


def _resolve_spec(spec, shape: tuple, axis: str,
                  sizes: Mapping[str, int]) -> P:
    """Turn one rule spec into a concrete PartitionSpec for ``shape``,
    replicating whenever the sharded dim would not divide evenly."""
    if spec is None:
        return P()
    if isinstance(spec, int):
        d = spec + len(shape) if spec < 0 else spec
        if not 0 <= d < len(shape):
            return P()
        size = sizes.get(axis, 0)
        if size and shape[d] % size:
            return P()           # not divisible → replicate, numerics exact
        out = [None] * len(shape)
        out[d] = axis
        return P(*out)
    # Explicit PartitionSpec, right-aligned to the leaf rank.
    entries = tuple(spec)
    pad = len(shape) - len(entries)
    if pad < 0:
        return P()
    entries = (None,) * pad + entries
    for d, name in enumerate(entries):
        if name is None:
            continue
        for ax in (name if isinstance(name, tuple) else (name,)):
            size = sizes.get(ax, 0)
            if size and shape[d] % size:
                return P()
    return P(*entries)


def match_partition_rules(
    rules: Sequence[tuple],
    params: Any,
    *,
    axis: str = "model",
    sizes: Optional[Mapping[str, int]] = None,
    mesh: Optional[Mesh] = None,
) -> Any:
    """Pytree of PartitionSpec for ``params`` from an ordered rule list.

    First rule whose regex ``re.search``-matches the '/'-joined path (and
    whose optional ``ndim`` constraint holds) wins.  Scalars are always
    replicated.  Raises ``ValueError`` for a path no rule matches — rule
    sets are expected to end with a catch-all ``(r"", None)``.
    """
    sizes = dict(sizes) if sizes is not None else (
        dict(mesh.shape) if mesh is not None else {}
    )
    compiled = []
    for rule in rules:
        pat, spec = rule[0], rule[1]
        ndim = rule[2] if len(rule) > 2 else None
        compiled.append((re.compile(pat), spec, ndim))

    def for_leaf(path, w):
        shape = np.shape(w)
        name = path_str(path)
        if len(shape) == 0:
            return P()           # scalar → replicated, regardless of rules
        for pat, spec, ndim in compiled:
            if ndim is not None and len(shape) != ndim:
                continue
            if pat.search(name):
                return _resolve_spec(spec, shape, axis, sizes)
        raise ValueError(
            f"no partition rule matched param {name!r} (shape {shape}); "
            "rule sets should end with a catch-all (r\"\", None)"
        )

    return jax.tree_util.tree_map_with_path(for_leaf, params)


def make_shard_and_gather_fns(specs: Any, mesh: Mesh) -> tuple[Any, Any]:
    """Per-leaf ``(shard_fns, gather_fns)`` trees for ``specs`` on ``mesh``.

    ``shard_fns[leaf](x)`` places ``x`` with its NamedSharding;
    ``gather_fns[leaf](x)`` reads it back to host numpy via per-shard
    reads (:func:`host_leaf`) — legal on multi-host meshes where a plain
    ``np.asarray`` of a non-fully-addressable array raises.
    """
    def make_pair(spec):
        sharding = NamedSharding(mesh, spec)

        def shard_fn(x, _s=sharding):
            return jax.device_put(x, _s)

        return shard_fn, host_leaf

    pairs = jax.tree.map(make_pair, specs,
                         is_leaf=lambda s: isinstance(s, P))
    shard_fns = jax.tree.map(lambda pr: pr[0], pairs,
                             is_leaf=lambda pr: isinstance(pr, tuple))
    gather_fns = jax.tree.map(lambda pr: pr[1], pairs,
                              is_leaf=lambda pr: isinstance(pr, tuple))
    return shard_fns, gather_fns


def shard_tree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """``device_put`` every leaf with its spec's NamedSharding."""
    return jax.tree.map(
        lambda w, s: jax.device_put(w, NamedSharding(mesh, s)),
        tree, specs,
    )


# ------------------------------------------------------- host-side reads --

def _index_key(index: tuple) -> tuple:
    """Hashable key for a shard's global-index tuple (slices are
    unhashable before 3.12)."""
    return tuple((s.start, s.stop, s.step) for s in index)


def host_leaf(a: Any) -> np.ndarray:
    """One (possibly sharded) array → host numpy via its addressable
    shards.  Never a device-side all-gather and never a full-array
    ``jax.device_get``: each device contributes exactly its own shard
    bytes, which is also the only legal read on a multi-host mesh."""
    if not isinstance(a, jax.Array):
        return np.asarray(a)
    shards = a.addressable_shards
    if len(shards) == 1:
        return np.asarray(shards[0].data)
    out = np.empty(a.shape, a.dtype)
    seen = set()
    for sh in shards:            # colearn: hot
        key = _index_key(sh.index)
        if key in seen:          # replicated copies: read once
            continue
        seen.add(key)
        # per-shard D2H read IS the point: each chip syncs only its own
        # slice, so there is no full-array transfer to batch after the loop
        out[sh.index] = np.asarray(sh.data)  # colearn: noqa(CL006): per-shard D2H is the point, no full-array sync
    return out


def host_tree(tree: Any) -> Any:
    """Per-shard host read of a whole pytree (see :func:`host_leaf`)."""
    return jax.tree.map(host_leaf, tree)


def leaf_gather_avoided(a: Any) -> int:
    """Bytes of per-chip replication a sharded leaf avoids: with ``n``
    distinct shards each chip holds ``nbytes/n`` instead of ``nbytes``,
    so a replicated layout (or the all-gather required to build one)
    would move/materialize ``nbytes·(n−1)/n`` more per chip."""
    if not isinstance(a, jax.Array):
        return 0
    try:
        shards = a.addressable_shards
    except Exception:
        return 0
    n = len({_index_key(sh.index) for sh in shards})
    if n <= 1:
        return 0
    return int(a.nbytes) * (n - 1) // n


def tree_gather_avoided(tree: Any) -> int:
    return sum(leaf_gather_avoided(l) for l in jax.tree.leaves(tree))


def estimate_gather_avoided(params: Any, rules: Sequence[tuple],
                            axis: str, size: int) -> int:
    """Pure shape math (no mesh, no devices): the per-chip replication
    bytes a ``size``-way sharded server avoids for ``params`` under
    ``rules`` — fleetsim's byte estimator for the sharded downlink."""
    if size <= 1:
        return 0
    specs = match_partition_rules(rules, params, axis=axis,
                                  sizes={axis: size})
    total = 0
    for w, s in zip(jax.tree.leaves(params),
                    jax.tree.leaves(specs,
                                    is_leaf=lambda x: isinstance(x, P))):
        if any(e == axis for e in s):
            nbytes = int(np.prod(np.shape(w))) * np.dtype(
                getattr(w, "dtype", np.float32)).itemsize
            total += nbytes * (size - 1) // size
    return total


def bytes_per_chip(tree: Any) -> int:
    """Max over devices of the bytes of ``tree`` resident on that chip
    (per-shard accounting; replicated leaves charge every chip, host
    numpy leaves charge one).  Deterministic on the forced-8-device CPU
    mesh — the measured stand-in for ``memory_stats()`` (empty on CPU
    backends) behind the mesh-smoke HBM sentinel."""
    per: dict = {}
    host = 0
    for l in jax.tree.leaves(tree):
        if isinstance(l, jax.Array):
            for sh in l.addressable_shards:
                per[sh.device] = per.get(sh.device, 0) + int(sh.data.nbytes)
        elif hasattr(l, "nbytes"):
            host += int(l.nbytes)
    return (max(per.values()) if per else 0) + host


# ------------------------------------------------------ server placement --

class ServerPlacement:
    """Sharded placement of the SERVER plane over a 1-D ``(model,)`` mesh.

    The socket coordinator's round math is purely elementwise (weighted
    fold, server optimizer), so slicing every tensor over the model axis
    is bitwise-exact: a per-shard sum in cohort order produces exactly
    the bytes of the full-leaf sum in the same order.  This object
    precomputes each leaf's distinct ``(device, index)`` shard layout and
    provides:

    - :meth:`shard` — place a params-shaped tree sharded on the mesh;
    - :meth:`slice_tree` — host-side scatter: each leaf → a tuple of its
      per-shard numpy slices (the StreamingFolder staging format, so no
      replicated device intermediate ever exists);
    - :meth:`assemble` — per-shard slices → a sharded ``jax.Array`` tree
      via ``make_array_from_single_device_arrays`` (every device receives
      only its own shard bytes).
    """

    def __init__(self, mesh: Mesh, axis: str, specs: Any, params: Any):
        if len(mesh.shape) != 1:
            raise ValueError(
                f"ServerPlacement wants a 1-D ({axis},) mesh, got axes "
                f"{tuple(mesh.shape)}"
            )
        self.mesh = mesh
        self.axis = axis
        self.specs = specs
        leaves, self.treedef = jax.tree.flatten(params)
        spec_leaves = self.treedef.flatten_up_to(specs)
        self._meta = []
        devices = list(mesh.devices.flat)
        self._dtypes = [np.dtype(getattr(w, "dtype", np.float32))
                        for w in leaves]
        for w, spec in zip(leaves, spec_leaves):
            shape = tuple(np.shape(w))
            sharding = NamedSharding(mesh, spec)
            dmap = sharding.devices_indices_map(shape)
            slices, seen = [], set()
            for d in devices:
                key = _index_key(tuple(
                    s if isinstance(s, slice) else slice(None)
                    for s in (dmap[d] or (slice(None),) * len(shape))
                ))
                if key in seen:
                    continue
                seen.add(key)
                slices.append((d, dmap[d]))
            self._meta.append((shape, spec, sharding, slices))

    @classmethod
    def from_params(cls, params: Any, mesh: Mesh, axis: str,
                    rules: Sequence[tuple]) -> "ServerPlacement":
        specs = match_partition_rules(rules, params, axis=axis,
                                      sizes=dict(mesh.shape))
        return cls(mesh, axis, specs, params)

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def sharded_fraction(self) -> float:
        """Fraction of parameter COUNT living sharded (vs replicated)."""
        tot = sharded = 0
        for shape, spec, _, _ in self._meta:
            n = int(np.prod(shape)) if shape else 1
            tot += n
            if any(e == self.axis for e in spec):
                sharded += n
        return sharded / max(tot, 1)

    def shard(self, tree: Any) -> Any:
        return shard_tree(tree, self.specs, self.mesh)

    def slice_tree(self, tree: Any) -> Any:
        """Each leaf → tuple of its distinct per-shard numpy slices (the
        symmetric scatter of a full host tensor onto the shard layout)."""
        leaves = self.treedef.flatten_up_to(tree)
        out = []
        for l, (shape, _, _, slices) in zip(leaves, self._meta):
            arr = np.asarray(l)
            out.append(tuple(
                np.ascontiguousarray(arr[idx]) for _, idx in slices
            ))
        return jax.tree.unflatten(self.treedef, out)

    def partition_flat_indices(
        self, leaf_pos: int, idx: np.ndarray, vals: np.ndarray,
    ) -> list[tuple[np.ndarray, np.ndarray, tuple]]:
        """Sparse counterpart of :meth:`slice_tree` for one leaf: scatter
        flat ``(indices, values)`` onto the shard layout without ever
        densifying.

        ``leaf_pos`` is the leaf's flatten-order position; ``idx`` holds
        flat (raveled) indices into the full leaf.  Returns one
        ``(local_flat_idx, values, shard_shape)`` per distinct shard, in
        :meth:`slice_tree`'s slice order, with indices offset-adjusted to
        the shard's coordinate frame — so scattering each piece into
        ``zeros(shard_shape)`` reproduces exactly the slice the dense
        path would have cut from a full scatter."""
        shape, _, _, slices = self._meta[leaf_pos]
        idx = np.asarray(idx, np.int64)
        if len(slices) == 1 or not shape:
            # Replicated (or scalar) leaf: the single shard IS the leaf.
            return [(idx, vals, shape)]
        multi = np.unravel_index(idx, shape)
        out = []
        for _, index in slices:
            starts = [0 if s.start is None else int(s.start) for s in index]
            stops = [shape[d] if s.stop is None else int(s.stop)
                     for d, s in enumerate(index)]
            sub_shape = tuple(b - a for a, b in zip(starts, stops))
            mask = np.ones(idx.shape, bool)
            for d in range(len(shape)):
                mask &= (multi[d] >= starts[d]) & (multi[d] < stops[d])
            local = np.ravel_multi_index(
                tuple(m[mask] - s for m, s in zip(multi, starts)), sub_shape
            )
            out.append((local.astype(np.int64), vals[mask], sub_shape))
        return out

    def assemble(self, sliced: Any) -> Any:
        """Per-shard slices (:meth:`slice_tree` layout) → sharded
        ``jax.Array`` tree; each slice is placed on ITS device only."""
        flat = jax.tree.leaves(sliced)
        it = iter(flat)
        out = []
        for shape, spec, sharding, slices in self._meta:
            parts = [next(it) for _ in slices]
            if len(parts) == 1:
                # Replicated leaf (single distinct shard): plain placement.
                out.append(jax.device_put(np.asarray(parts[0]).reshape(
                    shape if shape else ()), sharding))
                continue
            dtype = np.asarray(parts[0]).dtype
            mesh_sharding = NamedSharding(self.mesh, spec)
            arrays = [
                jax.device_put(np.ascontiguousarray(p, dtype), d)
                for p, (d, _) in zip(parts, slices)
            ]
            out.append(jax.make_array_from_single_device_arrays(
                shape, mesh_sharding, arrays))
        return jax.tree.unflatten(self.treedef, out)

    def shapes_tree(self) -> Any:
        """Host-side zero-memory shape/dtype stand-in for the params tree
        (read-only broadcast views) — folder/recovery templates without
        gathering the sharded arrays."""
        out = [
            np.broadcast_to(np.zeros((), dt), shape)
            for (shape, _, _, _), dt in zip(self._meta, self._dtypes)
        ]
        return jax.tree.unflatten(self.treedef, out)


def make_server_placement(
    params: Any,
    tp_size: int,
    axis: str,
    model_name: str,
    devices: Optional[Iterable] = None,
) -> Optional[ServerPlacement]:
    """Build the coordinator's sharded-server placement, or ``None`` (with
    a labeled ``fed.mesh_fallback_total`` count) when the host cannot
    honor ``tp_size`` or the rules shard nothing of this model."""
    from colearn_federated_learning_tpu import telemetry

    if tp_size <= 1:
        return None
    devs = list(devices) if devices is not None else list(jax.devices())
    reg = telemetry.get_registry()
    if len(devs) < tp_size:
        reg.counter("fed.mesh_fallback_total",
                    labels={"reason": "insufficient_devices"}).inc()
        return None
    mesh = Mesh(np.array(devs[:tp_size]), (axis,))
    placement = ServerPlacement.from_params(
        params, mesh, axis, rules_for_model(model_name))
    if placement.sharded_fraction() == 0.0:
        reg.counter("fed.mesh_fallback_total",
                    labels={"reason": "rules_matched_nothing"}).inc()
        return None
    return placement
