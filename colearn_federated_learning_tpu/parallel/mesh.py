"""Named-axis device mesh construction.

The federated engine lays clients over a 1-D mesh (fed/engine.py); the
sequence-parallel path wants a 2-D (clients, seq) mesh.  These helpers build
both from whatever devices are visible, and — on multi-host pods — put the
fastest-varying axes on ICI and the outermost axis on DCN, matching the
"collectives ride ICI, not DCN" layout rule.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def factor_devices(n: int, num_axes: int) -> tuple[int, ...]:
    """Factor ``n`` devices into ``num_axes`` mesh-axis sizes.

    Greedy: trailing axes take the smallest divisor > 1 so the leading
    (client/data) axis keeps the bulk — EXCEPT when the remainder is prime
    (incl. 2): then the whole remainder goes to the trailing axis, e.g.
    ``factor_devices(7, 2) == (1, 7)``, so a ring (``seq``) axis is never
    a useless size-1 axis.
    """
    if num_axes <= 0:
        raise ValueError("num_axes must be >= 1")
    sizes = []
    remaining = n
    for _ in range(num_axes - 1):
        # Smallest divisor > 1 for the trailing axes, so the leading axis
        # keeps the bulk.  When ``remaining`` is prime (incl. 2) the WHOLE
        # remainder goes to the trailing axis rather than a useless size-1
        # axis — a (1, n) mesh still gives ring attention a real ``seq``
        # ring, whereas (n, 1) broke ``attn_impl="ring"`` auto-meshing.
        d = next(
            (f for f in range(2, remaining) if remaining % f == 0),
            remaining if remaining > 1 else 1,
        )
        sizes.append(d)
        remaining //= d
    sizes.append(remaining)
    return tuple(reversed(sizes))


def make_mesh(
    axis_names: Sequence[str],
    axis_sizes: Optional[Sequence[int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a named-axis :class:`jax.sharding.Mesh`.

    - ``axis_sizes=None``: auto-factor all visible devices over the axes
      (first axis largest).  A ``-1`` entry absorbs the remaining devices.
    - Multi-host (``jax.process_count() > 1``): uses
      ``mesh_utils.create_hybrid_device_mesh`` so the FIRST axis spans DCN
      (one mesh row per host — the federated client axis tolerates slow
      links because it only carries one psum per round) and the remaining
      axes stay inside each host's ICI domain.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        sizes = list(factor_devices(n, len(axis_names)))
    else:
        sizes = list(axis_sizes)
        if sizes.count(-1) > 1:
            raise ValueError("at most one axis size may be -1")
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1]))
            if known == 0 or n % known:
                raise ValueError(f"cannot infer -1 axis: {n} devices over {sizes}")
            sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"mesh {dict(zip(axis_names, sizes))} needs {int(np.prod(sizes))} "
            f"devices, have {n}"
        )

    if jax.process_count() > 1:
        per_host = n // jax.process_count()
        if sizes[0] % jax.process_count() == 0 and per_host:
            from jax.experimental import mesh_utils

            dcn = [jax.process_count()] + [1] * (len(sizes) - 1)
            ici = [sizes[0] // jax.process_count()] + list(sizes[1:])
            # ``process_is_granule=True`` because our DCN shape counts
            # PROCESSES: the default granule is the TPU ``slice_index``,
            # which is one value across a whole single-slice pod (and
            # absent on CPU multi-process), so the slice-based grouping
            # could never match a process-shaped dcn_mesh_shape.  Genuine
            # shape mismatches still raise.
            arr = mesh_utils.create_hybrid_device_mesh(
                ici, dcn_mesh_shape=dcn, devices=devices,
                process_is_granule=True,
            )
            return Mesh(arr, tuple(axis_names))
    return Mesh(np.array(devices).reshape(sizes), tuple(axis_names))
