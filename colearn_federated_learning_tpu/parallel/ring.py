"""Ring attention: sequence parallelism over a mesh axis.

Long-context attention whose K/V blocks rotate around the mesh axis via
``jax.lax.ppermute`` while each device keeps its local Q block resident —
attention over a sequence S·L long costs each chip S steps of (L × L)
blockwise attention plus one neighbour-to-neighbour ICI transfer per step,
instead of materialising the full (S·L)² score matrix anywhere.  Softmax is
accumulated online (running max ``m``, normaliser ``l``, weighted-value
accumulator ``acc`` in float32), the same rescaling recurrence as
flash attention (ops/attention.py) applied across devices instead of across
VMEM tiles.

The reference has no long-context path at all (SURVEY.md §5 "Long-context /
SP: absent"); this is the TPU-native capability the rebuild adds so the
BERT/ViT federated configs scale past one chip's HBM.

Must be called inside ``shard_map`` with the sequence dimension sharded over
``axis_name``.  Works on any backend (tests run it on the 8-device virtual
CPU mesh; on TPU the ppermute rides ICI).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30  # additive mask value; big-negative not -inf so exp() is exact 0


def _block_attn(q, k, v, bias, m, l, acc, scale):
    """One blockwise online-softmax update.

    q: (B, Lq, H, D), k/v: (B, Lk, H, D), bias: (B, 1|H, Lq, Lk) additive.
    Carries m, l: (B, H, Lq) and acc: (B, Lq, H, D), all float32.
    """
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        logits = logits + bias
    m_new = jnp.maximum(m, logits.max(axis=-1))
    p = jnp.exp(logits - m_new[..., None])            # (B, H, Lq, Lk)
    # Fully-masked blocks: m_new sits at the _NEG floor, making exp(0)=1 for
    # masked entries; force those to 0 so padding never contributes.
    p = jnp.where(logits > 0.5 * _NEG, p, 0.0)
    corr = jnp.exp(m - m_new)                          # (B, H, Lq)
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: Optional[jax.Array] = None,
    *,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Attention with the sequence axis sharded over ``axis_name``.

    Args:
      q, k, v: local blocks ``(B, L_local, H, D)`` — the global sequence is
        ``axis_size * L_local`` long, laid out in axis-index order.
      kv_mask: optional ``(B, L_local)`` bool; False = padding key (masked
        out everywhere, like BERT's padding mask).
      causal: mask by GLOBAL position (query attends to keys ≤ its global
        index), for decoder-style long-context models.

    Returns the local output block ``(B, L_local, H, D)`` in q's dtype.
    Fully-masked query rows return 0.
    """
    s = lax.psum(1, axis_name)                  # devices on the ring
    my = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    perm = [(j, (j + 1) % s) for j in range(s)]

    qf = q.astype(jnp.float32)
    q_pos = my * Lq + lax.iota(jnp.int32, Lq) if causal else None

    def attend(i, m, l, acc, k_blk, v_blk, mask_blk):
        # After i rotations device ``my`` holds the block ORIGINATED by
        # device (my - i) mod s; global key positions follow from that.
        src = (my - i) % s
        bias = None
        if mask_blk is not None:
            bias = jnp.where(mask_blk, 0.0, _NEG)[:, None, None, :]
        if causal:
            k_pos = src * Lk + lax.iota(jnp.int32, Lk)
            cmask = (q_pos[:, None] >= k_pos[None, :]).astype(jnp.float32)
            cbias = (1.0 - cmask) * _NEG                    # (Lq, Lk)
            bias = cbias[None, None] if bias is None else bias + cbias[None, None]
        return _block_attn(qf, k_blk, v_blk, bias, m, l, acc, scale)

    def step(i, carry):
        # Rotation LEADS the step so the last iteration does not pay a
        # final, discarded neighbour transfer (1/s of total ring traffic).
        m, l, acc, k_blk, v_blk, mask_blk = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if mask_blk is not None:
            mask_blk = lax.ppermute(mask_blk, axis_name, perm)
        m, l, acc = attend(i, m, l, acc, k_blk, v_blk, mask_blk)
        return m, l, acc, k_blk, v_blk, mask_blk

    m0 = jnp.full((B, H, Lq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)
    acc0 = jnp.zeros((B, Lq, H, D), jnp.float32)
    m0, l0, acc0 = attend(0, m0, l0, acc0, k, v, kv_mask)   # home block
    m, l, acc, _, _, _ = lax.fori_loop(
        1, s, step, (m0, l0, acc0, k, v, kv_mask)
    )
    out = acc / jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: Optional[jax.Array] = None,
    *,
    causal: bool = False,
) -> jax.Array:
    """Single-device reference with the same (B, L, H, D) signature — the
    numerics oracle ring/flash attention are tested against, and the
    ``attn_impl="dense"`` core in models/attention.py."""
    Lq, Lk = q.shape[1], k.shape[1]
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (q.shape[-1] ** 0.5)
    if kv_mask is not None:
        logits = jnp.where(kv_mask[:, None, None, :], logits, _NEG)
    if causal:
        qp = lax.iota(jnp.int32, Lq)[:, None]
        kp = lax.iota(jnp.int32, Lk)[None, :]
        logits = jnp.where((qp >= kp)[None, None], logits, _NEG)
    p = jax.nn.softmax(logits, axis=-1)
    # Fully-masked rows: softmax over all-_NEG is uniform; zero them to
    # match ring_attention's convention.
    if kv_mask is not None:
        any_key = jnp.any(kv_mask, axis=-1)[:, None, None, None]
        p = p * any_key
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype))
    return out.astype(q.dtype)
