"""Sequence-parallel model execution helpers.

A model built with ``seq_axis_name`` (models/registry.py) computes on
sequence SHARDS: ring attention over the axis, global position offsets,
psum-finished pooling.  These helpers wrap such a model in the
``shard_map`` it requires, for use OUTSIDE the federated engine (the engine
wires SP into its own round shard_map; see fed/engine.py).
"""

from __future__ import annotations

from typing import Callable

import jax
from colearn_federated_learning_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_sp_apply(model, mesh: Mesh, seq_axis: str = "seq") -> Callable:
    """``fn(params, ids) -> logits`` running ``model`` sequence-parallel.

    ``ids``: full (B, L) token batch; internally sharded (B, L/S) per
    device along ``seq_axis``.  Logits are replicated (the model's pooling
    psum makes them identical on every shard).
    """
    if seq_axis not in mesh.shape:
        raise ValueError(f"mesh {tuple(mesh.shape)} has no {seq_axis!r} axis")

    def fwd(params, ids):
        return model.apply({"params": params}, ids, train=False)

    fn = shard_map(
        fwd,
        mesh=mesh,
        in_specs=(P(), P(None, seq_axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def make_sp_loss_grad(model, loss_fn: Callable, mesh: Mesh,
                      seq_axis: str = "seq") -> Callable:
    """``fn(params, ids, labels) -> (loss, grads)`` sequence-parallel.

    Grads are pmean'd over ``seq_axis``; paired with the model's
    ``psum_for_grad_pmean`` pooling collective (parallel/collectives.py)
    this reconstructs the exact full-sequence gradient, replicated on every
    device (ready for any optimizer step).
    """
    if seq_axis not in mesh.shape:
        raise ValueError(f"mesh {tuple(mesh.shape)} has no {seq_axis!r} axis")

    def local(params, ids, labels):
        logits = model.apply({"params": params}, ids, train=True)
        return loss_fn(logits, labels)

    def body(params, ids, labels):
        loss, grads = jax.value_and_grad(local)(params, ids, labels)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, seq_axis), grads)
        return loss, grads

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(None, seq_axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)
