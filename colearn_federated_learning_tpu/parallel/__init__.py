"""Multi-chip parallelism: meshes, ring attention, sequence parallelism.

The reference has no SPMD layer — its only parallelism is the federated
round itself over TCP (SURVEY.md §2 "Parallelism strategies").  The rebuild
is TPU-native, so scale comes from `jax.sharding` meshes instead:

- ``mesh``:  named-axis mesh construction (clients × seq × model), ICI-first
  with a DCN-aware hybrid layout for multi-host pods.
- ``partition``: regex-driven param partition rules → PartitionSpec trees,
  shard/gather fns, and the sharded server-plane placement (PR 9).
- ``ring``:  ring attention — blockwise attention with K/V blocks rotating
  around a mesh axis via ``lax.ppermute``, online-softmax accumulation; the
  long-context sequence-parallel primitive.
- ``sp``:    sequence-parallel transformer forward built on ``ring``.
"""

from colearn_federated_learning_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    factor_devices,
)
from colearn_federated_learning_tpu.parallel.partition import (  # noqa: F401
    CNN_RULES,
    BERT_RULES,
    DEFAULT_RULES,
    TRANSFORMER_RULES,
    ServerPlacement,
    make_server_placement,
    make_shard_and_gather_fns,
    match_partition_rules,
    rules_for_model,
)
from colearn_federated_learning_tpu.parallel.ring import (  # noqa: F401
    ring_attention,
)
