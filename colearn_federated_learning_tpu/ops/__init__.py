"""Pallas TPU kernels for the hot ops.

XLA already fuses the elementwise work around the framework's matmuls; the
kernels here cover what fusion can't: ``attention`` implements blockwise
flash attention (never materialises the (L, L) score matrix in HBM).  All
kernels run in interpret mode on CPU so the virtual-mesh test suite
exercises identical code paths.
"""

from colearn_federated_learning_tpu.ops.attention import (  # noqa: F401
    flash_attention,
)
