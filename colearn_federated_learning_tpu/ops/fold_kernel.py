"""Device-resident wire-speed fold: fused, jitted ingest kernels.

The StreamingFolder's hot path (comm/aggregation.py) is a per-update
host-numpy scatter.  This module moves that fold onto the accelerator:
one compiled kernel decodes a BATCH of buffered topk/topk8 contributions
(int8 values dequantized by their per-leaf scale), applies each
contribution's aggregation weight and scatter-adds the lot into the dense
accumulator with ``jnp`` ``.at[idx].add(vals)`` — end to end inside one
XLA computation, eager-free.  Dense and LoRA-factor contributions ride a
matching batched add kernel.

**Bitwise contract.**  The host fold is the parity oracle, so the kernel
reproduces its float semantics exactly:

- the batch folds through ``lax.scan`` — ONE compiled dispatch for N
  buffered contributions, but the accumulation order inside it is the
  cohort order, add for add, so the result is bit-identical to the host's
  sequential fold (a segment-sum/psum reorder would not be);
- the first contribution densifies by ASSIGNMENT (``.at[].set``) into
  fresh zeros, exactly like the host's ``flat[idx] = vals``;
- dequant multiplies round in host order: ``(value * scale) * weight``,
  two float32 roundings (plain topk values carry ``scale = 1.0`` — an
  exact identity multiply);
- padding uses ``mode='drop'`` (index == leaf size): a padded entry never
  touches the accumulator, so bucketing cannot normalize a ``-0.0``;
  padded DENSE rows are masked with ``jnp.where`` for the same reason.

**Compile-once contract.**  Kernels are cached per model: the module
cache is keyed on the flattened per-slot shape fingerprint, and batch /
top-k extents are padded up to power-of-two buckets so jitter in cohort
size or adaptive-k never retraces.  Every jitted entry point is wrapped
in a :class:`telemetry.runtime.CompileTracker`, making "compiles once per
model" a counter the tests pin, not a comment.

**Backends.**  ``xla`` is the device path proper.  On a CPU-only jax
backend XLA's scatter is slower than numpy, so ``auto`` resolves to the
``native`` lowering there: the same fused fold (decode + weight + scatter
in one pass over the staged pairs, ``native/src/fold.cpp``) on the host
the traffic already lands on — bit-identical to both the host oracle and
the ``xla`` kernel, and faster than the unfused numpy path.  On real
accelerators ``auto`` resolves to ``xla``.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional, Sequence

import numpy as np

BACKENDS = ("auto", "xla", "native")


def _bucket(n: int) -> int:
    """Smallest power of two >= n (>= 1) — the pad target that keeps the
    jit signature stable under cohort-size / adaptive-k jitter."""
    b = 1
    while b < n:
        b <<= 1
    return b


def resolve_backend(backend: str = "auto") -> str:
    """``auto`` → ``native`` on a CPU jax backend with the native library
    available, else ``xla``.  ``COLEARN_FOLD_BACKEND`` overrides (tests
    pin each lowering explicitly)."""
    backend = os.environ.get("COLEARN_FOLD_BACKEND", backend or "auto")
    if backend not in BACKENDS:
        raise ValueError(f"unknown fold backend {backend!r}")
    if backend != "auto":
        return backend
    import jax

    from colearn_federated_learning_tpu import native

    if jax.default_backend() == "cpu" and native.load() is not None:
        return "native"
    return "xla"


class FoldKernel:
    """Batched fold over a fixed SLOT layout.

    A slot is one accumulator piece: one leaf of the model tree, or one
    shard of a leaf under a ServerPlacement, always flat float32.  The
    folder owns the tree<->slot mapping; the kernel only ever sees
    ``sizes`` (the per-slot element counts) and operates on ``acc`` — a
    list of flat arrays (device-resident under ``xla``, host numpy under
    ``native``) that stays resident across calls until :meth:`to_host`.

    ``fold_sparse(acc, batch)``: ``batch`` is a list of
    ``(weight, slots)`` stages, ``slots`` one ``(idx int64, raw_vals,
    scale)`` triple per slot (``raw_vals`` int8 for topk8, float32 for
    topk; one dtype per batch).  ``fold_dense(acc, batch)``: ``batch`` is
    a list of per-slot lists of flat float32 contributions (pre-scaled at
    staging, like the host path).  Both accept ``acc=None`` to start a
    fold with the host's first-contribution semantics.
    """

    def __init__(self, sizes: Sequence[int], backend: str = "auto"):
        self.sizes = tuple(int(s) for s in sizes)
        self.backend = resolve_backend(backend)
        self.sparse_tracker = None
        self.dense_tracker = None
        if self.backend == "xla":
            self._build_jitted()

    # ------------------------------------------------------- xla path --
    def _build_jitted(self) -> None:
        import jax
        import jax.numpy as jnp

        from colearn_federated_learning_tpu.telemetry.runtime import (
            CompileTracker,
        )

        sizes = self.sizes

        def dequant(v, s, ws):
            # [B, k] raw values x [B] scales x [B] weights -> [B, k] f32
            # contributions, rounded in host order: (value*scale)*weight,
            # two float32 roundings.  Computed OUTSIDE the scan so the
            # whole batch materializes as a while-loop input — the
            # scatter-add below then reads the product from memory.
            # (Computing it inside the scan body lets XLA:CPU contract
            # the weight multiply into the scatter-add as an FMA — one
            # rounding, 1 ulp off the host oracle; optimization_barrier
            # and bitcast round-trips are both stripped before codegen.)
            return (v.astype(jnp.float32) * s[:, None]) * ws[:, None]

        def sparse_step(accs, xs):
            xi, xc = xs
            return tuple(
                a.at[i].add(c, mode="drop")
                for a, i, c in zip(accs, xi, xc)
            ), None

        def sparse_into(accs, idxs, valss, scales, ws):
            contribs = tuple(
                dequant(v, s, ws) for v, s in zip(valss, scales))
            accs, _ = jax.lax.scan(
                sparse_step, tuple(accs), (idxs, contribs))
            return accs

        def sparse_init(idxs, valss, scales, ws):
            contribs = tuple(
                dequant(v, s, ws) for v, s in zip(valss, scales))
            # Row 0 is always a real contribution (the wrapper only calls
            # the init variant with a non-empty batch): assignment into
            # fresh zeros, exactly the host's first densify.
            accs = tuple(
                jnp.zeros(n, jnp.float32).at[i[0]].set(c[0], mode="drop")
                for n, i, c in zip(sizes, idxs, contribs)
            )
            rest = jax.tree.map(lambda x: x[1:], (idxs, contribs))
            accs, _ = jax.lax.scan(sparse_step, accs, rest)
            return accs

        def dense_step(accs, xs):
            x, ok = xs
            # where, not +0.0: a padded row must leave the accumulator's
            # exact bits (adding zero would normalize a -0.0 entry).
            return tuple(
                jnp.where(ok, a + xi, a) for a, xi in zip(accs, x)
            ), None

        def dense_into(accs, xss, valid):
            accs, _ = jax.lax.scan(dense_step, tuple(accs), (xss, valid))
            return accs

        def dense_init(xss, valid):
            # The host path ADOPTS the first contribution as the
            # accumulator; row 0 is always real here too.
            accs = tuple(x[0] for x in xss)
            rest = jax.tree.map(lambda x: x[1:], (xss, valid))
            accs, _ = jax.lax.scan(dense_step, accs, rest)
            return accs

        self._sparse_into = CompileTracker(
            jax.jit(sparse_into), name="fold_kernel.sparse_into")
        self._sparse_init = CompileTracker(
            jax.jit(sparse_init), name="fold_kernel.sparse_init")
        self._dense_into = CompileTracker(
            jax.jit(dense_into), name="fold_kernel.dense_into")
        self._dense_init = CompileTracker(
            jax.jit(dense_init), name="fold_kernel.dense_init")
        self.sparse_tracker = self._sparse_into
        self.dense_tracker = self._dense_into

    @property
    def compiles(self) -> int:
        """Total first-signature compiles across the jitted entry points
        (0 under the native lowering — nothing traces)."""
        if self.backend != "xla":
            return 0
        return sum(t.compiles for t in (
            self._sparse_into, self._sparse_init,
            self._dense_into, self._dense_init))

    @property
    def recompiles(self) -> int:
        if self.backend != "xla":
            return 0
        return sum(t.recompiles for t in (
            self._sparse_into, self._sparse_init,
            self._dense_into, self._dense_init))

    # ---------------------------------------------------- sparse fold --
    def _pad_sparse(self, batch: Sequence) -> tuple:
        """Pad/stack one sparse batch to bucketed extents.

        Batch rows pad with weight 0 and index == slot size; per-slot k
        pads likewise — every padded entry carries an out-of-range index,
        so ``mode='drop'`` guarantees it never touches the accumulator
        regardless of its (zero) value.
        """
        b = len(batch)
        bb = _bucket(b)
        vdt = batch[0][1][0][1].dtype
        idxs, valss, scales = [], [], []
        for s, n in enumerate(self.sizes):
            kb = _bucket(max(int(stage[1][s][0].size) for stage in batch))
            idx = np.full((bb, kb), n, np.int64)
            vals = np.zeros((bb, kb), vdt)
            sc = np.ones(bb, np.float32)
            for r, (_, slots) in enumerate(batch):
                si, sv, ss = slots[s]
                idx[r, :si.size] = si
                vals[r, :sv.size] = sv
                sc[r] = ss
            idxs.append(idx)
            valss.append(vals)
            scales.append(sc)
        ws = np.zeros(bb, np.float32)
        ws[:b] = [w for w, _ in batch]
        return tuple(idxs), tuple(valss), tuple(scales), ws

    def fold_sparse(self, acc: Optional[list], batch: Sequence) -> list:
        if not batch:
            return acc
        if self.backend == "native":
            return self._fold_sparse_native(acc, batch)
        idxs, valss, scales, ws = self._pad_sparse(batch)
        if acc is None:
            return list(self._sparse_init(idxs, valss, scales, ws))
        return list(self._sparse_into(tuple(acc), idxs, valss, scales, ws))

    def _fold_sparse_native(self, acc: Optional[list], batch) -> list:
        from colearn_federated_learning_tpu import native

        init = acc is None
        if init:
            acc = [np.zeros(n, np.float32) for n in self.sizes]
        for w, slots in batch:
            for a, (idx, vals, scale) in zip(acc, slots):
                if not native.fold_sparse(a, idx, vals, scale, w, init):
                    # No toolchain: the equivalent numpy expression —
                    # same multiply order, same set-then-add semantics.
                    v = (vals.astype(np.float32) * scale) * np.float32(w)
                    if init:
                        a[idx] = v
                    else:
                        a[idx] += v
            init = False
        return acc

    # ----------------------------------------------------- dense fold --
    def fold_dense(self, acc: Optional[list], batch: Sequence) -> list:
        if not batch:
            return acc
        if self.backend == "native":
            # Host-speed lowering: adopt-then-add, identical to the host
            # fold (numpy IS the wire-speed dense add on a CPU server).
            start = 0
            if acc is None:
                acc = list(batch[0])
                start = 1
            for slots in batch[start:]:
                for a, x in zip(acc, slots):
                    np.add(a, x, out=a)
            return acc
        bb = _bucket(len(batch))
        valid = np.zeros(bb, bool)
        valid[:len(batch)] = True
        xss = []
        for s, n in enumerate(self.sizes):
            x = np.zeros((bb, n), np.float32)
            for r, slots in enumerate(batch):
                x[r] = slots[s]
            xss.append(x)
        if acc is None:
            return list(self._dense_init(tuple(xss), valid))
        return list(self._dense_into(tuple(acc), tuple(xss), valid))

    # ------------------------------------------------------- delivery --
    def to_host(self, acc: Optional[list]) -> Optional[list]:
        """Accumulator slots as host numpy (ONE device→host transfer per
        fold block under ``xla``; a no-op under ``native``)."""
        if acc is None:
            return None
        return [a if isinstance(a, np.ndarray) else np.asarray(a)
                for a in acc]


_KERNELS: dict[tuple, FoldKernel] = {}
_KERNELS_LOCK = threading.Lock()


def get_kernel(sizes: Sequence[int], backend: str = "auto") -> FoldKernel:
    """The shared kernel for one model's slot layout — cached on the
    shape fingerprint so every folder of the same model (one per round on
    the coordinator) reuses the same jitted computations: the kernel
    compiles once per model, not once per round."""
    resolved = resolve_backend(backend)
    key = (tuple(int(s) for s in sizes), resolved)
    with _KERNELS_LOCK:
        k = _KERNELS.get(key)
        if k is None:
            k = _KERNELS[key] = FoldKernel(key[0], backend=resolved)
        return k


def clear_kernel_cache() -> None:
    """Drop cached kernels (tests that count compiles from scratch)."""
    with _KERNELS_LOCK:
        _KERNELS.clear()


__all__ = [
    "BACKENDS",
    "FoldKernel",
    "clear_kernel_cache",
    "get_kernel",
    "resolve_backend",
]
