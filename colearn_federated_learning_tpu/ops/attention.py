"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online-softmax accumulation: each grid step owns
one (batch·head, q-block) tile, keeps K/V VMEM-resident, and loops over
k-blocks with running (max, normaliser, accumulator) carries — the (L, L)
score matrix never exists in HBM, and the two matmuls per block land on the
MXU.  The same rescaling recurrence runs ACROSS devices in
parallel/ring.py; composing the two (ring outside, flash inside each block)
is the standard long-context stack.

Gradients: ``flash_attention`` carries a ``jax.custom_vjp`` whose backward
pass is ALSO blockwise Pallas (FlashAttention-2 recurrence): the forward
additionally emits the per-row logsumexp, and two kernels recompute
probabilities tile-by-tile — one accumulating dQ over k-blocks, one
accumulating dK/dV over q-blocks — so the (L, L) score matrix never exists
in either direction.

On CPU (the virtual-mesh test platform) the kernel runs in Pallas interpret
mode automatically.

The reference has no kernel layer at all (SURVEY.md §1: "no custom kernel
layer"); this is TPU-native capability the rebuild adds for the BERT/ViT
federated configs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG = -1e30

@functools.lru_cache(maxsize=None)
def _tpu_generation() -> int:
    """TPU generation of the default backend's first device (0 = unknown
    or not a TPU).  Drives the VMEM cap and block-size defaults: v4/v5/v6
    carry ≥128 MB physical VMEM, v2/v3 far less."""
    import re

    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return 0
    m = re.search(r"v(\d+)", kind.lower())
    return int(m.group(1)) if m else 0


def _default_block() -> int:
    """512 on v4+ (and in interpret mode, where it only shortens the Python
    loop); 128 on v2/v3 — or any TPU whose generation we cannot parse —
    because the 512 configuration needs the raised VMEM cap that
    ``_tpu_params`` only grants to known v4+ hardware."""
    gen = _tpu_generation()
    if gen >= 4:
        return 512
    if gen == 0 and jax.default_backend() != "tpu":
        return 512
    return 128


def _tpu_params():
    """Mosaic compiler params for the non-interpret (real TPU) path: the
    default 16 MB scoped-vmem cap rejects the fast 512-block configuration
    beyond L≈4k; v4/v5/v6 have ≥128 MB physical VMEM, so raise the cap and
    let the (bq, bk) f32 score tiles + whole-row K/V residency fit
    (measured on v5e: L=32k fwd+bwd needs ~100 MB of scoped buffers).  On
    older generations (v2/v3) the raised cap itself would fail Mosaic
    compilation — keep the conservative 16 MB default there."""
    from jax.experimental.pallas import tpu as pltpu

    if _tpu_generation() >= 4:
        return pltpu.CompilerParams(vmem_limit_bytes=112 * 1024 * 1024)
    return None


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _flash_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *,
                  block_k: int, scale: float, causal: bool, block_q: int):
    """One (batch·head, q-block) tile; K/V for the whole row are VMEM-resident.

    q_ref: (1, block_q, D) — this tile's queries
    k_ref, v_ref: (1, Lk, D) — all keys/values for this batch·head
    bias_ref: (1, Lk, 1) — additive key bias (0 valid / _NEG masked).  The
      sequence dim sits on the SUBLANE axis with a singleton lane dim:
      Mosaic requires a block's lane dim be 128-divisible or span the whole
      array, and in-kernel dynamic slices must be lane-aligned — k-block
      offsets are only 8-aligned, which the sublane axis accepts.
    o_ref: (1, block_q, D)
    lse_ref: (1, block_q, 1) — per-row logsumexp, the backward residual
    """
    Lk = k_ref.shape[1]
    D = q_ref.shape[2]
    num_kb = Lk // block_k
    qb = pl.program_id(1)

    # Keep the model dtype (bf16 on TPU) INTO the dots: the MXU runs
    # bf16×bf16→f32 at full rate, while f32×f32 costs ~4× — casting up
    # front would throw away most of the kernel's throughput.  All
    # accumulation (m/l/acc, softmax math) stays float32.
    q = q_ref[0]                                             # (bq, D)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq, bk)
        s = s * scale + bias_ref[0, pl.ds(kb * block_k, block_k), 0][None, :]
        if causal:
            q_pos = qb * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # Fully-masked blocks: m_new sits at the _NEG floor and exp(0)=1
        # would leak padding; zero those entries (same fix as ring.py).
        p = jnp.where(s > 0.5 * _NEG, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        acc_new = acc * corr + lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # Skip k-blocks entirely above the diagonal.
        num_kb = jnp.minimum(num_kb, pl.cdiv((qb + 1) * block_q, block_k))
    m0 = jnp.full((q.shape[0], 1), _NEG, jnp.float32)
    l0 = jnp.zeros((q.shape[0], 1), jnp.float32)
    acc0 = jnp.zeros((q.shape[0], D), jnp.float32)
    m, l, acc = lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)
    # Fully-masked rows (l == 0) get lse = +BIG so the backward's
    # exp(s - lse) recomputation yields exactly-zero probabilities there.
    lse = jnp.where(l > 0.0, m + jnp.log(jnp.maximum(l, 1e-30)), -_NEG)
    lse_ref[0] = lse


def _blocks(q, k, v, kv_mask, block_q, block_k, interpret):
    """Shared fwd/bwd plumbing: row-major (B·H, L, D) views padded to block
    multiples, the additive key bias, and resolved block sizes."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None:
        block_q = _default_block()
    if block_k is None:
        block_k = _default_block()

    bq = min(block_q, _round_up(Lq, 8))
    bk = min(block_k, _round_up(Lk, 8))
    Lq_p, Lk_p = _round_up(Lq, bq), _round_up(Lk, bk)

    # (B, L, H, D) -> (B*H, L, D) rows; pad sequence to block multiples.
    def to_rows(a, L_p):
        a = jnp.pad(a, ((0, 0), (0, L_p - a.shape[1]), (0, 0), (0, 0)))
        return a.transpose(0, 2, 1, 3).reshape(B * H, L_p, a.shape[-1])

    if kv_mask is None:
        bias = jnp.zeros((B, Lk), jnp.float32)
    else:
        bias = jnp.where(kv_mask, 0.0, _NEG).astype(jnp.float32)
    bias = jnp.pad(bias, ((0, 0), (0, Lk_p - Lk)), constant_values=_NEG)
    bias = bias[:, :, None]                                   # (B, Lk_p, 1)
    return (B, Lq, H, D, Lk, bq, bk, Lq_p, Lk_p, to_rows, bias, interpret)


def _flash_impl(q, k, v, kv_mask, causal: bool,
                block_q: int, block_k: int, interpret: Optional[bool],
                return_lse: bool = False):
    (B, Lq, H, D, Lk, bq, bk, Lq_p, Lk_p, to_rows, bias,
     interpret) = _blocks(q, k, v, kv_mask, block_q, block_k, interpret)
    qr, kr, vr = to_rows(q, Lq_p), to_rows(k, Lk_p), to_rows(v, Lk_p)

    kernel = functools.partial(
        _flash_kernel, block_k=bk, scale=1.0 / (D ** 0.5),
        causal=causal, block_q=bq,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Lq_p // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk_p, 1), lambda b, i: (b // H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Lq_p, 1), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=None if interpret else _tpu_params(),
    )(qr, kr, vr, bias)
    out = out.reshape(B, H, Lq_p, D).transpose(0, 2, 1, 3)[:, :Lq]
    if return_lse:
        return out, lse                                    # lse stays padded
    return out


def _flash_dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, *, block_k: int, scale: float,
                     causal: bool, block_q: int):
    """dQ for one (batch·head, q-block) tile, looping over k-blocks:
    p = exp(qk^T·s + bias − lse);  ds = p ⊙ (dO·V^T − Δ);  dq += ds·K·s."""
    Lk = k_ref.shape[1]
    num_kb = Lk // block_k
    qb = pl.program_id(1)

    # Model-dtype (bf16) operands into every dot, f32 accumulation out —
    # see _flash_kernel.  The softmax scale folds into s post-dot.
    q = q_ref[0]                                             # (bq, D)
    do = do_ref[0]                                           # (bq, D)
    lse = lse_ref[0]                                         # (bq, 1)
    delta = delta_ref[0]                                     # (bq, 1)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        s = s * scale + bias_ref[0, pl.ds(kb * block_k, block_k), 0][None, :]
        if causal:
            q_pos = qb * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse)                                 # exact softmax
        p = jnp.where(s > 0.5 * _NEG, p, 0.0)
        dp = lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        num_kb = jnp.minimum(num_kb, pl.cdiv((qb + 1) * block_q, block_k))
    dq = lax.fori_loop(
        0, num_kb, body, jnp.zeros((q.shape[0], q.shape[1]), jnp.float32)
    )
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                      delta_ref, dk_ref, dv_ref, *, block_q: int,
                      scale: float, causal: bool, block_k: int):
    """dK/dV for one (batch·head, k-block) tile, looping over q-blocks:
    dv += p^T·dO;  dk += ds^T·(q·s)."""
    Lq = q_ref.shape[1]
    num_qb = Lq // block_q
    kb = pl.program_id(1)

    # Model-dtype (bf16) operands into every dot, f32 accumulation out —
    # see _flash_kernel.  The softmax scale is applied to s post-dot and
    # folded into dk once at the end (dk = scale · Σ ds^T q).
    k_blk = k_ref[0]                                         # (bk, D)
    v_blk = v_ref[0]
    bias = bias_ref[0, :, 0][None, :]                        # (1, bk)

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]    # (bq, 1)
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)   # (bq, bk)
        s = s * scale + bias
        if causal:
            q_pos = qb * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse)
        p = jnp.where(s > 0.5 * _NEG, p, 0.0)
        dv_new = dv + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_new, dv_new

    qb0 = 0
    if causal:
        # q-blocks strictly above the diagonal contribute nothing.
        qb0 = (kb * block_k) // block_q
    D = k_blk.shape[1]
    dk, dv = lax.fori_loop(
        qb0, num_qb, body,
        (jnp.zeros((k_blk.shape[0], D), jnp.float32),
         jnp.zeros((k_blk.shape[0], D), jnp.float32)),
    )
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, kv_mask, out, lse, g, causal,
                    block_q, block_k, interpret):
    (B, Lq, H, D, Lk, bq, bk, Lq_p, Lk_p, to_rows, bias,
     interpret) = _blocks(q, k, v, kv_mask, block_q, block_k, interpret)
    qr, kr, vr = to_rows(q, Lq_p), to_rows(k, Lk_p), to_rows(v, Lk_p)
    gr = to_rows(g, Lq_p)
    # Δ = rowsum(dO ⊙ O): tiny, batched — plain XLA, not worth a kernel.
    # Padded query rows have g = 0, so their Δ and ds vanish.
    outr = to_rows(out, Lq_p)
    delta = jnp.sum(gr.astype(jnp.float32) * outr.astype(jnp.float32),
                    axis=-1)[:, :, None]                     # (B·H, Lq_p, 1)

    scale = 1.0 / (D ** 0.5)
    dq_kernel = functools.partial(_flash_dq_kernel, block_k=bk, scale=scale,
                                  causal=causal, block_q=bq)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, Lq_p // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk_p, 1), lambda b, i: (b // H, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq_p, D), q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else _tpu_params(),
    )(qr, kr, vr, bias, gr, lse, delta)

    dkv_kernel = functools.partial(_flash_dkv_kernel, block_q=bq, scale=scale,
                                   causal=causal, block_k=bk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, Lk_p // bk),
        in_specs=[
            pl.BlockSpec((1, Lq_p, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, 1), lambda b, j: (b // H, j, 0)),
            pl.BlockSpec((1, Lq_p, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Lq_p, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Lq_p, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lk_p, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Lk_p, D), v.dtype),
        ],
        interpret=interpret,
        compiler_params=None if interpret else _tpu_params(),
    )(qr, kr, vr, bias, gr, lse, delta)

    def from_rows(a, L, L_p):
        return a.reshape(B, H, L_p, a.shape[-1]).transpose(0, 2, 1, 3)[:, :L]

    return (from_rows(dq, Lq, Lq_p), from_rows(dk, Lk, Lk_p),
            from_rows(dv, Lk, Lk_p))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, kv_mask, causal, block_q, block_k, interpret):
    return _flash_impl(q, k, v, kv_mask, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, kv_mask, causal, block_q, block_k, interpret):
    out, lse = _flash_impl(q, k, v, kv_mask, causal, block_q, block_k,
                           interpret, return_lse=True)
    return out, (q, k, v, kv_mask, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    # Blockwise Pallas backward (FlashAttention-2): probabilities are
    # recomputed tile-by-tile from the saved logsumexp — exact gradients,
    # no (L, L) matrix in either direction.
    q, k, v, kv_mask, out, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, kv_mask, out, lse, g, causal,
                                 block_q, block_k, interpret)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: Optional[jax.Array] = None,
    *,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Blockwise (flash) attention over ``(B, L, H, D)`` tensors.

    ``kv_mask``: optional ``(B, L_k)`` bool, False = padding key.  Fully
    masked query rows return 0, matching ``dense_attention``.
    ``interpret=None`` auto-selects Pallas interpret mode off-TPU.
    ``block_q``/``block_k`` default per TPU generation (512 on v4+, 128 on
    v2/v3 whose smaller VMEM rejects the large configuration).
    """
    return _flash(q, k, v, kv_mask, causal, block_q, block_k, interpret)
