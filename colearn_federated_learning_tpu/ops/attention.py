"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online-softmax accumulation: each grid step owns
one (batch·head, q-block) tile, keeps K/V VMEM-resident, and loops over
k-blocks with running (max, normaliser, accumulator) carries — the (L, L)
score matrix never exists in HBM, and the two matmuls per block land on the
MXU.  The same rescaling recurrence runs ACROSS devices in
parallel/ring.py; composing the two (ring outside, flash inside each block)
is the standard long-context stack.

Gradients: ``flash_attention`` carries a ``jax.custom_vjp`` whose backward
pass recomputes attention densely from the (q, k, v, mask) residuals —
exact gradients, forward-pass memory savings.  (A fused backward kernel is
a future optimisation, not a correctness gap.)

On CPU (the virtual-mesh test platform) the kernel runs in Pallas interpret
mode automatically.

The reference has no kernel layer at all (SURVEY.md §1: "no custom kernel
layer"); this is TPU-native capability the rebuild adds for the BERT/ViT
federated configs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG = -1e30


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _flash_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *,
                  block_k: int, scale: float, causal: bool, block_q: int):
    """One (batch·head, q-block) tile; K/V for the whole row are VMEM-resident.

    q_ref: (1, block_q, D) — this tile's queries
    k_ref, v_ref: (1, Lk, D) — all keys/values for this batch·head
    bias_ref: (1, 1, Lk) — additive key bias (0 valid / _NEG masked); rank 3
      so its block's trailing dims satisfy the TPU (8, 128) tiling rule
    o_ref: (1, block_q, D)
    """
    Lk = k_ref.shape[1]
    D = q_ref.shape[2]
    num_kb = Lk // block_k
    qb = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale                 # (bq, D)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq, bk)
        s = s + bias_ref[0, 0, pl.ds(kb * block_k, block_k)][None, :]
        if causal:
            q_pos = qb * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # Fully-masked blocks: m_new sits at the _NEG floor and exp(0)=1
        # would leak padding; zero those entries (same fix as ring.py).
        p = jnp.where(s > 0.5 * _NEG, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        acc_new = acc * corr + lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # Skip k-blocks entirely above the diagonal.
        num_kb = jnp.minimum(num_kb, pl.cdiv((qb + 1) * block_q, block_k))
    m0 = jnp.full((q.shape[0], 1), _NEG, jnp.float32)
    l0 = jnp.zeros((q.shape[0], 1), jnp.float32)
    acc0 = jnp.zeros((q.shape[0], D), jnp.float32)
    m, l, acc = lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def _flash_impl(q, k, v, kv_mask, causal: bool,
                block_q: int, block_k: int, interpret: Optional[bool]):
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bq = min(block_q, _round_up(Lq, 8))
    bk = min(block_k, _round_up(Lk, 8))
    Lq_p, Lk_p = _round_up(Lq, bq), _round_up(Lk, bk)

    # (B, L, H, D) -> (B*H, L, D) rows; pad sequence to block multiples.
    def to_rows(a, L_p):
        a = jnp.pad(a, ((0, 0), (0, L_p - a.shape[1]), (0, 0), (0, 0)))
        return a.transpose(0, 2, 1, 3).reshape(B * H, L_p, a.shape[-1])

    qr, kr, vr = to_rows(q, Lq_p), to_rows(k, Lk_p), to_rows(v, Lk_p)
    if kv_mask is None:
        bias = jnp.zeros((B, Lk), jnp.float32)
    else:
        bias = jnp.where(kv_mask, 0.0, _NEG).astype(jnp.float32)
    bias = jnp.pad(bias, ((0, 0), (0, Lk_p - Lk)), constant_values=_NEG)
    bias = bias[:, None, :]                                   # (B, 1, Lk_p)

    kernel = functools.partial(
        _flash_kernel, block_k=bk, scale=1.0 / (D ** 0.5),
        causal=causal, block_q=bq,
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Lq_p // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, Lk_p), lambda b, i: (b // H, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq_p, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, bias)
    return out.reshape(B, H, Lq_p, D).transpose(0, 2, 1, 3)[:, :Lq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, kv_mask, causal, block_q, block_k, interpret):
    return _flash_impl(q, k, v, kv_mask, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, kv_mask, causal, block_q, block_k, interpret):
    out = _flash_impl(q, k, v, kv_mask, causal, block_q, block_k, interpret)
    return out, (q, k, v, kv_mask)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    # Dense recompute from residuals: exact gradients, no stored (L, L)
    # forward activations.
    from colearn_federated_learning_tpu.parallel.ring import dense_attention

    q, k, v, kv_mask = res
    _, vjp = jax.vjp(
        lambda q, k, v: dense_attention(q, k, v, kv_mask, causal=causal),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: Optional[jax.Array] = None,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Blockwise (flash) attention over ``(B, L, H, D)`` tensors.

    ``kv_mask``: optional ``(B, L_k)`` bool, False = padding key.  Fully
    masked query rows return 0, matching ``dense_attention``.
    ``interpret=None`` auto-selects Pallas interpret mode off-TPU.
    """
    return _flash(q, k, v, kv_mask, causal, block_q, block_k, interpret)
