"""`colearn` command line: train / aggregate / eval / init / configs / bench.

Parity surface (BASELINE.json north_star): the reference exposes
``colearn train`` and ``colearn aggregate`` entrypoints and argparse flags
for rounds/epochs/lr/client count (SURVEY.md §2 "Config/scripts"); both
accept ``--backend=tpu|cpu|auto`` here.

Two federation modes:
- ``train`` (default role ``sim``): the TPU-native simulation — every client
  trains on-device in one jit program (fed/engine.py).
- ``train --role client`` + ``aggregate``: cross-silo over files — each silo
  produces an update file against a global-model file; the aggregator folds
  them with the configured server strategy (fed/offline.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

from colearn_federated_learning_tpu.utils.config import (
    CONFIGS,
    ExperimentConfig,
    get_config,
)


def _add_override_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", default="mnist_mlp_fedavg",
                   help=f"experiment config; one of {sorted(CONFIGS)}")
    p.add_argument("--backend", choices=["auto", "tpu", "cpu"], default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--tp-size", type=int, default=None,
                   help="model-axis size: shard the global model over a "
                        "tensor-parallel mesh (engine) and the server "
                        "plane over a (model,) mesh (coordinator; "
                        "parallel/partition.py)")
    p.add_argument("--rounds", type=int, default=None)
    p.add_argument("--num-clients", type=int, default=None)
    p.add_argument("--cohort-size", type=int, default=None)
    p.add_argument("--local-epochs", type=int, default=None)
    p.add_argument("--local-steps", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr-schedule", default=None,
                   choices=["constant", "cosine", "warmup_cosine"],
                   help="client-lr schedule across rounds "
                        "(fed/strategies.lr_scale_for_round)")
    p.add_argument("--warmup-rounds", type=int, default=None)
    p.add_argument("--lr-min-fraction", type=float, default=None,
                   help="cosine floor as a fraction of --lr")
    p.add_argument("--momentum", type=float, default=None)
    p.add_argument("--local-optimizer", default=None,
                   choices=["sgd", "adam", "adamw"])
    p.add_argument("--strategy", default=None,
                   choices=["fedavg", "fedprox", "fedadam", "fedyogi",
                            "scaffold", "fednova"])
    p.add_argument("--prox-mu", type=float, default=None)
    p.add_argument("--aggregator", default=None,
                   choices=["mean", "median", "trimmed_mean", "krum"],
                   help="Byzantine-robust server aggregation (fed/robust.py)")
    p.add_argument("--trim-fraction", type=float, default=None)
    p.add_argument("--edge-groups", type=int, default=None,
                   help=">= 2 turns on hierarchical edge->cloud federation "
                        "(fed/hierarchical.py)")
    p.add_argument("--edge-sync-period", type=int, default=None)
    p.add_argument("--dataset", default=None)
    p.add_argument("--partition", default=None,
                   choices=["iid", "dirichlet", "pathological"])
    p.add_argument("--dirichlet-alpha", type=float, default=None)
    p.add_argument("--dp-clip", type=float, default=None)
    p.add_argument("--dp-noise-multiplier", type=float, default=None)
    p.add_argument("--dp-delta", type=float, default=None,
                   help="δ at which the RDP accountant reports ε")
    p.add_argument("--dp-adaptive-clip", action="store_true", default=None,
                   help="track the --dp-target-quantile of update norms "
                        "(--dp-clip becomes the initial norm)")
    p.add_argument("--dp-target-quantile", type=float, default=None)
    p.add_argument("--dp-clip-lr", type=float, default=None)
    p.add_argument("--dp-bit-noise", type=float, default=None,
                   help="σ_b on the quantile-bit sum (0 = cohort/20)")
    p.add_argument("--secure-agg", action="store_true", default=None)
    p.add_argument("--secure-agg-neighbors", type=int, default=None,
                   help="k-regular random-ring masking (0 = all pairs)")
    p.add_argument("--compress", default=None,
                   choices=["none", "int8", "topk", "topk8"],
                   help="update compression on the wire/file planes "
                        "(topk8: int8 values inside the topk frame)")
    p.add_argument("--compress-feedback", action="store_true", default=None,
                   help="carry the uplink compression residual into the "
                        "next round's delta (error feedback; rejected "
                        "under secure_agg)")
    p.add_argument("--topk-fraction", type=float, default=None,
                   help="topk keep density (fraction of entries per leaf)")
    p.add_argument("--topk-adaptive", action="store_true", default=None,
                   help="steer each worker's topk density off its "
                        "error-feedback residual norm, clipped to "
                        "[--topk-min-fraction, --topk-max-fraction] "
                        "(needs --compress topk + feedback)")
    p.add_argument("--topk-min-fraction", type=float, default=None)
    p.add_argument("--topk-max-fraction", type=float, default=None)
    p.add_argument("--lora-rank", type=int, default=None,
                   help="rank-r LoRA adapter federation (fed/lora.py): "
                        "clients train and ship rank-r factors instead "
                        "of dense deltas (0 = off)")
    p.add_argument("--lora-alpha", type=float, default=None,
                   help="LoRA scaling numerator: merged delta is "
                        "B·A·(alpha/rank)")
    p.add_argument("--lora-merge-every", type=int, default=None,
                   help="server merges aggregated factors into the "
                        "global model every N aggregations")
    p.add_argument("--num-aggregators", type=int, default=None,
                   help="aggregator-tree fan-in: N `colearn aggregator` "
                        "processes each fold one cohort slice and ship "
                        "one partial sum to the coordinator "
                        "(comm/aggregator.py; 0 = flat)")
    p.add_argument("--agg-heartbeat-timeout", type=float, default=None,
                   help="treat an aggregator as dead when its retained "
                        "heartbeat is older than this many seconds")
    p.add_argument("--agg-buffer-interval", type=float, default=None,
                   dest="agg_buffer_interval_s",
                   help="tree-async fold cadence: each aggregator's "
                        "per-slice buffer targets one partial ship per "
                        "this many seconds (buffer depth auto-sizes from "
                        "the slice's measured arrival rate)")
    p.add_argument("--fold-device", action="store_true", default=None,
                   help="device-resident fold (ops/fold_kernel.py): "
                        "server folds run through the fused batched "
                        "kernel — in-kernel topk8 dequant + weighting + "
                        "scatter-add, one compile per model — instead "
                        "of the per-update host-numpy scatter; bitwise "
                        "identical to the host fold")
    p.add_argument("--compress-down", default=None,
                   choices=["none", "int8", "topk"],
                   help="DOWNLINK broadcast compression (synchronous "
                        "coordinator): ship the server delta against a "
                        "worker-side param cache (comm/downlink.py)")
    p.add_argument("--straggler-prob", type=float, default=None)
    p.add_argument("--eval-every", type=int, default=None)
    p.add_argument("--log-every", type=int, default=None)
    p.add_argument("--log-file", default=None)
    p.add_argument("--tensorboard-dir", default=None,
                   help="mirror scalar round metrics to TensorBoard")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=None)
    p.add_argument("--ckpt-stream", action="store_true", default=None,
                   help="shard-native streaming checkpoints "
                        "(ckpt/streaming.py): per-shard CRC-checked "
                        "files + a manifest commit marker fsynced last; "
                        "--resume re-shards onto the current mesh "
                        "without assembling the full tree")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of rounds 1-2 here")
    p.add_argument("--trace-dir", default=None,
                   help="write a Chrome-trace JSON of per-round phase spans "
                        "here (open in Perfetto / chrome://tracing, or use "
                        "`colearn trace-summary`)")
    p.add_argument("--trace-rounds", type=int, default=None,
                   help="span-trace only the first N rounds (0 = all)")
    p.add_argument("--attn-impl", default=None,
                   choices=["dense", "flash", "ring", "ulysses"],
                   help="attention core (models/attention.py)")
    p.add_argument("--width", type=int, default=None,
                   help="model width override (CNN channels / embed dim)")
    p.add_argument("--stem", default=None,
                   choices=["conv", "space_to_depth"],
                   help="CNN stem MFU lever (models/cnn.py)")
    p.add_argument("--norm", default=None, choices=["group", "none"],
                   help="CNN normalization (group | none)")
    p.add_argument("--remat", action="store_true", default=None,
                   help="rematerialize transformer blocks (jax.checkpoint): "
                        "activation HBM ~depth -> ~1 block")
    p.add_argument("--min-cohort-fraction", type=float, default=None,
                   help="aggregation quorum: a round whose completed "
                        "fraction of the cohort falls below this is an "
                        "explicit no-op (0 disables)")
    p.add_argument("--evict-after", type=int, default=None,
                   help="evict a device after N consecutive failed rounds "
                        "(>= 1)")
    p.add_argument("--comm-retries", type=int, default=None,
                   help="transport retries per request on transient "
                        "failures, budgeted against the round deadline "
                        "(0 disables)")
    p.add_argument("--comm-backoff-base", type=float, default=None,
                   help="retry backoff base seconds (exponential + full "
                        "jitter)")
    p.add_argument("--comm-backoff-max", type=float, default=None,
                   help="retry backoff cap seconds")
    p.add_argument("--worker-enroll-timeout", type=float, default=None,
                   help="worker-side role-assignment window in seconds; "
                        "expiry raises EnrollmentTimeout instead of "
                        "hanging")
    p.add_argument("--health-dir", default=None,
                   help="per-device health ledger directory "
                        "(telemetry/health.py): coordinator/aggregator/"
                        "fleetsim durably record deadline misses, "
                        "retries, latency sketches per device "
                        "(`colearn health` reads it)")
    p.add_argument("--learn-observe", action="store_true", default=None,
                   help="convergence observatory "
                        "(telemetry/convergence.py): stamp conv_* "
                        "learning-health keys (update norm, cosine to "
                        "the previous update, EWMA trend) on round "
                        "records and export learn.* metrics; `colearn "
                        "converge` renders the report")
    p.add_argument("--fault-plan", default=None,
                   help="JSON fault-plan file (faults/plan.py) installed "
                        "on this process's transport — deterministic "
                        "chaos testing")
    p.add_argument("--fault-seed", type=int, default=None,
                   help="override the fault plan's seed")


def _add_observability_flags(p: argparse.ArgumentParser) -> None:
    """Opt-in runtime observability plane for long-running processes
    (worker/coordinate): crash flight recorder, Prometheus endpoint,
    JSONL event stream.  All off by default — zero threads, zero files."""
    p.add_argument("--flight-dir", default=None,
                   help="crash flight recorder: heartbeat-rewrite a "
                        "bounded black box (flight_<pid>.json) here; "
                        "survives SIGKILL up to one heartbeat of "
                        "staleness (`colearn postmortem` reads these)")
    p.add_argument("--flight-heartbeat", type=float, default=5.0,
                   help="flight-recorder rewrite period in seconds")
    p.add_argument("--flight-watchdog", type=float, default=None,
                   help="declare a stall (and dump) after this many "
                        "seconds without round progress")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve /metrics (Prometheus text) and "
                        "/snapshot.json on 127.0.0.1:<port>; 0 binds an "
                        "ephemeral port announced as a metrics_port "
                        "event on stderr")
    p.add_argument("--events-file", default=None,
                   help="append lifecycle + round events as JSONL here "
                        "(push half of the export plane)")


def _setup_observability(args: argparse.Namespace, role: str,
                         tracers: tuple = ()) -> tuple:
    """Install whichever observability features the flags opted into.
    Returns ``(exporter, events, recorder)`` — each None when off."""
    from colearn_federated_learning_tpu import telemetry

    recorder = exporter = events = None
    if args.flight_dir:
        recorder = telemetry.install_flight_recorder(
            args.flight_dir, role=role,
            heartbeat_s=args.flight_heartbeat,
            watchdog_s=args.flight_watchdog)
        for tr in tracers:
            recorder.attach_tracer(tr)
    if args.metrics_port is not None:
        exporter = telemetry.MetricsExporter(port=args.metrics_port).start()
        print(json.dumps({"event": "metrics_port", "port": exporter.port}),
              file=sys.stderr)
    if args.events_file:
        events = telemetry.EventLog(args.events_file)
        events.emit("start", role=role)
    return exporter, events, recorder


def _obs_round_hook(events, recorder):
    """Per-round-record side channel: event-stream line + flight-ring
    entry + watchdog progress mark.  Cheap no-op when both are off."""
    def hook(rec: dict) -> None:
        if events is not None:
            events.emit("round", **{
                k: v for k, v in rec.items()
                if isinstance(v, (int, float, str, bool))})
        if recorder is not None:
            recorder.record("round", round=rec.get("round"))
            recorder.mark_progress()
    return hook


_FED_KEYS = {"rounds", "cohort_size", "local_epochs", "local_steps",
             "batch_size", "lr", "lr_schedule", "warmup_rounds",
             "lr_min_fraction", "momentum", "local_optimizer", "strategy",
             "prox_mu", "dp_clip", "dp_noise_multiplier", "dp_delta",
             "dp_adaptive_clip", "dp_target_quantile", "dp_clip_lr",
             "dp_bit_noise", "secure_agg", "secure_agg_neighbors",
             "straggler_prob", "compress", "compress_down", "aggregator",
             "compress_feedback", "topk_fraction", "topk_adaptive",
             "topk_min_fraction", "topk_max_fraction",
             "lora_rank", "lora_alpha", "lora_merge_every",
             "trim_fraction", "edge_groups", "edge_sync_period",
             "min_cohort_fraction"}
_DATA_KEYS = {"num_clients", "dataset", "partition", "dirichlet_alpha"}
_MODEL_KEYS = {"attn_impl", "remat", "stem", "norm", "width"}
_RUN_KEYS = {"backend", "seed", "tp_size", "eval_every", "log_every",
             "checkpoint_dir",
             "checkpoint_every", "profile_dir", "trace_dir", "trace_rounds",
             "evict_after", "worker_enroll_timeout", "comm_retries",
             "comm_backoff_base", "comm_backoff_max", "fault_plan",
             "fault_seed", "num_aggregators", "agg_heartbeat_timeout",
             "agg_buffer_interval_s", "health_dir", "learn_observe",
             "fold_device", "ckpt_stream"}


def config_from_args(args: argparse.Namespace) -> ExperimentConfig:
    """Resolve the experiment config and — BEFORE jax initializes a
    backend — honor ``--backend=cpu`` (env vars alone don't override a
    platform pinned by the host's sitecustomize)."""
    if getattr(args, "backend", None) == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass                      # backend already initialized
    cfg = get_config(args.config)
    sections = {"fed": {}, "data": {}, "model": {}, "run": {}}
    for key, val in vars(args).items():
        if val is None:
            continue
        if key in _FED_KEYS:
            sections["fed"][key] = val
        elif key in _DATA_KEYS:
            sections["data"][key] = val
        elif key in _MODEL_KEYS:
            sections["model"][key] = val
        elif key in _RUN_KEYS:
            sections["run"][key] = val
    return cfg.replace(
        fed=dataclasses.replace(cfg.fed, **sections["fed"]),
        data=dataclasses.replace(cfg.data, **sections["data"]),
        model=dataclasses.replace(cfg.model, **sections["model"]),
        run=dataclasses.replace(cfg.run, **sections["run"]),
    )


def cmd_train(args: argparse.Namespace) -> int:
    config = config_from_args(args)

    if args.role == "client":
        from colearn_federated_learning_tpu.fed import offline

        if args.client_id is None or not args.global_model or not args.out:
            print("train --role client requires --client-id, --global-model, "
                  "--out", file=sys.stderr)
            return 2
        stats = offline.client_update(config, args.client_id,
                                      args.global_model, args.out,
                                      residual_path=args.residual_path)
        print(json.dumps(stats))
        return 0

    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.metrics import MetricsLogger

    if config.fed.edge_groups >= 2:
        from colearn_federated_learning_tpu.fed.hierarchical import (
            HierarchicalLearner,
        )

        unsupported = [
            flag for flag, on in [
                ("--resume", args.resume),
                ("--per-client-eval", args.per_client_eval),
                ("--detection-eval", args.detection_eval),
                ("--personalize-steps", bool(args.personalize_steps)),
                ("--checkpoint-dir", bool(config.run.checkpoint_dir)),
                ("--profile-dir", bool(config.run.profile_dir)),
                ("--trace-dir", bool(config.run.trace_dir)),
            ] if on
        ]
        if unsupported:
            print(f"--edge-groups does not support {', '.join(unsupported)}",
                  file=sys.stderr)
            return 2
        learner = HierarchicalLearner(
            config, num_groups=config.fed.edge_groups,
            sync_period=config.fed.edge_sync_period,
        )
        with MetricsLogger(path=args.log_file, name=config.run.name,
                           tensorboard_dir=args.tensorboard_dir) as logger:
            learner.fit(log_fn=lambda rec: (
                logger.log(rec), print(json.dumps(rec), file=sys.stderr)
            ))
            loss, acc = learner.evaluate()
            print(json.dumps({"name": config.run.name,
                              "rounds": len(learner.history),
                              "edge_groups": config.fed.edge_groups,
                              "final_loss": loss, "final_acc": acc,
                              "data_source": learner.dataset.source}))
        return 0

    learner = FederatedLearner.from_config(config)
    with MetricsLogger(path=args.log_file, name=config.run.name,
                       tensorboard_dir=args.tensorboard_dir) as logger:
        if args.resume:
            step = learner.restore_checkpoint()
            print(f"resumed at round {step}", file=sys.stderr)

        def log_fn(rec):
            logger.log(rec)
            print(json.dumps(rec), file=sys.stderr)

        learner.fit(log_fn=log_fn)
        def dump_report(rep):
            from colearn_federated_learning_tpu.fed.evaluation import (
                sanitize_report,
            )

            print(json.dumps(sanitize_report(rep)), file=sys.stderr)

        if args.per_client_eval:
            dump_report(learner.evaluate_per_client())
        if args.personalize_steps:
            dump_report(
                learner.evaluate_personalized(steps=args.personalize_steps))
        if args.detection_eval:
            dump_report(learner.evaluate_detection())
        samples = (learner.cohort_size * learner.num_steps
                   * config.fed.batch_size)
        n_chips = learner.mesh.devices.size if learner.mesh is not None else 1
        summary = logger.summary(samples_per_round=samples, n_chips=n_chips)
        # Which registry branch fed the run — so a user who staged real
        # data under $COLEARN_DATA_DIR can confirm it was actually used.
        summary["data_source"] = learner.dataset.source
        if learner.last_trace_path:
            summary["trace_file"] = learner.last_trace_path
        print(json.dumps(summary))
    return 0


def cmd_init(args: argparse.Namespace) -> int:
    from colearn_federated_learning_tpu.fed import offline

    config = config_from_args(args)
    offline.init_global_model(config, args.out)
    print(json.dumps({"out": args.out, "round": 0}))
    return 0


def cmd_aggregate(args: argparse.Namespace) -> int:
    from colearn_federated_learning_tpu.fed import offline

    config = config_from_args(args)
    stats = offline.aggregate_updates(config, args.global_model, args.updates,
                                      args.out)
    print(json.dumps(stats))
    return 0


def cmd_eval(args: argparse.Namespace) -> int:
    from colearn_federated_learning_tpu.fed import offline

    config = config_from_args(args)
    print(json.dumps(offline.evaluate_global(
        config, args.global_model, detection=args.detection_eval)))
    return 0


def cmd_broker(args: argparse.Namespace) -> int:
    import threading

    from colearn_federated_learning_tpu.comm.broker import MessageBroker

    exporter, events, recorder = _setup_observability(args, role="broker")
    broker = MessageBroker(host=args.host, port=args.port).start()
    print(json.dumps({"host": broker.host, "port": broker.port}), flush=True)
    if recorder is not None:
        recorder.record("broker_listening", port=broker.port)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        broker.stop()
        if events is not None:
            events.emit("stop", role="broker")
        if exporter is not None:
            exporter.close()
    return 0


def _install_fault_plan(config: ExperimentConfig) -> None:
    """Install ``--fault-plan`` on this process's transport (chaos
    testing).  A no-op without the flag — the transport then pays a
    single pointer check per message."""
    if not config.run.fault_plan:
        return
    from colearn_federated_learning_tpu import faults

    plan = faults.FaultPlan.load(config.run.fault_plan,
                                 seed=config.run.fault_seed or None)
    faults.install(plan)
    print(f"fault plan installed: {len(plan.faults)} spec(s), "
          f"seed {plan.seed}", file=sys.stderr)


def cmd_worker(args: argparse.Namespace) -> int:
    from colearn_federated_learning_tpu.comm.worker import run_worker_forever

    config = config_from_args(args)
    if args.client_id is None:
        print("worker requires --client-id", file=sys.stderr)
        return 2
    _install_fault_plan(config)
    _setup_observability(args, role=f"worker{args.client_id}")
    mud = None
    if args.mud_profile:
        with open(args.mud_profile) as f:
            mud = f.read()
    run_worker_forever(config, args.client_id, args.broker_host,
                       args.broker_port, mud_profile=mud)
    return 0


def cmd_aggregator(args: argparse.Namespace) -> int:
    from colearn_federated_learning_tpu.comm.aggregator import (
        run_aggregator_forever,
    )

    config = config_from_args(args)
    if args.agg_id is None:
        print("aggregator requires --agg-id", file=sys.stderr)
        return 2
    _install_fault_plan(config)
    _setup_observability(args, role=f"aggregator{args.agg_id}")
    run_aggregator_forever(config, args.agg_id, args.broker_host,
                           args.broker_port, heartbeat_s=args.heartbeat)
    return 0


def _write_coordinator_trace(config, coord) -> None:
    """Flush the coordinator's span buffer (round phases + adopted worker
    spans) to a Chrome-trace JSON when --trace-dir is set."""
    if not config.run.trace_dir:
        return
    from colearn_federated_learning_tpu import telemetry

    path = telemetry.write_tracer(
        config.run.trace_dir, config.run.name, coord.tracer,
        metrics=telemetry.get_registry().snapshot(),
    )
    print(f"trace written to {path}", file=sys.stderr)


def _coordinator_resume(coord) -> None:
    """Tolerant ``--resume``: restore the latest checkpoint if one exists,
    else start cold (a coordinator killed before its FIRST checkpoint has
    nothing to restore — that must not crash the recovery supervisor).
    Emits a machine-readable event line either way; the mp chaos harness
    (faults/procsoak.py) keys its resume ledger on it."""
    from colearn_federated_learning_tpu import telemetry

    try:
        step = coord.restore_checkpoint()
    except FileNotFoundError:
        print(json.dumps({"event": "resume_cold"}), file=sys.stderr)
        return
    reg = telemetry.get_registry()
    event = {
        "event": "resumed", "round": step,
        "rounds_resumed_total": reg.counter(
            "fed.rounds_resumed_total").value,
    }
    ckpt = getattr(coord, "_ckpt", None)
    digest = getattr(ckpt, "last_restore_digest", None)
    if digest is not None:
        # Streaming restore: the digest is over the full-leaf host bytes
        # in flatten order, so it is tp-layout-independent — the chaos
        # harness compares it against load_generation_host's digest of
        # the generation it expects to survive the kill.
        event["ckpt_digest"] = digest
        event["ckpt_discarded"] = sum(
            getattr(ckpt, "generations_discarded", {}).values())
        event["resharded"] = reg.counter(
            "ckpt.resharded_resumes_total").value
    print(json.dumps(event), file=sys.stderr)


def _async_buffer_arg(value: str):
    """``--async-buffer``: 0 (off), a positive int K, or ``auto`` —
    adaptive K sized from the observed arrival rate
    (telemetry/arrival.py)."""
    if value == "auto":
        return "auto"
    try:
        return int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected an integer or 'auto', got {value!r}") from None


def cmd_coordinate(args: argparse.Namespace) -> int:
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )

    config = config_from_args(args)
    _install_fault_plan(config)
    _exporter, events, recorder = _setup_observability(
        args, role="coordinator")
    obs = _obs_round_hook(events, recorder)
    mud_policy = None
    if args.mud_require_profile or args.mud_allowed_types:
        from colearn_federated_learning_tpu.comm.mud import MudPolicy

        mud_policy = MudPolicy(
            require_profile=args.mud_require_profile,
            allowed_types=tuple(
                t for t in (args.mud_allowed_types or "").split(",") if t
            ),
        )
    if args.per_type:
        from colearn_federated_learning_tpu.comm.per_type import (
            PerTypeFederation,
        )

        fed = PerTypeFederation(
            config, args.broker_host, args.broker_port,
            round_timeout=args.round_timeout, mud_policy=mud_policy,
            min_devices_per_type=args.min_per_type,
        )

        def log_line(t, rec):
            # One atomic write per record: federation threads log
            # concurrently and print()'s separate newline write could
            # interleave lines mid-JSON.
            sys.stderr.write(json.dumps({"type": t, **rec}) + "\n")
            obs({"type": t, **rec})

        try:
            hists = fed.run(
                min_devices=args.min_devices,
                enroll_timeout=args.enroll_timeout,
                want_evaluator=not args.no_evaluator,
                log_fn=log_line,
            )
            print(json.dumps({
                "types": {t: (h[-1] if h else None)
                          for t, h in hists.items()},
                "skipped": fed.skipped,
                "errors": fed.errors,
            }))
        finally:
            fed.close()
        return 0 if hists and not fed.errors else 1
    if args.async_buffer:
        from colearn_federated_learning_tpu.comm.async_coordinator import (
            AsyncFederatedCoordinator,
        )

        coord = AsyncFederatedCoordinator(
            config, args.broker_host, args.broker_port,
            buffer_size=args.async_buffer,
            request_timeout=args.round_timeout,
            want_evaluator=not args.no_evaluator,
            mud_policy=mud_policy,
            prune_after=args.async_prune_after,
            prune_score=args.async_prune_score,
            probation=args.async_probation,
            observe=args.async_observe,
        )
        if recorder is not None:
            recorder.attach_tracer(coord.tracer)
        with coord:
            if args.resume:
                _coordinator_resume(coord)
            coord.enroll(min_devices=args.min_devices,
                         timeout=args.enroll_timeout)
            if coord.tree_mode:
                aggs = coord.enroll_aggregators(timeout=args.enroll_timeout)
                print(json.dumps({"event": "aggregators_enrolled",
                                  "aggregators": aggs}), file=sys.stderr)
            remaining = max(0, config.fed.rounds - len(coord.history))
            hist = coord.fit(
                aggregations=remaining,
                log_fn=lambda rec: (print(json.dumps(rec), file=sys.stderr),
                                    obs(rec))[0],
                elastic=args.elastic,
            )
            _write_coordinator_trace(config, coord)
            print(json.dumps(hist[-1]))
        return 0
    coord = FederatedCoordinator(config, args.broker_host, args.broker_port,
                                 round_timeout=args.round_timeout,
                                 want_evaluator=not args.no_evaluator,
                                 mud_policy=mud_policy)
    if recorder is not None:
        recorder.attach_tracer(coord.tracer)
    with coord:
        if args.resume:
            _coordinator_resume(coord)
        coord.enroll(min_devices=args.min_devices,
                     timeout=args.enroll_timeout)
        if args.resume:
            # Challenge-on-resume: retained announcements alone readmit
            # nobody — only ledger-known devices that answer the nonce
            # challenge keep their seat (comm/coordinator.py).
            verdict = coord.verify_resumed_devices()
            print(json.dumps({"event": "challenge_verified", **verdict}),
                  file=sys.stderr)
        if coord.num_aggregators:
            aggs = coord.enroll_aggregators(timeout=args.enroll_timeout)
            print(json.dumps({"event": "aggregators_enrolled",
                              "aggregators": aggs}), file=sys.stderr)
        hist = coord.fit(log_fn=lambda rec: (print(json.dumps(rec),
                                                   file=sys.stderr),
                                             obs(rec))[0],
                         elastic=args.elastic)
        if args.per_client_eval:
            print(json.dumps(coord.evaluate_per_client()), file=sys.stderr)
        _write_coordinator_trace(config, coord)
        print(json.dumps(hist[-1]))
    return 0


def _lock_witness_ok(summary: dict, args: argparse.Namespace) -> bool:
    """Lock-witness gate for the async/tree-async soaks: with
    ``--lock-witness`` the fleet must have produced per-process reports
    showing real lock traffic and ZERO witnessed ordering inversions or
    unguarded guarded-structure accesses."""
    if not args.lock_witness:
        return True
    lw = summary.get("lock_witness") or {}
    ok = (bool(lw.get("enabled"))
          and int(lw.get("reports", 0)) >= 1
          and int(lw.get("acquires", 0)) >= 1
          and int(lw.get("inversions", 0)) == 0
          and int(lw.get("unguarded", 0)) == 0)
    if not ok:
        print(f"# lock-witness gate failed: "
              f"{json.dumps({k: lw.get(k) for k in ('enabled', 'reports', 'acquires', 'inversions', 'unguarded')})}",
              file=sys.stderr)
        for rec in (lw.get("inversion_records", [])
                    + lw.get("unguarded_records", [])):
            print(f"#   {json.dumps(rec)}", file=sys.stderr)
    return ok


def cmd_chaos(args: argparse.Namespace) -> int:
    """Chaos soak.  Default: broker + workers + coordinator in THIS
    process, a fault plan installed after the warmup round (faults/soak).
    ``--mp``: broker, coordinator and workers as real subprocesses on
    real ports, SIGKILLed on a seeded schedule — including the
    coordinator, which must come back with --resume (faults/procsoak).
    ``--secure``: DH secure-aggregation federation vs a plain-FedAvg
    oracle in lockstep, maskers dropped after-fold/before-unmask; exact
    per-round param agreement is the gate (faults/soak.run_secure_soak).
    ``--ckpt``: streaming-checkpoint crash consistency — SIGKILL lands
    mid-save, --resume restores the last committed generation bitwise
    across a tp=2 -> tp=1 re-shard (faults/procsoak.run_ckpt_soak)."""
    if args.secure and args.mp:
        print("--secure is an in-process exactness gate; drop --mp",
              file=sys.stderr)
        return 2
    if args.agg and (args.secure or args.mp):
        print("--agg is its own multi-process gate; drop --secure/--mp",
              file=sys.stderr)
        return 2
    if args.chaos_async and (args.secure or args.mp or args.agg):
        print("--async is its own multi-process gate; "
              "drop --secure/--mp/--agg", file=sys.stderr)
        return 2
    if args.chaos_tree_async and (args.secure or args.mp or args.agg
                                  or args.chaos_async):
        print("--tree-async is its own multi-process gate; "
              "drop --secure/--mp/--agg/--async", file=sys.stderr)
        return 2
    if args.ckpt and (args.secure or args.mp or args.agg
                      or args.chaos_async or args.chaos_tree_async):
        print("--ckpt is its own multi-process gate; "
              "drop --secure/--mp/--agg/--async/--tree-async",
              file=sys.stderr)
        return 2
    if args.lock_witness and not (args.chaos_async
                                  or args.chaos_tree_async):
        print("--lock-witness instruments the buffered-async fleets; "
              "pair it with --async or --tree-async", file=sys.stderr)
        return 2
    if args.ckpt:
        from colearn_federated_learning_tpu.faults import procsoak

        summary = procsoak.run_ckpt_soak(
            rounds=args.rounds, n_workers=args.num_workers,
            workdir=args.workdir, round_timeout=args.mp_round_timeout,
            timeout_s=args.mp_timeout, kill=not args.no_faults,
            log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr),
        )
        print(json.dumps(summary))
        if summary["mode"] == "smoke":
            # Kill-free bitwise smoke: a tp=2 run's final generation must
            # resume bitwise-identically on tp=1 (digest match across the
            # re-shard, no kill involved).
            ok = (summary["exit_code"] == 0
                  and summary["resume_exit_code"] == 0
                  and summary["rounds_run"] >= args.rounds
                  and summary["resume_round_ok"]
                  and summary["digest_ok"]
                  and summary["reshard_ok"])
        else:
            # SIGKILL-during-save gate: the kill landed mid-save, the
            # resume fell back to the last COMMITTED generation (at most
            # one uncommitted generation lost) and restored it bitwise
            # across the tp=2 -> tp=1 re-shard, the federation finished
            # with loss parity vs the kill-free oracle, and the
            # postmortem attributes the kill.
            ok = (summary["exit_code"] == 0
                  and summary["oracle_exit_code"] == 0
                  and summary["rounds_run"] >= args.rounds
                  and summary["killed_mid_save"]
                  and summary["resumed"] >= 1
                  and summary["resume_round_ok"]
                  and summary["digest_ok"]
                  and summary["reshard_ok"]
                  and summary["loss_gap_ok"]
                  and summary["postmortem_attributed"]
                  and not summary["flight_missing"])
        return 0 if ok else 1
    if args.chaos_tree_async:
        from colearn_federated_learning_tpu.faults import procsoak

        summary = procsoak.run_tree_async_soak(
            aggregations=args.rounds, n_workers=args.num_workers,
            workdir=args.workdir, round_timeout=args.mp_round_timeout,
            timeout_s=args.mp_timeout, kill=not args.no_faults,
            log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr),
            lock_witness=args.lock_witness,
        )
        print(json.dumps(summary))
        ok = (_lock_witness_ok(summary, args)
              and summary["exit_code"] == 0
              and summary["oracle_exit_code"] == 0
              and summary["aggregations_run"] >= args.rounds
              and summary["oracle_aggregations_run"] >= args.rounds
              and summary["version_monotonic"]
              # The tree-async invariants a dead aggregator must not
              # break: a contribution folds exactly once (re-home with
              # ack-on-receipt), the tail loss tracks the kill-free
              # tree oracle, and the health ledgers survive.
              and summary["double_folds"] == 0
              and summary["loss_gap_ok"]
              and summary["health_ledger_ok"]
              # With the kills armed the gate must have EXERCISED the
              # failover: at least one re-home/drop, every re-homed
              # device attributed in the ledger, the dead aggregator
              # named by the postmortem, its flight dump on disk.
              and (args.no_faults
                   or (summary["failover_fired"]
                       and summary["rehomed_attributed"]
                       and summary["postmortem_attributed"]
                       and not summary["flight_missing"])))
        return 0 if ok else 1
    if args.chaos_async:
        from colearn_federated_learning_tpu.faults import procsoak

        summary = procsoak.run_async_soak(
            aggregations=args.rounds, n_workers=args.num_workers,
            workdir=args.workdir, round_timeout=args.mp_round_timeout,
            timeout_s=args.mp_timeout, kill=not args.no_faults,
            log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr),
            lock_witness=args.lock_witness,
        )
        print(json.dumps(summary))
        ok = (_lock_witness_ok(summary, args)
              and summary["exit_code"] == 0
              and summary["baseline_exit_code"] == 0
              and summary["aggregations_run"] >= args.rounds
              and summary["baseline_aggregations_run"] >= args.rounds
              # The three async-plane invariants a lost buffer must not
              # break: per-incarnation version monotonicity, an RDP
              # budget that replays to the recorded epsilon (no
              # double-charge through --resume), and a tail loss within
              # tolerance of the same-seed kill-free baseline.
              and summary["version_monotonic"]
              and summary["dp_replay_ok"]
              and summary["loss_gap_ok"]
              and summary["health_ledger_ok"]
              # With the kill armed the gate must have EXERCISED the
              # recovery: a real resume, a postmortem naming the victim,
              # its flight dump on disk, and the injected pump faults
              # attributed in the health ledger.
              and (args.no_faults
                   or (summary["resumed"] >= 1
                       and summary["postmortem_attributed"]
                       and summary["faults_attributed"]
                       and not summary["flight_missing"])))
        return 0 if ok else 1
    if args.agg:
        from colearn_federated_learning_tpu.faults import procsoak

        summary = procsoak.run_agg_soak(
            rounds=args.rounds, n_workers=args.num_workers,
            workdir=args.workdir, round_timeout=args.mp_round_timeout,
            timeout_s=args.mp_timeout, kill=not args.no_faults,
            log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr),
        )
        print(json.dumps(summary))
        ok = (summary["exit_code"] == 0
              and summary["oracle_exit_code"] == 0
              and summary["rounds_run"] == args.rounds
              and summary["oracle_ok"]
              # A gate that never exercised failover proves nothing:
              # with the kill armed, the tree must have re-homed or
              # quorum-dropped at least one slice, the postmortem must
              # attribute the kill, and the flight dump must exist.
              # The tree run's health ledgers must survive the kill.
              and summary["health_ledger_ok"]
              and (args.no_faults
                   or (summary["agg_failovers"] >= 1
                       and summary["postmortem_attributed"]
                       and not summary["flight_missing"])))
        return 0 if ok else 1
    if args.mp:
        from colearn_federated_learning_tpu.faults import procsoak

        kills = ([] if args.no_faults
                 else procsoak.canned_kill_schedule(args.rounds,
                                                    args.num_workers))
        summary = procsoak.run_proc_soak(
            rounds=args.rounds, n_workers=args.num_workers, kills=kills,
            workdir=args.workdir, round_timeout=args.mp_round_timeout,
            timeout_s=args.mp_timeout,
            log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr),
        )
        for k in summary["kills"]:
            print(f"# killed {k['target']} after round "
                  f"{k['fired_after_round']}", file=sys.stderr)
        print(json.dumps(summary))
        need_resume = any(k.target == "coordinator" for k in kills)
        ok = (summary["exit_code"] == 0
              and summary["rounds_run"] == args.rounds
              and summary["weighted_acc"] is not None
              and (summary["rounds_resumed"] >= 1 or not need_resume)
              # Every SIGKILLed process must have left a parseable
              # flight dump behind (heartbeat survivability).
              and not summary["flight_missing"])
        return 0 if ok else 1
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")   # soak is a CPU tool
    except RuntimeError:
        pass
    from colearn_federated_learning_tpu import faults

    if args.secure:
        from colearn_federated_learning_tpu.faults import soak

        if args.no_faults:
            plan = faults.FaultPlan([], seed=0)
        elif args.fault_plan:
            plan = faults.FaultPlan.load(args.fault_plan,
                                         seed=args.fault_seed or None)
        else:
            plan = soak.canned_secure_plan(
                seed=args.fault_seed if args.fault_seed is not None else 11)
        summary = soak.run_secure_soak(
            rounds=args.rounds, n_workers=args.num_workers, plan=plan,
            round_timeout=args.round_timeout,
            log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr),
        )
        print(json.dumps(summary))
        counters = summary["counters"]
        ok = (summary["rounds_run"] == args.rounds
              and summary["oracle_ok"]
              and not summary["skipped_rounds"]
              and counters["privacy.share_recovery_failures_total"] == 0
              # With faults scheduled, recovery must actually have run —
              # a gate that never exercised unmasking proves nothing.
              and (not plan.faults
                   or counters["privacy.masks_recovered_total"] >= 1))
        return 0 if ok else 1
    if args.no_faults:
        plan = None
    elif args.fault_plan:
        plan = faults.FaultPlan.load(args.fault_plan,
                                     seed=args.fault_seed or None)
    else:
        plan = faults.canned_plan(
            seed=args.fault_seed if args.fault_seed is not None else 7)
    config = None
    if args.compress_down and args.compress_down != "none":
        import dataclasses as _dc

        config = faults.default_soak_config(args.num_workers)
        config = _dc.replace(
            config, fed=_dc.replace(config.fed,
                                    compress_down=args.compress_down))
    summary = faults.run_soak(
        rounds=args.rounds, n_workers=args.num_workers, plan=plan,
        round_timeout=args.round_timeout, config=config,
        log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr),
    )
    for t in summary.get("top_faults", [])[:5]:
        print(f"# top fault {t['label']}: {t['count']}", file=sys.stderr)
    print(json.dumps(summary))
    ok = (summary["rounds_run"] == args.rounds
          and summary["weighted_acc"] is not None)
    return 0 if ok else 1


def cmd_fleetsim(args: argparse.Namespace) -> int:
    """Simulated-fleet training: chunked-vmap rounds over a seeded
    synthetic population with an arrival-process traffic model
    (fleetsim/) — per-round records on stderr, summary JSON on stdout."""
    from colearn_federated_learning_tpu import fleetsim
    from colearn_federated_learning_tpu.utils.config import (
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    spec = fleetsim.PopulationSpec(
        num_devices=args.devices, num_classes=args.classes,
        feature_dim=args.feature_dim, shard_capacity=args.capacity,
        label_skew=args.label_skew, seed=args.seed)
    population = fleetsim.DevicePopulation(spec)
    traffic = fleetsim.TrafficModel(
        fleetsim.TrafficSpec(base_rate=args.base_rate,
                             diurnal_amplitude=args.diurnal,
                             round_minutes=args.round_minutes,
                             seed=args.seed),
        spec.num_devices)
    config = ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=spec.num_classes,
                          hidden_dim=args.hidden_dim, depth=args.depth),
        fed=FedConfig(strategy=args.strategy, local_steps=args.local_steps,
                      batch_size=args.batch_size, lr=args.lr,
                      compress=args.compress,
                      compress_down=args.compress_down or "none",
                      lora_rank=args.lora_rank, lora_alpha=args.lora_alpha),
        run=RunConfig(name="fleetsim", seed=args.seed,
                      learn_observe=bool(args.learn_observe)))
    plan = None
    if args.fault_plan:
        from colearn_federated_learning_tpu import faults

        plan = faults.FaultPlan.load(args.fault_plan,
                                     seed=args.fault_seed or None)
    sim = fleetsim.FleetSim.from_population(
        config, population, traffic, cohort_size=args.cohort,
        chunk_size=args.chunk, fault_plan=plan)
    if args.trace_dir:
        sim.tracer.enabled = True
    if args.async_buffer:
        from colearn_federated_learning_tpu import telemetry

        history = sim.fit_async(
            args.rounds, buffer_size=args.async_buffer,
            max_staleness=args.async_max_staleness,
            prune_after=args.async_prune_after,
            probation=args.async_probation,
            observe=args.async_observe,
            aggregators=args.aggregators,
            log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr))
        last = history[-1]
        # Arrival tracking: what fraction of arrived updates were folded
        # (1 == the fold plane keeps up with the arrival stream; every
        # too-stale discard is tracked work lost).
        arrived = last["arrival_rate_per_min"] * last["sim_time_min"]
        folded = max(0.0, arrived - last["wasted_updates_total"])
        summary = {
            "devices": spec.num_devices,
            "buffer_size": last["buffer_size"],
            "aggregations": len(history),
            "model_version": last["model_version"],
            "sim_minutes": last["sim_time_min"],
            "arrival_rate_per_min": last["arrival_rate_per_min"],
            "agg_rate_per_min": last["agg_rate_per_min"],
            "arrival_tracking": folded / max(arrived, 1e-9),
            "staleness_mean": (
                sum(r["staleness_mean"] for r in history) / len(history)),
            "wasted_updates": last["wasted_updates_total"],
            "train_loss": last["train_loss"],
            "compiles": sim.compile_counts,
        }
        # Staleness tail over every FOLDED update this run (the labeled
        # histogram the observatory keeps) — the distribution, not just
        # the per-aggregation mean.
        hs = telemetry.get_registry().histogram(
            "fleetsim.async_staleness",
            labels={"outcome": "folded"}).summary()
        if hs.get("count"):
            summary["staleness_p50"] = hs["p50"]
            summary["staleness_p90"] = hs["p90"]
            summary["staleness_p99"] = hs["p99"]
        if args.async_buffer == "auto":
            summary["buffer_auto"] = True
        if args.aggregators:
            summary["aggregators"] = args.aggregators
            summary["agg_fold_tracking_min"] = last["agg_fold_tracking_min"]
        if args.async_prune_after:
            summary["pruned"] = last["pruned"]
            summary["pruned_total"] = last["pruned_total"]
        print(json.dumps(summary))
        return 0 if history and last["model_version"] > 0 else 1
    history = sim.fit(
        args.rounds,
        log_fn=lambda rec: print(json.dumps(rec), file=sys.stderr))
    if args.trace_dir:
        from colearn_federated_learning_tpu import telemetry

        path = telemetry.write_tracer(
            args.trace_dir, "fleetsim", sim.tracer,
            metrics=telemetry.get_registry().snapshot())
        print(f"trace written to {path}", file=sys.stderr)
    wall = sum(r["round_time_s"] for r in history) or 1e-9
    clients = sum(r["clients_trained"] for r in history)
    summary = {
        "devices": spec.num_devices,
        "cohort": args.cohort,
        "chunk": sim.chunk_size,
        "rounds": len(history),
        "clients_trained": clients,
        "rounds_per_sec": len(history) / wall,
        "clients_per_sec": clients / wall,
        "bytes_up_per_round": (
            sum(r["bytes_up_est"] for r in history) / len(history)),
        "bytes_down_per_round": (
            sum(r["bytes_down_est"] for r in history) / len(history)),
        "dropped": sum(r["dropped"] for r in history),
        "straggled": sum(r["straggled"] for r in history),
        "corrupted": sum(r["corrupted"] for r in history),
        "train_loss": history[-1]["train_loss"],
        # One entry per jitted executable; "chunk" staying at 1 across a
        # whole sweep is the pad-to-fixed-width invariant, machine-checked.
        "compiles": sim.compile_counts,
    }
    print(json.dumps(summary))
    return 0 if history and clients > 0 else 1


def cmd_lint(args: argparse.Namespace) -> int:
    """Run the AST lint (analysis/) — CPU-only, never initializes jax."""
    import os

    from colearn_federated_learning_tpu.analysis import engine as lint_engine
    from colearn_federated_learning_tpu.analysis import reporters

    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    if args.root:
        root = os.path.abspath(args.root)
    else:
        root = next(
            (c for c in (os.getcwd(), os.path.dirname(pkg_dir))
             if os.path.exists(os.path.join(c, "pyproject.toml"))),
            os.getcwd())
    config = lint_engine.LintConfig.from_pyproject(root)
    if args.rules:
        config.enable = [r.strip() for r in args.rules.split(",")]
    if args.disable:
        config.disable = tuple(
            r.strip() for r in args.disable.split(","))
    try:
        eng = lint_engine.LintEngine(config=config, root=root)
    except ValueError as e:
        print(f"colearn lint: {e}", file=sys.stderr)
        return 2
    paths = args.paths or [pkg_dir]
    baseline_path = (os.path.join(root, args.baseline)
                     if args.baseline else None)
    if args.write_baseline:
        # Lint without the current baseline, then accept everything found.
        result = eng.run(paths, baseline_path="")
        target = baseline_path or os.path.join(root, config.baseline)
        entries = lint_engine.write_baseline(target, result.findings)
        print(f"colearn lint: baselined {len(result.findings)} finding(s) "
              f"({len(entries)} fingerprint(s)) -> {target}")
        return 0
    if args.gate:
        # CI posture: the baseline is a MIGRATION vehicle, not a place
        # findings live.  The gate fails when any fingerprint is still
        # parked there, so every suppression is an inline, reasoned noqa.
        gate_baseline = baseline_path or os.path.join(root, config.baseline)
        entries = lint_engine.load_baseline(gate_baseline)
        if entries:
            print(f"colearn lint --gate: baseline {gate_baseline} still "
                  f"carries {len(entries)} fingerprint(s); fix the "
                  f"findings or move each to an inline "
                  f"`# colearn: noqa(CLxxx): <reason>`", file=sys.stderr)
            return 1
    result = eng.run(paths, baseline_path=baseline_path)
    if args.format == "json":
        print(reporters.render_json(result))
    elif args.format == "sarif":
        print(reporters.render_sarif(result))
    else:
        print(reporters.render_text(result))
    return result.exit_code


def cmd_trace_summary(args: argparse.Namespace) -> int:
    from colearn_federated_learning_tpu import telemetry

    try:
        doc = telemetry.load_trace(args.trace_file)
    except (OSError, ValueError) as e:
        print(f"cannot read trace {args.trace_file}: {e}", file=sys.stderr)
        return 2
    print(telemetry.summarize_trace(doc, root=args.root))
    return 0


def cmd_postmortem(args: argparse.Namespace) -> int:
    """Merge crash flight dumps with the round WAL into one causal report:
    who died, of what, at which round, and which rounds were in flight
    (logged but not yet durable in a checkpoint)."""
    import os

    from colearn_federated_learning_tpu import telemetry

    dumps = telemetry.load_flight_dumps(args.flight_dir)
    wal_entries = None
    if args.wal_dir:
        from colearn_federated_learning_tpu.ckpt.wal import RoundWal

        wal_dir = args.wal_dir
        if os.path.isfile(wal_dir):           # accept the file path too
            wal_dir = os.path.dirname(wal_dir) or "."
        wal_entries = RoundWal(wal_dir).load()
    report = telemetry.postmortem_report(
        dumps, wal_entries=wal_entries,
        checkpoint_step=args.checkpoint_step)
    if args.format == "json":
        print(json.dumps(report))
    else:
        print(telemetry.render_postmortem(report))
    return 0 if dumps else 1


def cmd_top(args: argparse.Namespace) -> int:
    """Terminal dashboard over a live /snapshot.json endpoint: round
    rate, cohort health, fault counters, compile churn, HBM."""
    import time
    import urllib.error
    import urllib.request

    from colearn_federated_learning_tpu.telemetry import runtime

    url = args.url or f"http://127.0.0.1:{args.port}/snapshot.json"
    prev = None
    while True:
        try:
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                snap = json.loads(resp.read().decode("utf-8"))
        except (OSError, urllib.error.URLError, ValueError) as e:
            print(f"colearn top: cannot fetch {url}: {e}", file=sys.stderr)
            return 1
        body = runtime.render_top(
            snap, prev=prev,
            interval_s=args.interval if prev is not None else 0.0)
        if args.once:
            print(body)
            return 0
        # Clear + home instead of curses: works in any terminal and in
        # script(1) captures.
        sys.stdout.write("\x1b[2J\x1b[H" + body + "\n")
        sys.stdout.flush()
        prev = snap
        time.sleep(args.interval)


def cmd_sentinel(args: argparse.Namespace) -> int:
    """Evaluate the [tool.colearn.slo] rules against committed results/
    benchmark JSONL — exit non-zero on any violation (the CI perf gate)."""
    import os

    from colearn_federated_learning_tpu.analysis import sentinel

    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    if args.root:
        root = os.path.abspath(args.root)
    else:
        root = next(
            (c for c in (os.getcwd(), os.path.dirname(pkg_dir))
             if os.path.exists(os.path.join(c, "pyproject.toml"))),
            os.getcwd())
    try:
        verdict = sentinel.evaluate_slo(root)
    except ValueError as e:
        print(f"colearn sentinel: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(verdict))
    else:
        print(sentinel.render_verdict(verdict))
    return 0 if verdict["ok"] else 1


def cmd_health(args: argparse.Namespace) -> int:
    """Render the per-device health ledger a --health-dir run wrote: top
    offenders, straggler latency tail, per-aggregator slice skew."""
    from colearn_federated_learning_tpu import telemetry

    try:
        devices = telemetry.load_health(args.health_dir)
    except (OSError, ValueError) as e:
        print(f"colearn health: cannot read {args.health_dir}: {e}",
              file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps({d: h.to_dict() for d, h in devices.items()}))
    else:
        print(telemetry.render_health(devices, top=args.top))
    return 0 if devices else 1


def cmd_converge(args: argparse.Namespace) -> int:
    """Round-over-round learning report from committed JSONL: any file
    or results dir whose records carry conv_* keys (a --learn-observe
    run, an event stream, a bench log)."""
    import glob
    from colearn_federated_learning_tpu import telemetry

    paths = ([args.results] if os.path.isfile(args.results)
             else sorted(glob.glob(
                 os.path.join(args.results, "**", "*.jsonl"),
                 recursive=True)))
    records: list = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError as e:
            print(f"colearn converge: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
    if not paths:
        print(f"colearn converge: no JSONL under {args.results}",
              file=sys.stderr)
        return 2
    report = telemetry.render_convergence_report(records)
    print(report)
    return 0 if not report.startswith("no learning signals") else 1


def cmd_configs(_args: argparse.Namespace) -> int:
    for name, cfg in sorted(CONFIGS.items()):
        print(f"{name}: {cfg.model.name} on {cfg.data.dataset}, "
              f"{cfg.data.num_clients} clients, {cfg.fed.strategy}")
    return 0


def cmd_bench(args: argparse.Namespace) -> int:
    from colearn_federated_learning_tpu import bench

    argv = ["--rounds", str(args.rounds), "--warmup", str(args.warmup),
            "--baseline-rounds", str(args.baseline_rounds),
            "--probe-timeout", str(args.probe_timeout),
            "--probe-budget", str(args.probe_budget)]
    if args.skip_baseline:
        argv.append("--skip-baseline")
    if args.force_cpu:
        argv.append("--force-cpu")
    bench.main(argv)
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="colearn")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_train = sub.add_parser("train", help="run federated training")
    _add_override_flags(p_train)
    p_train.add_argument("--role", choices=["sim", "client"], default="sim")
    p_train.add_argument("--client-id", type=int, default=None)
    p_train.add_argument("--global-model", default=None,
                         help="global model npz (client role)")
    p_train.add_argument("--out", default=None,
                         help="update npz to write (client role)")
    p_train.add_argument("--residual-path", default=None,
                         help="client role: persist the uplink error-"
                              "feedback compression residual here across "
                              "file-plane rounds (--compress-feedback)")
    p_train.add_argument("--resume", action="store_true")
    p_train.add_argument("--per-client-eval", action="store_true",
                         help="report per-client accuracy spread at the end")
    p_train.add_argument("--personalize-steps", type=int, default=0,
                         help="fine-tune-then-eval personalization probe: "
                              "N local SGD steps per client on half its "
                              "shard, scored on the held-out half")
    p_train.add_argument("--detection-eval", action="store_true",
                         help="detection-oriented held-out report "
                              "(per-class P/R/F1, alarm detection/"
                              "false-alarm rates — the IoT anomaly "
                              "metrics; class 0 = benign)")
    p_train.set_defaults(fn=cmd_train)

    p_init = sub.add_parser("init", help="write an initial global model file")
    _add_override_flags(p_init)
    p_init.add_argument("--out", required=True)
    p_init.set_defaults(fn=cmd_init)

    p_agg = sub.add_parser("aggregate",
                           help="fold client update files into a new global model")
    _add_override_flags(p_agg)
    p_agg.add_argument("--global-model", required=True)
    p_agg.add_argument("--updates", nargs="+", required=True)
    p_agg.add_argument("--out", required=True)
    p_agg.set_defaults(fn=cmd_aggregate)

    p_eval = sub.add_parser("eval", help="evaluate a global model file")
    _add_override_flags(p_eval)
    p_eval.add_argument("--global-model", required=True)
    p_eval.add_argument("--detection-eval", action="store_true",
                        help="add the anomaly-detection report (per-class "
                             "P/R/F1, alarm detection/false-alarm rates)")
    p_eval.set_defaults(fn=cmd_eval)

    sub.add_parser("configs", help="list experiment configs").set_defaults(
        fn=cmd_configs)
    p_broker = sub.add_parser("broker", help="run the pub/sub control-plane "
                                             "broker (MQTT equivalent)")
    p_broker.add_argument("--host", default="127.0.0.1")
    p_broker.add_argument("--port", type=int, default=0)
    _add_observability_flags(p_broker)
    p_broker.set_defaults(fn=cmd_broker)

    p_worker = sub.add_parser("worker", help="run a device worker process "
                                             "(shard + local trainer)")
    _add_override_flags(p_worker)
    p_worker.add_argument("--client-id", type=int, default=None)
    p_worker.add_argument("--broker-host", default="127.0.0.1")
    p_worker.add_argument("--broker-port", type=int, required=True)
    p_worker.add_argument("--mud-profile", default=None,
                          help="path to this device's RFC 8520 MUD JSON, "
                               "announced on enrollment (comm/mud.py)")
    _add_observability_flags(p_worker)
    p_worker.set_defaults(fn=cmd_worker)

    p_aggtier = sub.add_parser(
        "aggregator",
        help="run one aggregator-tree process: folds its cohort slice "
             "and ships one partial sum to the coordinator "
             "(comm/aggregator.py)")
    _add_override_flags(p_aggtier)
    p_aggtier.add_argument("--agg-id", type=int, default=None)
    p_aggtier.add_argument("--broker-host", default="127.0.0.1")
    p_aggtier.add_argument("--broker-port", type=int, required=True)
    p_aggtier.add_argument("--heartbeat", type=float, default=0.5,
                           help="retained-announce heartbeat period (s); "
                                "the coordinator's liveness signal")
    _add_observability_flags(p_aggtier)
    p_aggtier.set_defaults(fn=cmd_aggregator)

    p_coord = sub.add_parser("coordinate",
                             help="run the federated coordinator over "
                                  "enrolled workers")
    _add_override_flags(p_coord)
    p_coord.add_argument("--broker-host", default="127.0.0.1")
    p_coord.add_argument("--broker-port", type=int, required=True)
    p_coord.add_argument("--min-devices", type=int, default=2)
    p_coord.add_argument("--enroll-timeout", type=float, default=60.0)
    p_coord.add_argument("--round-timeout", type=float, default=120.0)
    p_coord.add_argument("--no-evaluator", action="store_true")
    p_coord.add_argument("--elastic", action="store_true",
                         help="admit late-joining workers between rounds")
    p_coord.add_argument("--resume", action="store_true",
                         help="restore the latest checkpoint from "
                              "--checkpoint-dir before training")
    p_coord.add_argument("--per-client-eval", action="store_true",
                         help="report each trainer's own-shard accuracy "
                              "after training (worker self_eval op)")
    p_coord.add_argument("--per-type", action="store_true",
                         help="one federation per MUD device type (the "
                              "CoLearn topology; comm/per_type.py) — "
                              "each type trains its own global model")
    p_coord.add_argument("--min-per-type", type=int, default=2,
                         help="smallest device class that gets its own "
                              "federation under --per-type")
    p_coord.add_argument("--mud-require-profile", action="store_true",
                         help="refuse devices that enroll without an RFC "
                              "8520 MUD profile (comm/mud.py)")
    p_coord.add_argument("--mud-allowed-types", default=None,
                         help="comma-separated device types admitted to "
                              "the federation (MUD colearn:device-type)")
    p_coord.add_argument("--async-buffer", type=_async_buffer_arg,
                         default=0,
                         help="> 0 switches to buffered-asynchronous "
                              "aggregation (FedBuff-style): apply the "
                              "staleness-weighted mean every N updates "
                              "instead of running synchronous rounds; "
                              "'auto' sizes N from the observed arrival "
                              "rate (target fold cadence)")
    p_coord.add_argument("--async-observe", action="store_true",
                         help="stamp observatory keys (contribution "
                              "mass, arrival rate, staleness tail) into "
                              "async aggregation records (implied by "
                              "--async-buffer auto)")
    p_coord.add_argument("--async-prune-after", type=int, default=0,
                         help="pause a device's dispatch pump after this "
                              "many CONSECUTIVE too-stale discards "
                              "(straggler pruning; needs --health-dir)")
    p_coord.add_argument("--async-prune-score", type=float, default=0.0,
                         help="pause pumps whose health-ledger score "
                              "(failure weights + latency-vs-median "
                              "term) reaches this; 0 disables "
                              "(needs --health-dir)")
    p_coord.add_argument("--async-probation", type=int, default=8,
                         help="aggregations a pruned device sits out "
                              "before probation re-admits its pump")
    _add_observability_flags(p_coord)
    p_coord.set_defaults(fn=cmd_coordinate)

    p_chaos = sub.add_parser("chaos",
                             help="run an in-process chaos soak: a tiny "
                                  "federation under an injected fault "
                                  "plan, reporting recovery counters")
    p_chaos.add_argument("--rounds", type=int, default=10)
    p_chaos.add_argument("--num-workers", type=int, default=4)
    p_chaos.add_argument("--round-timeout", type=float, default=6.0,
                         help="per-round deadline for the FAULTED rounds "
                              "(the warmup round gets a generous one)")
    p_chaos.add_argument("--fault-plan", default=None,
                         help="JSON fault-plan file; default is the "
                              "canned acceptance plan (faults/soak.py)")
    p_chaos.add_argument("--fault-seed", type=int, default=None)
    p_chaos.add_argument("--no-faults", action="store_true",
                         help="run the soak without any plan (baseline)")
    p_chaos.add_argument("--compress-down", default=None,
                         choices=["none", "int8", "topk"],
                         help="soak with downlink delta compression on "
                              "(exercises the cache-miss resync path "
                              "under faults)")
    p_chaos.add_argument("--secure", action="store_true",
                         help="secure-aggregation exactness gate: DH "
                              "masked federation vs plain-FedAvg oracle "
                              "in lockstep under the dropout plan; fails "
                              "unless every round's recovered sum matches "
                              "the oracle (faults/soak.run_secure_soak)")
    p_chaos.add_argument("--mp", action="store_true",
                         help="multi-process soak: broker/coordinator/"
                              "workers as real subprocesses, real SIGKILL "
                              "on the canned schedule (coordinator "
                              "included — exercises --resume recovery)")
    p_chaos.add_argument("--agg", action="store_true",
                         help="aggregator-tree failover gate: a real "
                              "2-aggregator federation with one "
                              "aggregator SIGKILLed mid-round, final "
                              "params lockstep vs a flat oracle run "
                              "(faults/procsoak.run_agg_soak)")
    p_chaos.add_argument("--async", dest="chaos_async",
                         action="store_true",
                         help="buffered-async chaos gate: broker/workers/"
                              "async coordinator as real subprocesses, "
                              "SIGKILL mid-aggregation + --resume "
                              "relaunch; gates version monotonicity, "
                              "accountant replay, and final loss vs a "
                              "same-seed kill-free async run "
                              "(faults/procsoak.run_async_soak)")
    p_chaos.add_argument("--tree-async", dest="chaos_tree_async",
                         action="store_true",
                         help="buffered-async THROUGH the aggregator "
                              "tree: 2 per-slice aggregator buffers, "
                              "aggregator 0 SIGKILLed mid-aggregation "
                              "(stays dead — its in-flight slice must "
                              "re-home to the sibling with zero double-"
                              "folds) plus a broker kill-and-rebind; "
                              "tail-loss parity vs a same-seed kill-free "
                              "tree oracle "
                              "(faults/procsoak.run_tree_async_soak)")
    p_chaos.add_argument("--ckpt", action="store_true",
                         help="streaming-checkpoint chaos gate: a tp=2 "
                              "--ckpt-stream federation is SIGKILLed "
                              "mid-save (shard files down, manifest not "
                              "yet committed) and must --resume on tp=1 "
                              "from the last COMMITTED generation, "
                              "bitwise (digest match across the "
                              "re-shard), with loss parity vs a "
                              "kill-free oracle; with --no-faults runs "
                              "the kill-free cross-tp bitwise smoke "
                              "(faults/procsoak.run_ckpt_soak)")
    p_chaos.add_argument("--lock-witness", action="store_true",
                         help="(--async/--tree-async) run every fleet "
                              "process with the runtime lock witness "
                              "(faults/lockwitness) armed and gate on "
                              "zero observed ordering inversions and "
                              "zero unguarded guarded-structure "
                              "accesses")
    p_chaos.add_argument("--workdir", default=None,
                         help="--mp scratch dir for checkpoints + process "
                              "logs (default: a fresh temp dir)")
    p_chaos.add_argument("--mp-round-timeout", type=float, default=120.0,
                         help="--mp per-round deadline (covers the first "
                              "round's jit compile in every worker)")
    p_chaos.add_argument("--mp-timeout", type=float, default=600.0,
                         help="--mp whole-soak wall-clock backstop; a hung "
                              "federation is killed and reported")
    p_chaos.set_defaults(fn=cmd_chaos)

    p_fleet = sub.add_parser("fleetsim",
                             help="simulate a 1k-1M device fleet: chunked "
                                  "vmap rounds over a synthetic population "
                                  "with a traffic model (fleetsim/)")
    p_fleet.add_argument("--devices", type=int, default=10_000)
    p_fleet.add_argument("--cohort", type=int, default=1024)
    p_fleet.add_argument("--rounds", type=int, default=5)
    p_fleet.add_argument("--chunk", type=int, default=1024,
                         help="vmap chunk size: memory is O(chunk), wall "
                              "time is O(cohort/chunk) dispatches")
    p_fleet.add_argument("--seed", type=int, default=0)
    p_fleet.add_argument("--classes", type=int, default=10)
    p_fleet.add_argument("--feature-dim", type=int, default=32)
    p_fleet.add_argument("--capacity", type=int, default=32,
                         help="padded per-device shard size")
    p_fleet.add_argument("--label-skew", type=float, default=0.7,
                         help="P(label == device home class); non-IID knob")
    p_fleet.add_argument("--base-rate", type=float, default=2.0,
                         help="mean device check-ins per hour")
    p_fleet.add_argument("--diurnal", type=float, default=0.8,
                         help="day/night availability swing in [0, 1]")
    p_fleet.add_argument("--round-minutes", type=float, default=10.0)
    p_fleet.add_argument("--strategy", default="fedavg",
                         choices=["fedavg", "fedprox", "fedadam", "fedyogi"])
    p_fleet.add_argument("--local-steps", type=int, default=4)
    p_fleet.add_argument("--batch-size", type=int, default=16)
    p_fleet.add_argument("--lr", type=float, default=0.05)
    p_fleet.add_argument("--hidden-dim", type=int, default=64)
    p_fleet.add_argument("--depth", type=int, default=2)
    p_fleet.add_argument("--compress", default="none",
                         choices=["none", "int8", "topk", "topk8"],
                         help="uplink scheme for the byte estimates")
    p_fleet.add_argument("--lora-rank", type=int, default=0,
                         help="rank-r adapter federation: price the "
                              "factor-frame uplink (bytes_up_saved_est; "
                              "training dynamics stay dense in the sim)")
    p_fleet.add_argument("--lora-alpha", type=float, default=16.0)
    p_fleet.add_argument("--compress-down", default="none",
                         choices=["none", "int8", "topk"])
    p_fleet.add_argument("--fault-plan", default=None,
                         help="JSON fault plan; (device, round, op='train') "
                              "keys drive per-simulated-device drop/"
                              "straggle/corrupt")
    p_fleet.add_argument("--fault-seed", type=int, default=None)
    p_fleet.add_argument("--trace-dir", default=None,
                         help="write the sweep's span trace (fleet_round/"
                              "train_chunks/train_chunk) as a Chrome-trace "
                              "JSON here; read with `colearn trace-summary`")
    p_fleet.add_argument("--async-buffer", type=_async_buffer_arg,
                         default=0,
                         help="> 0 runs the buffered-ASYNC simulation "
                              "instead of sync rounds: fold every N "
                              "arrival-ordered completions with staleness "
                              "weighting (FleetSim.fit_async); --rounds "
                              "then counts aggregations; 'auto' sizes N "
                              "from the observed arrival rate")
    p_fleet.add_argument("--async-observe", action="store_true",
                         help="async mode: stamp observatory keys "
                              "(staleness tail, contribution mass, EWMA "
                              "arrival rate) into records (implied by "
                              "--async-buffer auto)")
    p_fleet.add_argument("--async-max-staleness", type=int, default=10,
                         help="async mode: discard updates staler than "
                              "this many versions (wasted compute)")
    p_fleet.add_argument("--async-prune-after", type=int, default=0,
                         help="async mode: stop re-dispatching a device "
                              "after this many CONSECUTIVE too-stale "
                              "discards (0 = off)")
    p_fleet.add_argument("--aggregators", type=int, default=0,
                         help="async mode: two-tier tree — devices "
                              "sliced by service time across N per-"
                              "slice auto-K buffers, partials folded "
                              "unscaled at the edge and staleness-"
                              "discounted at the root against the "
                              "OLDEST constituent (0 = flat async)")
    p_fleet.add_argument("--async-probation", type=int, default=8,
                         help="async mode: aggregations a pruned device "
                              "sits out before re-admission")
    p_fleet.add_argument("--learn-observe", action="store_true",
                         help="convergence observatory: stamp conv_* "
                              "learning-health keys (update norm / cosine "
                              "/ trend, per-cohort drift skew) on round "
                              "records; `colearn converge` renders them")
    p_fleet.set_defaults(fn=cmd_fleetsim)

    p_lint = sub.add_parser("lint",
                            help="run the AST invariant checks "
                                 "(CL001-CL010; analysis/) — fast, "
                                 "CPU-only, no jax init")
    p_lint.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the installed "
                             "package)")
    p_lint.add_argument("--format", choices=["text", "json", "sarif"],
                        default="text")
    p_lint.add_argument("--gate", action="store_true",
                        help="CI gate: additionally fail when the "
                             "baseline file still carries accepted "
                             "fingerprints — every suppression must be "
                             "an inline reasoned noqa")
    p_lint.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all registered)")
    p_lint.add_argument("--disable", default=None,
                        help="comma-separated rule ids to skip")
    p_lint.add_argument("--baseline", default=None,
                        help="baseline JSON path relative to --root "
                             "(default: [tool.colearn.lint].baseline)")
    p_lint.add_argument("--write-baseline", action="store_true",
                        help="accept every current finding into the "
                             "baseline file and exit 0")
    p_lint.add_argument("--root", default=None,
                        help="repo root holding pyproject.toml + baseline "
                             "(default: cwd, else the package parent)")
    p_lint.set_defaults(fn=cmd_lint)

    p_trace = sub.add_parser("trace-summary",
                             help="print a per-phase time breakdown of a "
                                  "--trace-dir Chrome-trace JSON file")
    p_trace.add_argument("trace_file", help="path to the *_trace.json file")
    p_trace.add_argument("--root", default="round",
                         help="span name used as the per-round denominator")
    p_trace.set_defaults(fn=cmd_trace_summary)

    p_pm = sub.add_parser("postmortem",
                          help="merge crash flight dumps (--flight-dir) "
                               "with the round WAL into a who-died-where "
                               "report")
    p_pm.add_argument("flight_dir",
                      help="directory holding flight_<pid>.json dumps "
                           "(searched recursively)")
    p_pm.add_argument("--wal-dir", default=None,
                      help="checkpoint dir holding round_wal.jsonl (or "
                           "the file itself) to reconcile rounds against")
    p_pm.add_argument("--checkpoint-step", type=int, default=None,
                      help="latest durable checkpoint round; WAL entries "
                           "past it count as in flight, not committed")
    p_pm.add_argument("--format", choices=["text", "json"], default="text")
    p_pm.set_defaults(fn=cmd_postmortem)

    p_top = sub.add_parser("top",
                           help="live terminal view of a --metrics-port "
                                "process: round rate, cohort health, "
                                "faults, compiles, HBM")
    p_top.add_argument("--port", type=int, default=9100,
                       help="metrics port of the process to watch")
    p_top.add_argument("--url", default=None,
                       help="full /snapshot.json URL (overrides --port)")
    p_top.add_argument("--interval", type=float, default=2.0,
                       help="refresh period in seconds")
    p_top.add_argument("--once", action="store_true",
                       help="print one snapshot and exit (no screen clear)")
    p_top.set_defaults(fn=cmd_top)

    p_slo = sub.add_parser("sentinel",
                           help="evaluate [tool.colearn.slo] rules against "
                                "results/*.jsonl; non-zero exit on any "
                                "regression (the CI perf gate)")
    p_slo.add_argument("--root", default=None,
                       help="repo root holding pyproject.toml and the "
                            "rule-referenced result files (default: cwd, "
                            "else the package parent)")
    p_slo.add_argument("--format", choices=["text", "json"], default="text")
    p_slo.set_defaults(fn=cmd_sentinel)

    p_health = sub.add_parser("health",
                              help="per-device fleet health from a "
                                   "--health-dir run: top offenders, "
                                   "straggler tail, per-aggregator skew")
    p_health.add_argument("health_dir",
                          help="directory holding health_*.jsonl ledgers "
                               "(searched recursively)")
    p_health.add_argument("--top", type=int, default=10,
                          help="offender rows to show")
    p_health.add_argument("--format", choices=["text", "json"],
                          default="text")
    p_health.set_defaults(fn=cmd_health)

    p_conv = sub.add_parser("converge",
                            help="round-over-round learning report from "
                                 "a --learn-observe run's JSONL (update "
                                 "norm / cosine / trend per round)")
    p_conv.add_argument("results",
                        help="JSONL file, or directory searched "
                             "recursively for *.jsonl")
    p_conv.set_defaults(fn=cmd_converge)

    p_bench = sub.add_parser("bench", help="run the headline benchmark")
    p_bench.add_argument("--rounds", type=int, default=20)
    p_bench.add_argument("--warmup", type=int, default=2)
    p_bench.add_argument("--baseline-rounds", type=int, default=1)
    p_bench.add_argument("--skip-baseline", action="store_true")
    p_bench.add_argument("--probe-timeout", type=float, default=90.0)
    p_bench.add_argument("--probe-budget", type=float, default=210.0)
    p_bench.add_argument("--force-cpu", action="store_true")
    p_bench.set_defaults(fn=cmd_bench)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
