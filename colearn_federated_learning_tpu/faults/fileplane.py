"""File/hierarchical-plane fault hooks for an installed FaultPlan.

The comm plane injects faults through a transport interposer; the file
and hierarchical planes have no transport, so their exchange points call
these hooks directly.  Each hook is a zero-cost no-op when no plan is
installed (:func:`~.inject.active_plan` is None) — production code keeps
no fault-specific control flow, just the hook call.

Keying: ``device_id`` carries the silo/client id (file plane) or group
id (hierarchical plane); ``hop`` names the exchange leg the fault hits:

- ``update`` — silo → aggregator update file (file plane);
- ``sync``   — edge group → cloud contribution (hierarchical);
- ``seed``   — cloud → edge group re-seed (hierarchical).

The checkpoint plane (ckpt/streaming.py) keys its ``ckpt_*`` hooks by
``shard × generation × op``: ``device_id`` carries the shard ordinal,
``round`` the generation step, and ``hop`` the write op (``shard`` |
``history`` | ``manifest``).

Faults fire on the ``server`` site (the default), matching how the plan
treats the device-authoritative end.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from colearn_federated_learning_tpu.faults import inject
from colearn_federated_learning_tpu.faults.plan import ANY, FaultPlan

HOP_UPDATE = "update"
HOP_SYNC = "sync"
HOP_SEED = "seed"
HOP_SHARD = "shard"
HOP_HISTORY = "history"
HOP_MANIFEST = "manifest"


def _match_specs(kind: str, ident: str, round_idx: Optional[int],
                 hop: str) -> list:
    plan: FaultPlan | None = inject.active_plan()
    if plan is None:
        return []
    # ``op`` mirrors the hop so plans may key on either field.
    fired = plan.match(ident, round_idx, hop if hop != ANY else "",
                       kinds=(kind,), site="server", hop=hop)
    if fired:
        inject._count(kind, ident)
    return fired


def _match(kind: str, ident: str, round_idx: Optional[int],
           hop: str) -> bool:
    return bool(_match_specs(kind, ident, round_idx, hop))


def should_drop(ident: str, round_idx: Optional[int],
                hop: str = HOP_UPDATE) -> bool:
    """True when a ``drop_silo`` spec fires for this exchange leg — the
    caller withholds the silo/group's contribution entirely."""
    return _match("drop_silo", ident, round_idx, hop)


def stale_meta(meta: dict, ident: str, round_idx: Optional[int],
               hop: str = HOP_UPDATE) -> dict:
    """Apply a ``stale_round`` fault to an update's metadata: the round
    stamp is wound back one round, as a silo replaying an old file
    would.  Returns ``meta`` untouched when no spec fires."""
    if not _match("stale_round", ident, round_idx, hop):
        return meta
    stamped = dict(meta)
    stamped["round"] = int(meta.get("round", 0)) - 1
    return stamped


def maybe_truncate(path: str, ident: str, round_idx: Optional[int],
                   hop: str = HOP_UPDATE) -> bool:
    """Apply a ``truncate_file`` fault: cut the written file to half its
    bytes, exactly the torn npz a SIGKILLed silo without atomic writes
    would leave behind.  Returns True when the fault fired."""
    if not _match("truncate_file", ident, round_idx, hop):
        return False
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    return True


# ------------------------------------------------------ checkpoint plane --

def ckpt_slow_io(shard: int, generation: Optional[int], op: str) -> bool:
    """Apply a ``slow_io`` fault: sleep the spec's ``ms`` before the
    write — stretching the save window so the kill-during-save chaos
    gate can land a real SIGKILL between shard commit and manifest
    commit deterministically.  Returns True when a spec fired."""
    fired = _match_specs("slow_io", str(shard), generation, op)
    for spec in fired:
        if spec.ms:
            time.sleep(spec.ms / 1000.0)
    return bool(fired)


def ckpt_torn_shard(path: str, shard: int,
                    generation: Optional[int]) -> bool:
    """Apply a ``torn_shard`` fault: cut a just-committed shard file to
    half its bytes — the torn artifact restore's recovery matrix must
    discard (``ckpt.generations_discarded_total{reason=torn_shard}``)
    by falling back a generation.  Returns True when the fault fired."""
    if not _match("torn_shard", str(shard), generation, HOP_SHARD):
        return False
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    return True


def ckpt_stale_manifest(generation: Optional[int]) -> bool:
    """True when a ``stale_manifest`` spec fires: the caller suppresses
    the generation's manifest write entirely, leaving the shard files
    uncommitted — exactly the state a SIGKILL between the last shard
    fsync and the manifest replace produces."""
    return _match("stale_manifest", ANY, generation, HOP_MANIFEST)
