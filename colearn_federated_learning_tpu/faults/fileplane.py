"""File/hierarchical-plane fault hooks for an installed FaultPlan.

The comm plane injects faults through a transport interposer; the file
and hierarchical planes have no transport, so their exchange points call
these hooks directly.  Each hook is a zero-cost no-op when no plan is
installed (:func:`~.inject.active_plan` is None) — production code keeps
no fault-specific control flow, just the hook call.

Keying: ``device_id`` carries the silo/client id (file plane) or group
id (hierarchical plane); ``hop`` names the exchange leg the fault hits:

- ``update`` — silo → aggregator update file (file plane);
- ``sync``   — edge group → cloud contribution (hierarchical);
- ``seed``   — cloud → edge group re-seed (hierarchical).

Faults fire on the ``server`` site (the default), matching how the plan
treats the device-authoritative end.
"""

from __future__ import annotations

import os
from typing import Optional

from colearn_federated_learning_tpu.faults import inject
from colearn_federated_learning_tpu.faults.plan import ANY, FaultPlan

HOP_UPDATE = "update"
HOP_SYNC = "sync"
HOP_SEED = "seed"


def _match(kind: str, ident: str, round_idx: Optional[int],
           hop: str) -> bool:
    plan: FaultPlan | None = inject.active_plan()
    if plan is None:
        return False
    # ``op`` mirrors the hop so plans may key on either field.
    fired = plan.match(ident, round_idx, hop if hop != ANY else "",
                       kinds=(kind,), site="server", hop=hop)
    if fired:
        inject._count(kind, ident)
    return bool(fired)


def should_drop(ident: str, round_idx: Optional[int],
                hop: str = HOP_UPDATE) -> bool:
    """True when a ``drop_silo`` spec fires for this exchange leg — the
    caller withholds the silo/group's contribution entirely."""
    return _match("drop_silo", ident, round_idx, hop)


def stale_meta(meta: dict, ident: str, round_idx: Optional[int],
               hop: str = HOP_UPDATE) -> dict:
    """Apply a ``stale_round`` fault to an update's metadata: the round
    stamp is wound back one round, as a silo replaying an old file
    would.  Returns ``meta`` untouched when no spec fires."""
    if not _match("stale_round", ident, round_idx, hop):
        return meta
    stamped = dict(meta)
    stamped["round"] = int(meta.get("round", 0)) - 1
    return stamped


def maybe_truncate(path: str, ident: str, round_idx: Optional[int],
                   hop: str = HOP_UPDATE) -> bool:
    """Apply a ``truncate_file`` fault: cut the written file to half its
    bytes, exactly the torn npz a SIGKILLed silo without atomic writes
    would leave behind.  Returns True when the fault fired."""
    if not _match("truncate_file", ident, round_idx, hop):
        return False
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    return True
