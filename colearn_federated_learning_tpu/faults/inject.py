"""Transport interposer that applies a :class:`~.plan.FaultPlan`.

The injector is the only piece that knows how to express each fault kind
through the generic transport seams (comm/transport.py hook points +
ordinary transport exceptions), so production transport code carries no
fault-specific control flow:

- ``delay``            — sleep ``ms`` before the handler runs;
- ``drop_request``     — raise ``SkipRequest``: the request is silently
                         discarded, the client times out (a lost packet);
- ``flap_reconnect``   — raise ``ConnectionClosed``: the server severs
                         the connection pre-reply (a reset), the client's
                         retry path reconnects;
- ``corrupt_payload``  — write a frame with a deliberately wrong CRC32 in
                         place of the reply, then sever: the client's
                         ``recv_msg`` raises ``CorruptFrame``;
- ``crash_worker``     — stop the device's whole TensorServer: every
                         later request sees a dead peer until the worker
                         is restarted (mid-run crash).
"""

from __future__ import annotations

import socket
import time

from colearn_federated_learning_tpu.comm import protocol, transport
from colearn_federated_learning_tpu.faults.plan import FaultPlan
from colearn_federated_learning_tpu.telemetry import registry as _metrics

_REQUEST_KINDS = ("delay", "drop_request", "flap_reconnect", "crash_worker")
_REPLY_KINDS = ("corrupt_payload",)

# The installed plan, shared with the file/hierarchical plane hooks
# (faults/fileplane.py) so one ``install`` drives every plane.
_active_plan: FaultPlan | None = None


def active_plan() -> FaultPlan | None:
    """The currently installed plan, or None — the file/hierarchical
    fault hooks are zero-cost no-ops when this is None."""
    return _active_plan


def _key(header: dict) -> tuple[int, str]:
    rnd = header.get("round")
    return (None if rnd is None else int(rnd)), str(header.get("op", ""))


def _count(kind: str, device: str = "") -> None:
    reg = _metrics.get_registry()
    reg.counter("fault.injected_total",
                labels={"device": str(device), "kind": kind}).inc()
    reg.counter(f"fault.injected.{kind}").inc()


def send_corrupt_frame(sock: socket.socket) -> None:
    """Emit a frame whose CRC32 cannot match its contents — what a flaky
    NIC/path would deliver.  Lengths stay sane so the receiver reads the
    whole frame and fails the integrity check, not the length sanity
    check."""
    hdr = b'{"status":"ok"}'
    body = b"\x00corrupted\x00"
    crc = protocol.frame_crc(hdr, body) ^ 0xDEADBEEF
    sock.sendall(protocol._HDR.pack(len(hdr)) + hdr
                 + protocol._BODY.pack(len(body), crc) + body)


class FaultInjector(transport.TransportInterposer):
    """Apply ``plan`` at the transport seams (see module docstring)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def _apply(self, fault, server, conn) -> None:
        _count(fault.kind, server.ident if server is not None else "")
        if fault.kind == "delay":
            time.sleep(fault.ms / 1000.0)
        elif fault.kind == "drop_request":
            raise transport.SkipRequest(f"injected drop ({fault})")
        elif fault.kind == "flap_reconnect":
            raise protocol.ConnectionClosed(f"injected flap ({fault})")
        elif fault.kind == "crash_worker":
            if server is not None:
                server.stop()
            raise protocol.ConnectionClosed(f"injected crash ({fault})")

    # ------------------------------------------------- transport hooks --
    def server_request(self, server, conn, header) -> None:
        rnd, op = _key(header)
        for f in self.plan.match(server.ident, rnd, op,
                                 kinds=_REQUEST_KINDS, site="server"):
            self._apply(f, server, conn)

    def server_reply(self, server, conn, header) -> None:
        rnd, op = _key(header)
        for f in self.plan.match(server.ident, rnd, op,
                                 kinds=_REPLY_KINDS, site="server"):
            _count(f.kind, server.ident)
            send_corrupt_frame(conn)
            raise protocol.ConnectionClosed(f"injected corruption ({f})")

    def client_request(self, client, header) -> None:
        rnd, op = _key(header)
        for f in self.plan.match(client.ident, rnd, op,
                                 kinds=("delay", "flap_reconnect"),
                                 site="client"):
            _count(f.kind, client.ident)
            if f.kind == "delay":
                time.sleep(f.ms / 1000.0)
            else:
                raise protocol.ConnectionClosed(f"injected flap ({f})")


def install(plan: FaultPlan) -> FaultInjector:
    """Install ``plan`` process-wide; returns the injector (its ``plan``
    keeps the firing ledger).  Also publishes the plan to the
    file/hierarchical plane hooks (:func:`active_plan`).  Call
    :func:`uninstall` when done."""
    global _active_plan
    injector = FaultInjector(plan)
    transport.install_interposer(injector)
    _active_plan = plan
    return injector


def uninstall() -> None:
    global _active_plan
    transport.install_interposer(None)
    _active_plan = None
