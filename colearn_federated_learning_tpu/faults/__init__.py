"""Deterministic fault injection for the federation planes.

``plan`` describes WHAT fails (a seeded schedule keyed by
``(device_id, round, op[, hop])``), ``inject`` applies it at the
transport interposer seams, ``fileplane`` applies the file/hierarchical
kinds at the exchange-file seams, and ``soak``/``procsoak`` run a
federation under a plan — in-process and as real subprocesses with real
SIGKILL respectively — and report whether the robustness machinery
(retries, quorum, eviction, CRC framing, checkpoint resume) actually
held.  Production code never imports this package beyond the hook
functions — comm/transport.py only exposes the seams.
"""

from colearn_federated_learning_tpu.faults.plan import (
    ANY,
    ANY_ROUND,
    FILE_KINDS,
    KINDS,
    FaultPlan,
    FaultSpec,
)
from colearn_federated_learning_tpu.faults.inject import (
    FaultInjector,
    active_plan,
    install,
    uninstall,
)
from colearn_federated_learning_tpu.faults.soak import (
    canned_plan,
    default_soak_config,
    run_soak,
)
from colearn_federated_learning_tpu.faults.procsoak import (
    KillSpec,
    canned_kill_schedule,
    run_proc_soak,
)

__all__ = [
    "ANY",
    "ANY_ROUND",
    "FILE_KINDS",
    "KINDS",
    "FaultPlan",
    "FaultSpec",
    "FaultInjector",
    "KillSpec",
    "active_plan",
    "canned_kill_schedule",
    "install",
    "uninstall",
    "canned_plan",
    "default_soak_config",
    "run_proc_soak",
    "run_soak",
]
