"""Deterministic fault injection for the federation comm plane.

``plan`` describes WHAT fails (a seeded schedule keyed by
``(device_id, round, op)``), ``inject`` applies it at the transport
interposer seams, and ``soak`` runs an in-process federation under a plan
and reports whether the robustness machinery (retries, quorum, eviction,
CRC framing) actually held.  Production code never imports this package —
comm/transport.py only exposes the seams.
"""

from colearn_federated_learning_tpu.faults.plan import (
    ANY,
    ANY_ROUND,
    KINDS,
    FaultPlan,
    FaultSpec,
)
from colearn_federated_learning_tpu.faults.inject import (
    FaultInjector,
    install,
    uninstall,
)
from colearn_federated_learning_tpu.faults.soak import (
    canned_plan,
    default_soak_config,
    run_soak,
)

__all__ = [
    "ANY",
    "ANY_ROUND",
    "KINDS",
    "FaultPlan",
    "FaultSpec",
    "FaultInjector",
    "install",
    "uninstall",
    "canned_plan",
    "default_soak_config",
    "run_soak",
]
