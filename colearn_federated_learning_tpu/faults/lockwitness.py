"""Runtime lock witness: observed acquisition order + guarded-access stamps.

The static concurrency rules (CL017–CL021) reason about the lexical
lock structure; this module checks the same contracts against what the
threads actually do while a chaos soak runs.  It is ZERO-COST when off:
the :func:`lock` / :func:`condition` / :func:`guarded` factories return
plain ``threading`` primitives / the bare object unless the
``COLEARN_LOCK_WITNESS`` environment variable is truthy, so production
constructors call them unconditionally.

When enabled:

- every witnessed lock records a per-thread held stack; acquiring B
  while holding A adds the edge ``A -> B`` to a process-global graph,
  and an acquisition whose edge closes a path back to an already-held
  lock is recorded as an **inversion** (the deadlock CL018 looks for
  statically, caught in vivo);
- :func:`guarded` wraps a declared dict/list/set so every mutating (and
  iterating) operation checks that the declared guard is held by the
  current thread; a bare access is recorded as an **unguarded-access
  witness** with the caller's file:line;
- at interpreter exit each process dumps its report to
  ``$COLEARN_LOCK_WITNESS_DIR/lockwitness-<pid>.json`` (when the dir is
  set), which the procsoak fleets collect into the soak summary — the
  ``chaos --async/--tree-async --lock-witness`` gate requires zero
  inversions and zero unguarded accesses.

The wrappers deliberately keep ``threading`` semantics: a witnessed
Condition is a real ``threading.Condition`` built around a witnessed
lock (``wait`` releases/reacquires through the wrapper, so the held
stack stays truthful across the block).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_ENV = "COLEARN_LOCK_WITNESS"
_DIR_ENV = "COLEARN_LOCK_WITNESS_DIR"
_TRUTHY = {"1", "true", "on", "yes"}


def enabled() -> bool:
    return os.environ.get(_ENV, "").strip().lower() in _TRUTHY


# ------------------------------------------------------------- registry --
class _Witness:
    """Process-global witness state (edges, inversions, unguarded)."""

    def __init__(self) -> None:
        self.mu = threading.Lock()
        self.edges: Dict[Tuple[str, str], int] = {}
        self.inversions: List[dict] = []
        self.unguarded: List[dict] = []
        self.acquires = 0
        self.guarded_ops = 0
        self._tls = threading.local()
        self._dump_registered = False

    # held stack for the calling thread
    def held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def on_attempt(self, name: str) -> None:
        """Record ordering edges at acquire ATTEMPT: the inversion exists
        the moment a thread tries B-while-holding-A against an observed
        A-after-B order — even if the acquire then times out (which is
        exactly how a real deadlock manifests)."""
        stack = self.held()
        with self.mu:
            for h in stack:
                if h == name:
                    continue
                edge = (h, name)
                fresh = edge not in self.edges
                self.edges[edge] = self.edges.get(edge, 0) + 1
                if fresh and self._path(name, h):
                    self.inversions.append({
                        "edge": [h, name],
                        "held": list(stack),
                        "thread": threading.current_thread().name,
                    })

    def on_acquired(self, name: str) -> None:
        with self.mu:
            self.acquires += 1
        self.held().append(name)

    def on_released(self, name: str) -> None:
        stack = self.held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def _path(self, src: str, dst: str) -> bool:  # colearn: holds(mu)
        """True when ``src`` already reaches ``dst`` in the edge graph
        (so a fresh dst->src edge closes a cycle).  Caller holds mu."""
        seen: Set[str] = set()
        frontier = [src]
        while frontier:
            node = frontier.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            frontier.extend(b for (a, b) in self.edges if a == node)
        return False

    def on_unguarded(self, structure: str, op: str, guard: str) -> None:
        # 0=here, 1=_stamp, 2=_check, 3=guarded dunder, 4=caller
        frame = sys._getframe(4)
        site = f"{os.path.basename(frame.f_code.co_filename)}:{frame.f_lineno}"
        with self.mu:
            self.unguarded.append({
                "structure": structure, "op": op, "guard": guard,
                "site": site,
                "thread": threading.current_thread().name,
            })

    def report(self) -> dict:
        with self.mu:
            return {
                "enabled": True,
                "pid": os.getpid(),
                "acquires": self.acquires,
                "guarded_ops": self.guarded_ops,
                "edges": sorted(f"{a}->{b}" for a, b in self.edges),
                "inversions": list(self.inversions),
                "unguarded": list(self.unguarded),
            }

    def maybe_register_dump(self) -> None:
        if self._dump_registered or not os.environ.get(_DIR_ENV):
            return
        self._dump_registered = True
        atexit.register(self._dump)

    def _dump(self) -> None:
        out_dir = os.environ.get(_DIR_ENV)
        if not out_dir:
            return
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"lockwitness-{os.getpid()}.json")
            with open(path, "w") as f:
                json.dump(self.report(), f, indent=2, sort_keys=True)
                f.write("\n")
        except OSError:  # colearn: noqa(CL003): atexit dump is best-effort diagnostics; nowhere left to report
            pass


_WITNESS = _Witness()


def report() -> dict:
    """Current process's witness report (``{"enabled": False}`` when off)."""
    if not enabled():
        return {"enabled": False}
    return _WITNESS.report()


def reset() -> None:
    """Drop all witness state (unit tests seed fresh scenarios)."""
    global _WITNESS
    registered = _WITNESS._dump_registered
    _WITNESS = _Witness()
    _WITNESS._dump_registered = registered


# ---------------------------------------------------------------- locks --
class WitnessLock:
    """Duck-typed ``threading.Lock`` recording acquisition order.  Also
    implements the private ``_is_owned`` / ``_release_save`` /
    ``_acquire_restore`` hooks ``threading.Condition`` probes for, so a
    Condition built on top keeps the held stack truthful across wait()."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._owner: Optional[int] = None
        _WITNESS.maybe_register_dump()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            _WITNESS.on_attempt(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            _WITNESS.on_acquired(self.name)
        return got

    def release(self) -> None:
        self._owner = None
        _WITNESS.on_released(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        self.release()

    def _acquire_restore(self, _state) -> None:
        self.acquire()

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class WitnessRLock(WitnessLock):
    """Reentrant variant: re-acquisition by the owner only deepens a
    count (one held-stack entry, one edge set)."""

    def __init__(self, name: str):
        super().__init__(name)
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._owner == threading.get_ident():
            self._count += 1
            return True
        if blocking:
            _WITNESS.on_attempt(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._count = 1
            _WITNESS.on_acquired(self.name)
        return got

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise RuntimeError(f"release of un-owned witness rlock "
                               f"{self.name}")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            _WITNESS.on_released(self.name)
            self._lock.release()


def lock(name: str):
    """A ``threading.Lock`` (witness-wrapped when the witness is on)."""
    if not enabled():
        return threading.Lock()
    return WitnessLock(name)


def rlock(name: str):
    if not enabled():
        return threading.RLock()
    return WitnessRLock(name)


def condition(name: str):
    """A ``threading.Condition`` (built on a witnessed lock when on)."""
    if not enabled():
        return threading.Condition()
    return threading.Condition(WitnessLock(name))


# ------------------------------------------------------------- guarded --
def _guard_lock(guard):
    """The WitnessLock inside a witnessed lock/Condition, else None."""
    if isinstance(guard, WitnessLock):
        return guard
    inner = getattr(guard, "_lock", None)
    return inner if isinstance(inner, WitnessLock) else None


def _stamp(structure: str, guard, op: str) -> None:
    gl = _guard_lock(guard)
    with _WITNESS.mu:
        _WITNESS.guarded_ops += 1
    if gl is not None and gl._is_owned():
        return
    _WITNESS.on_unguarded(structure, op,
                          gl.name if gl is not None else "?")


class _GuardedDict(dict):
    def __init__(self, data, structure, guard):
        super().__init__(data)
        self._structure = structure
        self._guard = guard

    def _check(self, op):
        _stamp(self._structure, self._guard, op)

    def __getitem__(self, k):
        self._check("getitem")
        return super().__getitem__(k)

    def __setitem__(self, k, v):
        self._check("setitem")
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._check("delitem")
        super().__delitem__(k)

    def __iter__(self):
        self._check("iter")
        return super().__iter__()

    def get(self, k, default=None):
        self._check("get")
        return super().get(k, default)

    def pop(self, *a, **kw):
        self._check("pop")
        return super().pop(*a, **kw)

    def update(self, *a, **kw):
        self._check("update")
        return super().update(*a, **kw)

    def setdefault(self, *a, **kw):
        self._check("setdefault")
        return super().setdefault(*a, **kw)

    def clear(self):
        self._check("clear")
        return super().clear()

    def items(self):
        self._check("items")
        return super().items()

    def values(self):
        self._check("values")
        return super().values()


class _GuardedSet(set):
    def __init__(self, data, structure, guard):
        super().__init__(data)
        self._structure = structure
        self._guard = guard

    def _check(self, op):
        _stamp(self._structure, self._guard, op)

    def add(self, v):
        self._check("add")
        return super().add(v)

    def discard(self, v):
        self._check("discard")
        return super().discard(v)

    def remove(self, v):
        self._check("remove")
        return super().remove(v)

    def __contains__(self, v):
        self._check("contains")
        return super().__contains__(v)

    def __iter__(self):
        self._check("iter")
        return super().__iter__()

    def clear(self):
        self._check("clear")
        return super().clear()


class _GuardedList(list):
    def __init__(self, data, structure, guard):
        super().__init__(data)
        self._structure = structure
        self._guard = guard

    def _check(self, op):
        _stamp(self._structure, self._guard, op)

    def append(self, v):
        self._check("append")
        return super().append(v)

    def extend(self, it):
        self._check("extend")
        return super().extend(it)

    def pop(self, *a):
        self._check("pop")
        return super().pop(*a)

    def remove(self, v):
        self._check("remove")
        return super().remove(v)

    def __setitem__(self, i, v):
        self._check("setitem")
        return super().__setitem__(i, v)

    def __iter__(self):
        self._check("iter")
        return super().__iter__()

    def clear(self):
        self._check("clear")
        return super().clear()


def guarded(obj, structure: str, guard):
    """Stamp ``obj`` (dict/list/set) so accesses assert ``guard`` is held
    by the calling thread.  Returns ``obj`` unchanged when the witness is
    off or the guard is not witness-wrapped (plain threading primitive)."""
    if not enabled() or _guard_lock(guard) is None:
        return obj
    if isinstance(obj, dict):
        return _GuardedDict(obj, structure, guard)
    if isinstance(obj, set):
        return _GuardedSet(obj, structure, guard)
    if isinstance(obj, list):
        return _GuardedList(obj, structure, guard)
    return obj
