"""Deterministic fault schedules for the federation planes.

A :class:`FaultPlan` is a seeded list of :class:`FaultSpec` entries keyed
by ``(device_id, round, op)`` — and, for the file/hierarchical planes, by
``hop`` (which exchange leg the fault hits).  Matching is pure
bookkeeping — the plan never touches a socket or a file; :mod:`.inject`
turns matches into transport behavior and :mod:`.fileplane` into
exchange-file behavior.  Determinism is the point: the same plan + seed
produces the same faults at the same keys on every run, so a chaos soak
is a regression test, not a dice roll.

Comm-plane kinds (applied by :mod:`.inject` at the transport seams):
``drop_request``, ``delay``, ``corrupt_payload``, ``crash_worker``,
``flap_reconnect``.  The ``op`` key matches whatever the request header
carries, so secure-aggregation rounds expose two extra drop points:
``op="share_setup"`` (device pruned before training — no recovery
needed) and ``op="unmask"`` (device folds its masked update, then goes
silent DURING recovery — the after-fold/before-unmask window the
dropout protocol exists for).  File/hierarchical-plane kinds (applied by
:mod:`.fileplane`, keyed ``(silo|group, round, hop)``):

- ``truncate_file`` — an update npz is cut short mid-write (killed silo);
- ``stale_round``   — an update carries an old round stamp (silo replay);
- ``drop_silo``     — a silo/group's contribution never arrives.

Checkpoint-plane kinds (applied by :mod:`.fileplane`'s ``ckpt_*`` hooks
inside ``ckpt/streaming.py`` saves, keyed ``(shard, generation, op)``
with ``hop`` carrying the op — ``shard`` | ``history`` | ``manifest``):

- ``torn_shard``     — a committed shard file is cut to half its bytes
  (the torn artifact recovery must fall back over);
- ``stale_manifest`` — the generation's manifest write is suppressed, so
  the save aborts uncommitted (a SIGKILL between the last shard fsync
  and the manifest replace);
- ``slow_io``        — the shard/manifest write sleeps ``ms`` first (the
  deterministic window the kill-during-save chaos gate fires into).

JSON surface (``--fault-plan plan.json``)::

    {"seed": 7, "faults": [
        {"kind": "delay", "device_id": "1", "round": 2, "op": "train",
         "ms": 250},
        {"kind": "corrupt_payload", "device_id": "2", "round": 3},
        {"kind": "truncate_file", "device_id": "silo0", "round": 1,
         "hop": "update"},
        {"kind": "drop_silo", "device_id": "g1", "round": 2, "hop": "sync"}
    ]}
"""

from __future__ import annotations

import dataclasses
import json
import threading
import zlib
from typing import Optional

KINDS = ("drop_request", "delay", "corrupt_payload", "crash_worker",
         "flap_reconnect", "truncate_file", "stale_round", "drop_silo",
         "torn_shard", "stale_manifest", "slow_io")

FILE_KINDS = ("truncate_file", "stale_round", "drop_silo")

CKPT_KINDS = ("torn_shard", "stale_manifest", "slow_io")

ANY = "*"          # wildcard device_id / op
ANY_ROUND = -1     # wildcard round


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault.

    ``count`` bounds how many times the spec fires (0 = unlimited);
    ``probability`` gates each candidate firing through a deterministic
    per-key hash of the plan seed, so sub-1.0 rates are reproducible.
    ``site`` selects which transport end applies it (faults fire on the
    device's server side by default — that is where ``device_id`` is
    authoritative).  ``hop`` keys file/hierarchical-plane faults to one
    exchange leg (file plane: ``update``; hierarchical: ``sync`` edge→
    cloud, ``seed`` cloud→edge); it is ignored by the comm plane."""

    kind: str
    device_id: str = ANY
    round: int = ANY_ROUND
    op: str = ANY
    ms: float = 0.0                  # delay duration
    count: int = 1                   # max firings; 0 = unlimited
    probability: float = 1.0
    site: str = "server"             # server | client
    hop: str = ANY                   # file/hier exchange leg

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {KINDS}")
        if self.site not in ("server", "client"):
            raise ValueError(f"fault site must be server|client, "
                             f"got {self.site!r}")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(
                f"probability must be in [0, 1], got {self.probability}")
        if self.ms < 0 or self.count < 0:
            raise ValueError("ms and count must be >= 0")

    def matches(self, device_id: str, round_idx: Optional[int],
                op: str, hop: str = ANY) -> bool:
        if self.device_id != ANY and self.device_id != str(device_id):
            return False
        if self.round != ANY_ROUND and (round_idx is None
                                        or int(round_idx) != self.round):
            return False
        if self.op != ANY and self.op != op:
            return False
        if self.hop != ANY and self.hop != hop:
            return False
        return True


def _hash_unit(seed: int, key: str) -> float:
    """Deterministic uniform in [0, 1) from (seed, key) — crc32-based so
    the schedule is identical across processes and Python hash seeds."""
    h = zlib.crc32(f"{seed}:{key}".encode())
    return h / float(1 << 32)


class FaultPlan:
    """Seeded, deterministic fault schedule with firing bookkeeping."""

    def __init__(self, faults: list[FaultSpec] = (), seed: int = 0):
        self.faults = list(faults)
        self.seed = int(seed)
        self._fired = [0] * len(self.faults)
        self._lock = threading.Lock()

    # ---------------------------------------------------------- config --
    @classmethod
    def from_json(cls, text: str, seed: Optional[int] = None) -> "FaultPlan":
        doc = json.loads(text)
        specs = [FaultSpec(**f) for f in doc.get("faults", [])]
        return cls(specs, seed=doc.get("seed", 0) if seed is None else seed)

    @classmethod
    def load(cls, path: str, seed: Optional[int] = None) -> "FaultPlan":
        with open(path) as f:
            return cls.from_json(f.read(), seed=seed)

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "faults": [dataclasses.asdict(f) for f in self.faults],
        }, indent=2)

    # ---------------------------------------------------------- firing --
    def match(self, device_id: str, round_idx: Optional[int], op: str,
              kinds: tuple = KINDS, site: str = "server", hop: str = ANY
              ) -> list[FaultSpec]:
        """The specs that FIRE for this ``(device_id, round, op[, hop])``
        event, consuming one firing from each returned spec's ``count``
        budget.  Deterministic: the probability gate hashes the plan seed
        with the event key and the spec index, never a live RNG.  The hop
        joins the hash key only when given, so comm-plane schedules are
        bit-identical to the pre-hop format."""
        out = []
        with self._lock:
            for i, f in enumerate(self.faults):
                if f.site != site or f.kind not in kinds:
                    continue
                if f.count and self._fired[i] >= f.count:
                    continue
                if not f.matches(device_id, round_idx, op, hop):
                    continue
                if f.probability < 1.0:
                    key = f"{device_id}:{round_idx}:{op}:{i}"
                    if hop != ANY:
                        key = f"{device_id}:{round_idx}:{op}:{hop}:{i}"
                    if _hash_unit(self.seed, key) >= f.probability:
                        continue
                self._fired[i] += 1
                out.append(f)
        return out

    @property
    def fired(self) -> dict[int, int]:
        """``{spec index: times fired}`` for specs that fired at least
        once — the soak report's injection ledger."""
        with self._lock:
            return {i: n for i, n in enumerate(self._fired) if n}

    def total_fired(self) -> int:
        with self._lock:
            return sum(self._fired)
