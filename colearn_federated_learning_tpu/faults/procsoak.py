"""Multi-process chaos soak: real subprocesses, real ports, real SIGKILL.

The in-process soak (faults/soak.py) exercises the robustness machinery
through a transport interposer — everything a Python exception can
express.  This harness exercises what it cannot: fd leaks, half-written
frames, torn files and lost process state.  It spawns the broker, N
``colearn worker`` processes and a ``colearn coordinate`` process on real
sockets, then delivers ``SIGKILL`` on a deterministic schedule keyed by
round — including to the coordinator mid-round, which must come back with
``--resume`` and finish the original round budget from its checkpoint +
round WAL, and to the broker, which is respawned on its original port
and must be healed INTO by the survivors (worker re-enrollment
watchdogs, coordinator ``_rebuild_broker``) without losing a round.

The schedule is event-driven, not timer-driven: a :class:`KillSpec`
fires the moment the coordinator's stderr emits the round record for
``after_round``, so the signal lands while the NEXT round is in flight.
That keeps the soak deterministic in ROUND time even though wall-clock
varies run to run.

``scripts/chaos_soak_mp.py`` wraps this in a baseline-vs-faulted
convergence gate; ``colearn chaos --mp`` is the one-run flavor.
"""

from __future__ import annotations

import dataclasses
import json
import os
import select
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Optional

_CLI = "colearn_federated_learning_tpu.cli"


@dataclasses.dataclass(frozen=True)
class KillSpec:
    """One scheduled SIGKILL.

    ``target`` is ``"coordinator"``, ``"broker"`` or
    ``"worker:<client_id>"``.  The signal is sent as soon as the round
    record for ``after_round`` appears, i.e. it lands mid-round
    ``after_round + 1``.  ``restart`` respawns the victim: a worker
    re-announces on a fresh port (and is re-admitted by the elastic
    coordinator after eviction), the coordinator comes back with
    ``--resume``, and the broker rebinds its ORIGINAL port — the
    control-plane SPOF heals through the worker re-enrollment watchdog
    and the coordinator's ``_rebuild_broker`` without any address
    change."""

    target: str
    after_round: int
    restart: bool = True

    def __post_init__(self):
        if self.target not in ("coordinator", "broker") and not (
                self.target.startswith("worker:")
                and self.target.split(":", 1)[1].isdigit()):
            raise ValueError(
                f"target must be 'coordinator', 'broker' or "
                f"'worker:<id>', got {self.target!r}")
        if self.after_round < 0:
            raise ValueError(
                f"after_round must be >= 0, got {self.after_round}")
        if self.target in ("coordinator", "broker") and not self.restart:
            raise ValueError(
                f"killing the {self.target} without restart ends the "
                "federation; use restart=True")


def canned_kill_schedule(rounds: int, n_workers: int) -> list[KillSpec]:
    """The acceptance schedule, scaled to the run length:

    - a worker dies mid-round 2 and restarts (exercises eviction +
      elastic re-admission on a fresh port) — only when the run is long
      enough for it to be evicted AND re-converge;
    - the coordinator dies mid-round ``rounds // 2 + 1``, after the
      round-``rounds//2`` checkpoint committed, and must resume;
    - the broker dies one round after the coordinator resumed and
      rebinds its original port (control-plane SPOF: worker watchdogs
      re-enroll, the coordinator rebuilds its client) — only when the
      run leaves at least one full round after the rebind to prove the
      federation still commits.
    """
    kills = []
    if rounds >= 5 and n_workers >= 3:
        kills.append(KillSpec("worker:1", after_round=1))
    kills.append(KillSpec("coordinator",
                          after_round=max(0, rounds // 2 - 1)))
    if rounds >= 4:
        kills.append(KillSpec("broker", after_round=rounds // 2))
    return kills


def _config_flags(rounds: int, n_workers: int, seed: int,
                  checkpoint_dir: Optional[str] = None) -> list[str]:
    """CLI overrides reproducing faults/soak.default_soak_config — same
    tiny CPU federation, robustness features ON."""
    flags = [
        "--config", "mnist_mlp_fedavg", "--backend", "cpu",
        "--dataset", "mnist_tiny", "--partition", "iid",
        "--num-clients", str(n_workers), "--rounds", str(rounds),
        "--cohort-size", "0", "--local-steps", "4", "--batch-size", "16",
        "--lr", "0.05", "--momentum", "0.0", "--strategy", "fedavg",
        "--min-cohort-fraction", "0.5", "--evict-after", "2",
        "--comm-retries", "2", "--seed", str(seed),
    ]
    if checkpoint_dir:
        flags += ["--checkpoint-dir", checkpoint_dir,
                  "--checkpoint-every", "1"]
    return flags


def _parse_json(line: str) -> Optional[dict]:
    try:
        doc = json.loads(line)
    except json.JSONDecodeError:
        return None            # ordinary log chatter on the same stream
    return doc if isinstance(doc, dict) else None


class _Fleet:
    """Process bookkeeping for one soak run (spawn/kill/cleanup)."""

    def __init__(self, workdir: str, env: dict):
        self.workdir = workdir
        self.env = env
        self.broker: Optional[subprocess.Popen] = None
        self.workers: dict[int, subprocess.Popen] = {}
        self.coord: Optional[subprocess.Popen] = None
        self._logs: list = []

    def _log_file(self, name: str):
        f = open(os.path.join(self.workdir, name), "ab")
        self._logs.append(f)
        return f

    def spawn(self, args: list[str], **kw) -> subprocess.Popen:
        return subprocess.Popen([sys.executable, "-m", _CLI, *args],
                                env=self.env, **kw)

    def start_broker(self, timeout: float,
                     extra: list[str] = ()) -> tuple[str, int]:
        self._broker_extra = list(extra)
        self.broker = self.spawn(
            ["broker", *self._broker_extra], stdout=subprocess.PIPE,
            stderr=self._log_file("broker.log"), text=True)
        ready, _, _ = select.select([self.broker.stdout], [], [], timeout)
        if not ready:
            raise RuntimeError("broker never announced its port")
        doc = _parse_json(self.broker.stdout.readline())
        if not doc:
            raise RuntimeError("broker printed no address line")
        self._broker_addr = (doc["host"], int(doc["port"]))
        return self._broker_addr

    def restart_broker(self, timeout: float = 15.0,
                       attempts: int = 20) -> None:
        """Respawn the broker bound to its ORIGINAL host:port.

        Workers and the coordinator hold that address — the heal paths
        (worker re-enrollment watchdog, coordinator ``_rebuild_broker``)
        reconnect, they do not rediscover.  The listener socket dies
        with the SIGKILLed process, but the kernel may briefly hold the
        port through lingering accepted connections, so the rebind
        retries with a short sleep instead of failing the soak on a
        race the real deployment would also just retry through."""
        host, port = self._broker_addr
        for _ in range(attempts):
            self.broker = self.spawn(
                ["broker", "--host", host, "--port", str(port),
                 *self._broker_extra],
                stdout=subprocess.PIPE,
                stderr=self._log_file("broker.log"), text=True)
            ready, _, _ = select.select([self.broker.stdout], [], [],
                                        timeout)
            if ready:
                doc = _parse_json(self.broker.stdout.readline())
                if doc and int(doc["port"]) == port:
                    return
            if self.broker.poll() is None:
                self.broker.kill()
            self.broker.wait()
            time.sleep(0.25)
        raise RuntimeError(f"broker failed to rebind {host}:{port} "
                           f"after {attempts} attempts")

    def start_worker(self, client_id: int, cfg: list[str], host: str,
                     port: int) -> None:
        log = self._log_file(f"worker{client_id}.log")
        self.workers[client_id] = self.spawn(
            ["worker", *cfg, "--client-id", str(client_id),
             "--broker-host", host, "--broker-port", str(port)],
            stdout=log, stderr=log)

    def start_coordinator(self, cfg: list[str], host: str, port: int,
                          n_workers: int, round_timeout: float,
                          enroll_timeout: float,
                          resume: bool) -> subprocess.Popen:
        args = ["coordinate", *cfg, "--broker-host", host,
                "--broker-port", str(port),
                "--min-devices", str(n_workers),
                "--round-timeout", str(round_timeout),
                "--enroll-timeout", str(enroll_timeout),
                "--no-evaluator", "--per-client-eval", "--elastic"]
        if resume:
            args.append("--resume")
        self.coord = self.spawn(
            args, stdout=self._log_file("coordinator.out"),
            stderr=subprocess.PIPE, text=True)
        return self.coord

    def kill_all(self) -> None:
        for p in ([self.coord, self.broker] + list(self.workers.values())):
            if p is not None and p.poll() is None:
                p.kill()

    def close(self) -> None:
        self.kill_all()
        for p in ([self.coord, self.broker] + list(self.workers.values())):
            if p is not None:
                p.wait()
        for f in self._logs:
            f.close()


def run_proc_soak(
    rounds: int = 6,
    n_workers: int = 3,
    kills: Optional[list[KillSpec]] = None,
    workdir: Optional[str] = None,
    round_timeout: float = 120.0,
    enroll_timeout: float = 90.0,
    timeout_s: float = 600.0,
    seed: int = 0,
    log_fn: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Run one multi-process soak and return its summary.

    The summary mirrors faults/soak.run_soak where the concepts overlap
    (``records`` — deduplicated by round, LAST record wins so a resumed
    re-run of an uncommitted round replaces the lost one — plus
    ``skipped_rounds``, ``evicted``, ``per_client_acc``) and adds the
    process-level ledger: ``kills`` delivered, ``rounds_resumed`` (count
    of successful ``--resume`` recoveries, reported by the coordinator's
    resume event line), ``coordinator_incarnations``, the final
    ``exit_code``, and the flight ledger — ``flight_dumps`` (parseable
    black boxes found) and ``flight_missing`` (SIGKILLed pids that left
    no parseable dump; must be empty)."""
    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    kills = list(kills or [])
    for k in kills:
        if k.target.startswith("worker:"):
            wid = int(k.target.split(":", 1)[1])
            if not 0 <= wid < n_workers:
                raise ValueError(f"{k.target} out of range "
                                 f"[0, {n_workers})")
    workdir = workdir or tempfile.mkdtemp(prefix="colearn_mpsoak_")
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, "ckpt")
    flight_dir = os.path.join(workdir, "flight")

    env = dict(os.environ)
    env["PYTHONUNBUFFERED"] = "1"      # round records must stream, not batch
    env["JAX_PLATFORMS"] = "cpu"

    fleet = _Fleet(workdir, env)
    # Hard wall-clock backstop: a hung federation (the exact bug class
    # this harness hunts) must fail the run, not the CI job's timeout.
    watchdog = threading.Timer(timeout_s, fleet.kill_all)
    watchdog.daemon = True

    records: dict[int, dict] = {}
    events: list[dict] = []
    per_client: dict = {}
    resumed = 0
    incarnations = 1
    delivered: list[dict] = []
    pending = sorted(kills, key=lambda k: (k.after_round, k.target))
    rc: Optional[int] = None

    try:
        watchdog.start()
        # Every process flies with the black box on a fast heartbeat: a
        # SIGKILL is uncatchable, so the per-kill dump the summary
        # asserts below IS the victim's last heartbeat rewrite.  The
        # broker carries it too — a broker KillSpec's pid must show up
        # in the flight ledger like any other victim's.
        flight_flags = ["--flight-dir", flight_dir,
                        "--flight-heartbeat", "0.5"]
        host, port = fleet.start_broker(timeout=30.0, extra=flight_flags)
        worker_cfg = _config_flags(rounds, n_workers, seed) + flight_flags
        for i in range(n_workers):
            fleet.start_worker(i, worker_cfg, host, port)
        coord_cfg = _config_flags(rounds, n_workers, seed,
                                  checkpoint_dir=ckpt_dir) + flight_flags

        def launch(resume: bool) -> subprocess.Popen:
            return fleet.start_coordinator(
                coord_cfg, host, port, n_workers, round_timeout,
                enroll_timeout, resume=resume)

        coord = launch(resume=False)
        restart_pending = False
        # Mirror the coordinator's stderr to a workdir log: the harness
        # parses JSON records off the stream, but a crash traceback is
        # NOT JSON and would otherwise vanish with the pipe.
        err_log = fleet._log_file("coordinator.err")
        while True:
            line = coord.stderr.readline()
            if line:
                err_log.write(line.encode())
                err_log.flush()
            if not line:
                coord.wait()
                if restart_pending:
                    restart_pending = False
                    incarnations += 1
                    coord = launch(resume=True)
                    continue
                rc = coord.returncode
                break
            doc = _parse_json(line.strip())
            if doc is None:
                continue
            if "event" in doc:
                events.append(doc)
                if doc["event"] == "resumed":
                    resumed += 1
                continue
            if "num_clients_evaluated" in doc:
                per_client = doc
                continue
            if "round" not in doc:
                continue
            r = int(doc["round"])
            records[r] = doc           # last record per round wins
            if log_fn is not None:
                log_fn(doc)
            while pending and pending[0].after_round <= r:
                spec = pending.pop(0)
                kill_rec = {**dataclasses.asdict(spec),
                            "fired_after_round": r}
                if spec.target == "coordinator":
                    kill_rec["pid"] = coord.pid
                    coord.send_signal(signal.SIGKILL)
                    restart_pending = True
                elif spec.target == "broker":
                    victim = fleet.broker
                    if victim is not None and victim.poll() is None:
                        kill_rec["pid"] = victim.pid
                        victim.send_signal(signal.SIGKILL)
                        victim.wait()
                    fleet.restart_broker()
                else:
                    wid = int(spec.target.split(":", 1)[1])
                    victim = fleet.workers.get(wid)
                    if victim is not None and victim.poll() is None:
                        kill_rec["pid"] = victim.pid
                        victim.send_signal(signal.SIGKILL)
                        victim.wait()
                    if spec.restart:
                        fleet.start_worker(wid, worker_cfg, host, port)
                delivered.append(kill_rec)
    finally:
        watchdog.cancel()
        fleet.close()

    if rc is None:
        raise RuntimeError(
            f"coordinator never exited cleanly within {timeout_s}s "
            f"(records for rounds {sorted(records)})")

    # Flight-dump ledger: every SIGKILLed pid must have left a parseable
    # black box (the acceptance criterion the flight recorder exists
    # for).  A dump that exists but does not parse counts as missing —
    # the atomic-write contract says a dump either parses or is absent.
    from colearn_federated_learning_tpu.telemetry import flight as _flight

    dumps = _flight.load_flight_dumps(flight_dir)
    dumped_pids = {d.get("pid") for d in dumps if "error" not in d}
    flight_missing = sorted({k["pid"] for k in delivered if "pid" in k}
                            - dumped_pids)

    recs = [records[r] for r in sorted(records)]
    return {
        "rounds_run": len(recs),
        "records": recs,
        "completed_rounds": [r["round"] for r in recs
                             if r["completed"] > 0
                             and not r.get("skipped_quorum")],
        "skipped_rounds": [r["round"] for r in recs
                           if r.get("skipped_quorum")],
        "evicted": sorted({d for r in recs for d in r.get("evicted", [])}),
        "weighted_acc": per_client.get("weighted_acc"),
        "weighted_loss": per_client.get("weighted_loss"),
        "per_client_acc": per_client.get("per_client", {}),
        "rounds_resumed": resumed,
        "coordinator_incarnations": incarnations,
        "kills": delivered,
        "flight_dumps": len(dumped_pids),
        "flight_missing": flight_missing,
        "events": events,
        "exit_code": rc,
        "workdir": workdir,
    }
