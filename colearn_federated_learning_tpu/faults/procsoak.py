"""Multi-process chaos soak: real subprocesses, real ports, real SIGKILL.

The in-process soak (faults/soak.py) exercises the robustness machinery
through a transport interposer — everything a Python exception can
express.  This harness exercises what it cannot: fd leaks, half-written
frames, torn files and lost process state.  It spawns the broker, N
``colearn worker`` processes and a ``colearn coordinate`` process on real
sockets, then delivers ``SIGKILL`` on a deterministic schedule keyed by
round — including to the coordinator mid-round, which must come back with
``--resume`` and finish the original round budget from its checkpoint +
round WAL, and to the broker, which is respawned on its original port
and must be healed INTO by the survivors (worker re-enrollment
watchdogs, coordinator ``_rebuild_broker``) without losing a round.

The schedule is event-driven, not timer-driven: a :class:`KillSpec`
fires the moment the coordinator's stderr emits the round record for
``after_round``, so the signal lands while the NEXT round is in flight.
That keeps the soak deterministic in ROUND time even though wall-clock
varies run to run.

``scripts/chaos_soak_mp.py`` wraps this in a baseline-vs-faulted
convergence gate; ``colearn chaos --mp`` is the one-run flavor.
"""

from __future__ import annotations

import dataclasses
import json
import os
import select
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Optional

_CLI = "colearn_federated_learning_tpu.cli"


@dataclasses.dataclass(frozen=True)
class KillSpec:
    """One scheduled SIGKILL.

    ``target`` is ``"coordinator"``, ``"async-coordinator"``,
    ``"broker"``, ``"worker:<client_id>"`` or ``"aggregator:<n>"``.
    The signal is sent as soon as the round record for ``after_round``
    appears, i.e. it lands mid-round ``after_round + 1`` (for the
    buffered-async plane ``after_round`` counts AGGREGATIONS — the kill
    lands mid-aggregation, while dispatcher pumps are in flight).
    ``restart`` respawns the victim: a worker re-announces on a fresh
    port (and is re-admitted by the elastic coordinator after
    eviction), the coordinator comes back with ``--resume``, and the
    broker rebinds its ORIGINAL port — the control-plane SPOF heals
    through the worker re-enrollment watchdog and the coordinator's
    ``_rebuild_broker`` without any address change.  An aggregator is
    the one role that may STAY dead (``restart=False``): the root must
    re-home its slice onto a sibling or quorum-drop it — that failover
    IS the thing the agg soak gates on."""

    target: str
    after_round: int
    restart: bool = True

    def __post_init__(self):
        singletons = ("coordinator", "async-coordinator", "broker")
        if self.target not in singletons and not (
                self.target.split(":", 1)[0] in ("worker", "aggregator")
                and ":" in self.target
                and self.target.split(":", 1)[1].isdigit()):
            raise ValueError(
                f"target must be 'coordinator', 'async-coordinator', "
                f"'broker', 'worker:<id>' or 'aggregator:<n>', "
                f"got {self.target!r}")
        if self.after_round < 0:
            raise ValueError(
                f"after_round must be >= 0, got {self.after_round}")
        if self.target in singletons and not self.restart:
            raise ValueError(
                f"killing the {self.target} without restart ends the "
                "federation; use restart=True")


def canned_kill_schedule(rounds: int, n_workers: int) -> list[KillSpec]:
    """The acceptance schedule, scaled to the run length:

    - a worker dies mid-round 2 and restarts (exercises eviction +
      elastic re-admission on a fresh port) — only when the run is long
      enough for it to be evicted AND re-converge;
    - the coordinator dies mid-round ``rounds // 2 + 1``, after the
      round-``rounds//2`` checkpoint committed, and must resume;
    - the broker dies one round after the coordinator resumed and
      rebinds its original port (control-plane SPOF: worker watchdogs
      re-enroll, the coordinator rebuilds its client) — only when the
      run leaves at least one full round after the rebind to prove the
      federation still commits.
    """
    kills = []
    if rounds >= 5 and n_workers >= 3:
        kills.append(KillSpec("worker:1", after_round=1))
    kills.append(KillSpec("coordinator",
                          after_round=max(0, rounds // 2 - 1)))
    if rounds >= 4:
        kills.append(KillSpec("broker", after_round=rounds // 2))
    return kills


def _config_flags(rounds: int, n_workers: int, seed: int,
                  checkpoint_dir: Optional[str] = None) -> list[str]:
    """CLI overrides reproducing faults/soak.default_soak_config — same
    tiny CPU federation, robustness features ON."""
    flags = [
        "--config", "mnist_mlp_fedavg", "--backend", "cpu",
        "--dataset", "mnist_tiny", "--partition", "iid",
        "--num-clients", str(n_workers), "--rounds", str(rounds),
        "--cohort-size", "0", "--local-steps", "4", "--batch-size", "16",
        "--lr", "0.05", "--momentum", "0.0", "--strategy", "fedavg",
        "--min-cohort-fraction", "0.5", "--evict-after", "2",
        "--comm-retries", "2", "--seed", str(seed),
    ]
    if checkpoint_dir:
        flags += ["--checkpoint-dir", checkpoint_dir,
                  "--checkpoint-every", "1"]
    return flags


def _parse_json(line: str) -> Optional[dict]:
    try:
        doc = json.loads(line)
    except json.JSONDecodeError:
        return None            # ordinary log chatter on the same stream
    return doc if isinstance(doc, dict) else None


class _Fleet:
    """Process bookkeeping for one soak run (spawn/kill/cleanup)."""

    def __init__(self, workdir: str, env: dict):
        self.workdir = workdir
        self.env = env
        self.broker: Optional[subprocess.Popen] = None
        self.workers: dict[int, subprocess.Popen] = {}
        self.aggregators: dict[int, subprocess.Popen] = {}
        self.coord: Optional[subprocess.Popen] = None
        self._logs: list = []

    def _log_file(self, name: str):
        f = open(os.path.join(self.workdir, name), "ab")
        self._logs.append(f)
        return f

    def spawn(self, args: list[str], **kw) -> subprocess.Popen:
        return subprocess.Popen([sys.executable, "-m", _CLI, *args],
                                env=self.env, **kw)

    def start_broker(self, timeout: float,
                     extra: list[str] = ()) -> tuple[str, int]:
        self._broker_extra = list(extra)
        self.broker = self.spawn(
            ["broker", *self._broker_extra], stdout=subprocess.PIPE,
            stderr=self._log_file("broker.log"), text=True)
        ready, _, _ = select.select([self.broker.stdout], [], [], timeout)
        if not ready:
            raise RuntimeError("broker never announced its port")
        doc = _parse_json(self.broker.stdout.readline())
        if not doc:
            raise RuntimeError("broker printed no address line")
        self._broker_addr = (doc["host"], int(doc["port"]))
        return self._broker_addr

    def restart_broker(self, timeout: float = 15.0,
                       attempts: int = 20) -> None:
        """Respawn the broker bound to its ORIGINAL host:port.

        Workers and the coordinator hold that address — the heal paths
        (worker re-enrollment watchdog, coordinator ``_rebuild_broker``)
        reconnect, they do not rediscover.  The listener socket dies
        with the SIGKILLed process, but the kernel may briefly hold the
        port through lingering accepted connections, so the rebind
        retries with a short sleep instead of failing the soak on a
        race the real deployment would also just retry through."""
        host, port = self._broker_addr
        for _ in range(attempts):
            self.broker = self.spawn(
                ["broker", "--host", host, "--port", str(port),
                 *self._broker_extra],
                stdout=subprocess.PIPE,
                stderr=self._log_file("broker.log"), text=True)
            ready, _, _ = select.select([self.broker.stdout], [], [],
                                        timeout)
            if ready:
                doc = _parse_json(self.broker.stdout.readline())
                if doc and int(doc["port"]) == port:
                    return
            if self.broker.poll() is None:
                self.broker.kill()
            self.broker.wait()
            time.sleep(0.25)
        raise RuntimeError(f"broker failed to rebind {host}:{port} "
                           f"after {attempts} attempts")

    def start_worker(self, client_id: int, cfg: list[str], host: str,
                     port: int) -> None:
        log = self._log_file(f"worker{client_id}.log")
        self.workers[client_id] = self.spawn(
            ["worker", *cfg, "--client-id", str(client_id),
             "--broker-host", host, "--broker-port", str(port)],
            stdout=log, stderr=log)

    def start_aggregator(self, agg_id: int, cfg: list[str], host: str,
                         port: int) -> None:
        log = self._log_file(f"aggregator{agg_id}.log")
        self.aggregators[agg_id] = self.spawn(
            ["aggregator", *cfg, "--agg-id", str(agg_id),
             "--broker-host", host, "--broker-port", str(port)],
            stdout=log, stderr=log)

    def start_coordinator(self, cfg: list[str], host: str, port: int,
                          n_workers: int, round_timeout: float,
                          enroll_timeout: float,
                          resume: bool) -> subprocess.Popen:
        args = ["coordinate", *cfg, "--broker-host", host,
                "--broker-port", str(port),
                "--min-devices", str(n_workers),
                "--round-timeout", str(round_timeout),
                "--enroll-timeout", str(enroll_timeout),
                "--no-evaluator", "--per-client-eval", "--elastic"]
        if resume:
            args.append("--resume")
        self.coord = self.spawn(
            args, stdout=self._log_file("coordinator.out"),
            stderr=subprocess.PIPE, text=True)
        return self.coord

    def start_async_coordinator(self, cfg: list[str], host: str, port: int,
                                n_workers: int, round_timeout: float,
                                enroll_timeout: float, buffer_size: int,
                                resume: bool) -> subprocess.Popen:
        """Buffered-async flavor of :meth:`start_coordinator`:
        ``--async-buffer`` switches the CLI onto
        comm/async_coordinator.py, which has no per-client eval plane —
        the gate compares train-loss trajectories instead."""
        args = ["coordinate", *cfg, "--broker-host", host,
                "--broker-port", str(port),
                "--min-devices", str(n_workers),
                "--round-timeout", str(round_timeout),
                "--enroll-timeout", str(enroll_timeout),
                "--async-buffer", str(buffer_size),
                "--no-evaluator", "--elastic"]
        if resume:
            args.append("--resume")
        self.coord = self.spawn(
            args, stdout=self._log_file("coordinator.out"),
            stderr=subprocess.PIPE, text=True)
        return self.coord

    def _all_procs(self) -> list:
        return ([self.coord, self.broker] + list(self.workers.values())
                + list(self.aggregators.values()))

    def kill_all(self) -> None:
        for p in self._all_procs():
            if p is not None and p.poll() is None:
                p.kill()

    def close(self) -> None:
        self.kill_all()
        for p in self._all_procs():
            if p is not None:
                p.wait()
        for f in self._logs:
            f.close()


def run_proc_soak(
    rounds: int = 6,
    n_workers: int = 3,
    kills: Optional[list[KillSpec]] = None,
    workdir: Optional[str] = None,
    round_timeout: float = 120.0,
    enroll_timeout: float = 90.0,
    timeout_s: float = 600.0,
    seed: int = 0,
    n_aggregators: int = 0,
    health: bool = False,
    log_fn: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Run one multi-process soak and return its summary.

    The summary mirrors faults/soak.run_soak where the concepts overlap
    (``records`` — deduplicated by round, LAST record wins so a resumed
    re-run of an uncommitted round replaces the lost one — plus
    ``skipped_rounds``, ``evicted``, ``per_client_acc``) and adds the
    process-level ledger: ``kills`` delivered, ``rounds_resumed`` (count
    of successful ``--resume`` recoveries, reported by the coordinator's
    resume event line), ``coordinator_incarnations``, the final
    ``exit_code``, and the flight ledger — ``flight_dumps`` (parseable
    black boxes found) and ``flight_missing`` (SIGKILLed pids that left
    no parseable dump; must be empty)."""
    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    kills = list(kills or [])
    for k in kills:
        if k.target.startswith("worker:"):
            wid = int(k.target.split(":", 1)[1])
            if not 0 <= wid < n_workers:
                raise ValueError(f"{k.target} out of range "
                                 f"[0, {n_workers})")
        elif k.target.startswith("aggregator:"):
            aid = int(k.target.split(":", 1)[1])
            if not 0 <= aid < n_aggregators:
                raise ValueError(f"{k.target} out of range "
                                 f"[0, {n_aggregators})")
    workdir = workdir or tempfile.mkdtemp(prefix="colearn_mpsoak_")
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, "ckpt")
    flight_dir = os.path.join(workdir, "flight")

    env = dict(os.environ)
    env["PYTHONUNBUFFERED"] = "1"      # round records must stream, not batch
    env["JAX_PLATFORMS"] = "cpu"

    fleet = _Fleet(workdir, env)
    # Hard wall-clock backstop: a hung federation (the exact bug class
    # this harness hunts) must fail the run, not the CI job's timeout.
    watchdog = threading.Timer(timeout_s, fleet.kill_all)
    watchdog.daemon = True

    records: dict[int, dict] = {}
    events: list[dict] = []
    per_client: dict = {}
    resumed = 0
    incarnations = 1
    delivered: list[dict] = []
    pending = sorted(kills, key=lambda k: (k.after_round, k.target))
    rc: Optional[int] = None

    try:
        watchdog.start()
        # Every process flies with the black box on a fast heartbeat: a
        # SIGKILL is uncatchable, so the per-kill dump the summary
        # asserts below IS the victim's last heartbeat rewrite.  The
        # broker carries it too — a broker KillSpec's pid must show up
        # in the flight ledger like any other victim's.
        flight_flags = ["--flight-dir", flight_dir,
                        "--flight-heartbeat", "0.5"]
        # Health flags ride on the federation roles only — the broker's
        # parser has no override flags, and the ledger is written by the
        # coordinator/aggregator planes anyway.
        health_flags = (["--health-dir", os.path.join(workdir, "health")]
                        if health else [])
        host, port = fleet.start_broker(timeout=30.0, extra=flight_flags)
        worker_cfg = (_config_flags(rounds, n_workers, seed)
                      + flight_flags + health_flags)
        for i in range(n_workers):
            fleet.start_worker(i, worker_cfg, host, port)
        # Aggregator tier (tree ingest): spawned between broker and
        # coordinator so the retained announcements are on the broker
        # before the root's enroll_aggregators() subscribes.
        agg_cfg = worker_cfg
        for a in range(n_aggregators):
            fleet.start_aggregator(a, agg_cfg, host, port)
        coord_cfg = (_config_flags(rounds, n_workers, seed,
                                   checkpoint_dir=ckpt_dir)
                     + flight_flags + health_flags)
        if n_aggregators:
            coord_cfg += ["--num-aggregators", str(n_aggregators)]

        def launch(resume: bool) -> subprocess.Popen:
            return fleet.start_coordinator(
                coord_cfg, host, port, n_workers, round_timeout,
                enroll_timeout, resume=resume)

        coord = launch(resume=False)
        restart_pending = False
        # Mirror the coordinator's stderr to a workdir log: the harness
        # parses JSON records off the stream, but a crash traceback is
        # NOT JSON and would otherwise vanish with the pipe.
        err_log = fleet._log_file("coordinator.err")
        while True:
            line = coord.stderr.readline()
            if line:
                err_log.write(line.encode())
                err_log.flush()
            if not line:
                coord.wait()
                if restart_pending:
                    restart_pending = False
                    incarnations += 1
                    coord = launch(resume=True)
                    continue
                rc = coord.returncode
                break
            doc = _parse_json(line.strip())
            if doc is None:
                continue
            if "event" in doc:
                events.append(doc)
                if doc["event"] == "resumed":
                    resumed += 1
                continue
            if "num_clients_evaluated" in doc:
                per_client = doc
                continue
            if "round" not in doc:
                continue
            r = int(doc["round"])
            records[r] = doc           # last record per round wins
            if log_fn is not None:
                log_fn(doc)
            while pending and pending[0].after_round <= r:
                spec = pending.pop(0)
                kill_rec = {**dataclasses.asdict(spec),
                            "fired_after_round": r}
                if spec.target == "coordinator":
                    kill_rec["pid"] = coord.pid
                    coord.send_signal(signal.SIGKILL)
                    restart_pending = True
                elif spec.target == "broker":
                    victim = fleet.broker
                    if victim is not None and victim.poll() is None:
                        kill_rec["pid"] = victim.pid
                        victim.send_signal(signal.SIGKILL)
                        victim.wait()
                    fleet.restart_broker()
                elif spec.target.startswith("aggregator:"):
                    aid = int(spec.target.split(":", 1)[1])
                    victim = fleet.aggregators.get(aid)
                    if victim is not None and victim.poll() is None:
                        kill_rec["pid"] = victim.pid
                        victim.send_signal(signal.SIGKILL)
                        victim.wait()
                    if spec.restart:
                        fleet.start_aggregator(aid, agg_cfg, host, port)
                else:
                    wid = int(spec.target.split(":", 1)[1])
                    victim = fleet.workers.get(wid)
                    if victim is not None and victim.poll() is None:
                        kill_rec["pid"] = victim.pid
                        victim.send_signal(signal.SIGKILL)
                        victim.wait()
                    if spec.restart:
                        fleet.start_worker(wid, worker_cfg, host, port)
                delivered.append(kill_rec)
    finally:
        watchdog.cancel()
        fleet.close()

    if rc is None:
        raise RuntimeError(
            f"coordinator never exited cleanly within {timeout_s}s "
            f"(records for rounds {sorted(records)})")

    # Flight-dump ledger: every SIGKILLed pid must have left a parseable
    # black box (the acceptance criterion the flight recorder exists
    # for).  A dump that exists but does not parse counts as missing —
    # the atomic-write contract says a dump either parses or is absent.
    from colearn_federated_learning_tpu.telemetry import flight as _flight

    dumps = _flight.load_flight_dumps(flight_dir)
    dumped_pids = {d.get("pid") for d in dumps if "error" not in d}
    flight_missing = sorted({k["pid"] for k in delivered if "pid" in k}
                            - dumped_pids)

    recs = [records[r] for r in sorted(records)]
    return {
        "rounds_run": len(recs),
        "records": recs,
        "completed_rounds": [r["round"] for r in recs
                             if r["completed"] > 0
                             and not r.get("skipped_quorum")],
        "skipped_rounds": [r["round"] for r in recs
                           if r.get("skipped_quorum")],
        "evicted": sorted({d for r in recs for d in r.get("evicted", [])}),
        "weighted_acc": per_client.get("weighted_acc"),
        "weighted_loss": per_client.get("weighted_loss"),
        "per_client_acc": per_client.get("per_client", {}),
        "rounds_resumed": resumed,
        "coordinator_incarnations": incarnations,
        "agg_failovers": sum(int(r.get("agg_failovers", 0)) for r in recs),
        "kills": delivered,
        "flight_dumps": len(dumped_pids),
        "flight_missing": flight_missing,
        "events": events,
        "exit_code": rc,
        "workdir": workdir,
    }


def _final_checkpoint_state(ckpt_dir: str):
    """Load the server state from the LATEST checkpoint under
    ``ckpt_dir`` without a target template (the harness has no model —
    the saved metadata carries the tree structure and dtypes)."""
    import orbax.checkpoint as ocp

    mgr = ocp.CheckpointManager(os.path.abspath(ckpt_dir))
    try:
        step = mgr.latest_step()
        if step is None:
            return None, None
        restored = mgr.restore(
            step, args=ocp.args.Composite(state=ocp.args.StandardRestore()))
        return restored["state"], step
    finally:
        mgr.close()


def _max_param_diff(state_a, state_b) -> float:
    """Max abs elementwise difference across two server-state pytrees
    (leaf-path aligned; a structure mismatch is itself a failure and
    surfaces as ``inf``)."""
    import jax
    import numpy as np

    la, ta = jax.tree_util.tree_flatten_with_path(state_a)
    lb, tb = jax.tree_util.tree_flatten_with_path(state_b)
    if ta != tb or [p for p, _ in la] != [p for p, _ in lb]:
        return float("inf")
    worst = 0.0
    for (_, a), (_, b) in zip(la, lb):
        a, b = np.asarray(a), np.asarray(b)
        if a.shape != b.shape:
            return float("inf")
        if a.size:
            worst = max(worst, float(np.max(np.abs(
                a.astype(np.float64) - b.astype(np.float64)))))
    return worst


def run_agg_soak(
    rounds: int = 4,
    n_workers: int = 3,
    workdir: Optional[str] = None,
    round_timeout: float = 120.0,
    enroll_timeout: float = 90.0,
    timeout_s: float = 600.0,
    kill: bool = True,
    seed: int = 0,
    tol: float = 2e-4,
    log_fn: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Aggregator-tree chaos gate: tree soak under a real aggregator
    SIGKILL, lockstep against a flat (no-tree) oracle.

    Two full subprocess federations with identical config and seed:

    - **tree** — 2 aggregator processes own the device slices; with
      ``kill=True`` aggregator 0 is SIGKILLed mid-round (and stays
      dead), so the root must re-home its slice onto aggregator 1 or
      quorum-drop it (``agg_failovers >= 1`` in the round records);
    - **oracle** — the same federation folding flat at the root, no
      kills.

    The gate then compares the FINAL checkpointed server state of both
    runs: re-homing must lose no contribution, so the tree run's params
    stay within ``tol`` of the oracle's (the slack covers fold-order
    float non-associativity between arrival-order flat folds and
    slice-blocked tree folds, same bound as the secure-soak gate).  The
    killed aggregator must also have left a parseable flight dump whose
    postmortem attributes the death to the aggregator role, and the tree
    run's ``--health-dir`` ledgers must survive the kill: parseable and
    non-empty (``health_ledger_ok``/``health_devices`` in the summary,
    the same files `colearn health <workdir>/tree/health` renders)."""
    workdir = workdir or tempfile.mkdtemp(prefix="colearn_aggsoak_")
    os.makedirs(workdir, exist_ok=True)
    kills = ([KillSpec("aggregator:0",
                       after_round=max(0, rounds // 2 - 1),
                       restart=False)]
             if kill else [])

    tree = run_proc_soak(
        rounds=rounds, n_workers=n_workers, kills=kills,
        workdir=os.path.join(workdir, "tree"),
        round_timeout=round_timeout, enroll_timeout=enroll_timeout,
        timeout_s=timeout_s, seed=seed, n_aggregators=2, health=True,
        log_fn=log_fn)
    # The oracle flies with the health plane too: the ledger's per-round
    # fsync shifts arrival timing, and the flat fold is arrival-order —
    # an asymmetric config costs an ulp of fold-order noise in the
    # param comparison for no reason.
    oracle = run_proc_soak(
        rounds=rounds, n_workers=n_workers, kills=[],
        workdir=os.path.join(workdir, "flat"),
        round_timeout=round_timeout, enroll_timeout=enroll_timeout,
        timeout_s=timeout_s, seed=seed, n_aggregators=0, health=True,
        log_fn=log_fn)

    state_t, step_t = _final_checkpoint_state(
        os.path.join(workdir, "tree", "ckpt"))
    state_o, step_o = _final_checkpoint_state(
        os.path.join(workdir, "flat", "ckpt"))
    if state_t is None or state_o is None or step_t != step_o:
        max_diff = float("inf")
    else:
        max_diff = _max_param_diff(state_t, state_o)
    oracle_ok = max_diff <= tol

    # Postmortem attribution: the killed aggregator's black box must be
    # in the tree run's flight ledger AND the merged report must name
    # the victim as an aggregator — the same artifact `colearn
    # postmortem --flight-dir <workdir>/tree/flight` shows an operator.
    from colearn_federated_learning_tpu.telemetry import flight as _flight

    killed_pids = {k["pid"] for k in tree["kills"] if "pid" in k}
    if killed_pids:
        dumps = _flight.load_flight_dumps(
            os.path.join(workdir, "tree", "flight"))
        report = _flight.postmortem_report(dumps)
        attributed = any(
            p.get("pid") in killed_pids
            and str(p.get("role", "")).startswith("aggregator")
            for p in report.get("processes", []))
    else:
        attributed = not kill

    # Health-ledger durability: every tree role flew with --health-dir,
    # and the fsync-per-flush WAL discipline means the SIGKILLed
    # aggregator's per-device records must still PARSE (a torn final
    # line is tolerated; mid-file corruption raises) and must not be
    # empty — straggler attribution that dies with its process is no
    # attribution at all.
    from colearn_federated_learning_tpu.telemetry import health as _health

    try:
        devices = _health.load_health(os.path.join(workdir, "tree",
                                                   "health"))
    except ValueError:
        devices = {}
    health_ok = bool(devices)

    return {
        "exit_code": tree["exit_code"],
        "oracle_exit_code": oracle["exit_code"],
        "rounds_run": tree["rounds_run"],
        "oracle_rounds_run": oracle["rounds_run"],
        "oracle_ok": oracle_ok,
        "max_param_diff": max_diff,
        "checkpoint_step": step_t,
        "agg_failovers": tree["agg_failovers"],
        "postmortem_attributed": attributed,
        "health_ledger_ok": health_ok,
        "health_devices": len(devices),
        "flight_missing": tree["flight_missing"],
        "kills": tree["kills"],
        "records": tree["records"],
        "workdir": workdir,
    }


def _async_config_flags(aggregations: int, n_workers: int, seed: int,
                        checkpoint_dir: Optional[str] = None) -> list[str]:
    """The async-soak federation: the sync soak's tiny CPU config plus a
    fixed-clip DP mechanism, so every aggregation record carries the
    realized ``dp_z_eff``/``dp_epsilon`` the replay gate re-derives.
    ``--evict-after`` is loosened vs the sync soak's 2: injected
    client-side flaps land as consecutive pump failures, and the gate
    wants them ATTRIBUTED (health ledger retries), not escalated into
    evictions of perfectly healthy workers.  The noise multiplier is
    deliberately tiny: the replay gate needs every aggregation CHARGED
    (any mechanism will do), while the loss-parity gate needs both runs
    to actually converge — production-grade noise on a 3-client toy
    federation swamps the clipped deltas and both trajectories
    diverge."""
    flags = _config_flags(aggregations, n_workers, seed,
                          checkpoint_dir=checkpoint_dir)
    flags += ["--evict-after", "4",
              "--dp-clip", "1.0",
              "--dp-noise-multiplier", str(_ASYNC_DP_NOISE),
              "--dp-delta", str(_ASYNC_DP_DELTA)]
    return flags


_ASYNC_DP_DELTA = 1e-5
_ASYNC_DP_NOISE = 0.02


def _async_fault_plan() -> dict:
    """Client-site transport faults for the FAULTED async run: the plan
    is installed in the coordinator process (``--fault-plan``), so these
    fire inside the dispatcher pumps' ``TensorClient.request`` calls —
    flaps surface as pump failures the health ledger must attribute as
    retries, delays stretch the per-device latency EWMA.  Count-bounded
    so the run still converges."""
    return {"seed": 0, "faults": [
        {"kind": "flap_reconnect", "device_id": "*", "op": "train",
         "count": 2, "site": "client"},
        {"kind": "delay", "device_id": "*", "op": "train",
         "ms": 150, "count": 3, "site": "client"},
    ]}


def _run_async_fleet(
    aggregations: int,
    n_workers: int,
    buffer_size: int,
    kills: list[KillSpec],
    workdir: str,
    round_timeout: float,
    enroll_timeout: float,
    timeout_s: float,
    seed: int,
    n_aggregators: int = 0,
    fault_plan: Optional[dict] = None,
    log_fn: Optional[Callable[[dict], None]] = None,
    lock_witness: bool = False,
) -> dict:
    """One buffered-async subprocess federation (broker + N workers +
    async coordinator), with the proc-soak kill loop re-keyed on
    AGGREGATION records: the async plane logs ``{"aggregation": i,
    "model_version": v, ...}`` lines instead of round records, and a
    ``KillSpec("async-coordinator", after_round=k)`` fires the moment
    aggregation ``k``'s record appears — mid-aggregation ``k + 1``,
    while dispatcher pumps are in flight.  Records are deduplicated by
    aggregation index (LAST wins: a resumed incarnation's re-run of an
    uncommitted aggregation replaces the lost one), and model-version
    monotonicity is checked per incarnation as the stream arrives."""
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, "ckpt")
    flight_dir = os.path.join(workdir, "flight")

    env = dict(os.environ)
    env["PYTHONUNBUFFERED"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    witness_dir = os.path.join(workdir, "lockwitness")
    if lock_witness:
        # Every fleet process runs its locks through
        # faults.lockwitness and dumps a per-pid report at exit; the
        # summary below aggregates them into a zero-inversion /
        # zero-unguarded gate.
        env["COLEARN_LOCK_WITNESS"] = "1"
        env["COLEARN_LOCK_WITNESS_DIR"] = witness_dir
    else:
        # An operator's ambient witness env must not leak into a soak
        # that did not ask for it (the overhead would skew timings).
        env.pop("COLEARN_LOCK_WITNESS", None)
        env.pop("COLEARN_LOCK_WITNESS_DIR", None)

    fleet = _Fleet(workdir, env)
    watchdog = threading.Timer(timeout_s, fleet.kill_all)
    watchdog.daemon = True

    records: dict[int, dict] = {}
    events: list[dict] = []
    resumed = 0
    incarnations = 1
    delivered: list[dict] = []
    pending = sorted(kills, key=lambda k: (k.after_round, k.target))
    version_monotonic = True
    last_version = -1
    rc: Optional[int] = None

    try:
        watchdog.start()
        flight_flags = ["--flight-dir", flight_dir,
                        "--flight-heartbeat", "0.5"]
        health_flags = ["--health-dir", os.path.join(workdir, "health")]
        host, port = fleet.start_broker(timeout=30.0, extra=flight_flags)
        worker_cfg = (_async_config_flags(aggregations, n_workers, seed)
                      + flight_flags + health_flags)
        if n_aggregators:
            # A per-slice buffer of 1-2 devices can never clear the
            # default distinct-contributor quorum (ceil(0.5 * workers))
            # at the root — partials ship per SLICE, not per cohort.
            # Last flag wins in argparse, so the override rides at the
            # end of both role configs.
            worker_cfg += ["--min-cohort-fraction", "0"]
        for i in range(n_workers):
            fleet.start_worker(i, worker_cfg, host, port)
        # Aggregator tier: spawned before the coordinator so the
        # retained announcements are on the broker before the async
        # root's enroll_aggregators() subscribes.
        agg_cfg = worker_cfg
        for a in range(n_aggregators):
            fleet.start_aggregator(a, agg_cfg, host, port)
        coord_cfg = (_async_config_flags(aggregations, n_workers, seed,
                                         checkpoint_dir=ckpt_dir)
                     + flight_flags + health_flags)
        if n_aggregators:
            # The 1s heartbeat deadline (default 5s) keeps failover
            # detection well inside the post-kill runway of a short
            # soak; the oracle gets the same value so the runs stay
            # config-identical.
            coord_cfg += ["--num-aggregators", str(n_aggregators),
                          "--min-cohort-fraction", "0",
                          "--agg-heartbeat-timeout", "1.0"]
        if fault_plan is not None:
            plan_path = os.path.join(workdir, "fault_plan.json")
            with open(plan_path, "w") as f:
                json.dump(fault_plan, f)
            coord_cfg += ["--fault-plan", plan_path]

        def launch(resume: bool) -> subprocess.Popen:
            return fleet.start_async_coordinator(
                coord_cfg, host, port, n_workers, round_timeout,
                enroll_timeout, buffer_size, resume=resume)

        coord = launch(resume=False)
        restart_pending = False
        err_log = fleet._log_file("coordinator.err")
        while True:
            line = coord.stderr.readline()
            if line:
                err_log.write(line.encode())
                err_log.flush()
            if not line:
                coord.wait()
                if restart_pending:
                    restart_pending = False
                    incarnations += 1
                    # A fresh incarnation resumes from its checkpointed
                    # version — which may sit BELOW the dead process's
                    # last streamed record (uncommitted aggregations are
                    # lost by design).  Monotonicity restarts with it.
                    last_version = -1
                    coord = launch(resume=True)
                    continue
                rc = coord.returncode
                break
            doc = _parse_json(line.strip())
            if doc is None:
                continue
            if "event" in doc:
                events.append(doc)
                if doc["event"] == "resumed":
                    resumed += 1
                continue
            if "aggregation" not in doc:
                continue
            agg = int(doc["aggregation"])
            v = doc.get("model_version")
            if v is not None:
                if int(v) <= last_version:
                    version_monotonic = False
                last_version = int(v)
            records[agg] = doc         # last record per aggregation wins
            if log_fn is not None:
                log_fn(doc)
            while pending and pending[0].after_round <= agg:
                spec = pending.pop(0)
                kill_rec = {**dataclasses.asdict(spec),
                            "fired_after_round": agg}
                if spec.target in ("coordinator", "async-coordinator"):
                    kill_rec["pid"] = coord.pid
                    coord.send_signal(signal.SIGKILL)
                    restart_pending = True
                elif spec.target == "broker":
                    victim = fleet.broker
                    if victim is not None and victim.poll() is None:
                        kill_rec["pid"] = victim.pid
                        victim.send_signal(signal.SIGKILL)
                        victim.wait()
                    fleet.restart_broker()
                elif spec.target.startswith("aggregator:"):
                    aid = int(spec.target.split(":", 1)[1])
                    victim = fleet.aggregators.get(aid)
                    if victim is not None and victim.poll() is None:
                        kill_rec["pid"] = victim.pid
                        victim.send_signal(signal.SIGKILL)
                        victim.wait()
                    if spec.restart:
                        fleet.start_aggregator(aid, agg_cfg, host, port)
                else:
                    wid = int(spec.target.split(":", 1)[1])
                    victim = fleet.workers.get(wid)
                    if victim is not None and victim.poll() is None:
                        kill_rec["pid"] = victim.pid
                        victim.send_signal(signal.SIGKILL)
                        victim.wait()
                    if spec.restart:
                        fleet.start_worker(wid, worker_cfg, host, port)
                delivered.append(kill_rec)
    finally:
        watchdog.cancel()
        fleet.close()

    if rc is None:
        raise RuntimeError(
            f"async coordinator never exited cleanly within {timeout_s}s "
            f"(records for aggregations {sorted(records)})")

    from colearn_federated_learning_tpu.telemetry import flight as _flight

    dumps = _flight.load_flight_dumps(flight_dir)
    dumped_pids = {d.get("pid") for d in dumps if "error" not in d}
    flight_missing = sorted({k["pid"] for k in delivered if "pid" in k}
                            - dumped_pids)

    recs = [records[a] for a in sorted(records)]
    return {
        "lock_witness": (_collect_lockwitness(witness_dir)
                         if lock_witness else {"enabled": False}),
        "aggregations_run": len(recs),
        "records": recs,
        "version_monotonic": version_monotonic,
        "resumed": resumed,
        "coordinator_incarnations": incarnations,
        "kills": delivered,
        "flight_dumps": len(dumped_pids),
        "flight_missing": flight_missing,
        "events": events,
        "exit_code": rc,
        "workdir": workdir,
    }


def _collect_lockwitness(witness_dir: str) -> dict:
    """Merge the fleet's per-pid ``lockwitness-*.json`` dumps into one
    gateable summary: report count, total inversions/unguarded (with the
    offending records inlined for the operator), and the acquire volume
    that vouches the witness actually saw traffic."""
    reports = []
    skipped = 0
    if os.path.isdir(witness_dir):
        for name in sorted(os.listdir(witness_dir)):
            if not (name.startswith("lockwitness-")
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(witness_dir, name)) as f:
                    reports.append(json.load(f))
            except (OSError, ValueError):
                # An unparseable dump (process died mid-write) is not a
                # witnessed bug; the skipped count exposes the gap.
                skipped += 1
    inversions = [inv for r in reports for inv in r.get("inversions", [])]
    unguarded = [u for r in reports for u in r.get("unguarded", [])]
    return {
        "enabled": True,
        "reports": len(reports),
        "reports_unparseable": skipped,
        "acquires": sum(int(r.get("acquires", 0)) for r in reports),
        "guarded_ops": sum(int(r.get("guarded_ops", 0)) for r in reports),
        "inversions": len(inversions),
        "unguarded": len(unguarded),
        "inversion_records": inversions,
        "unguarded_records": unguarded,
    }


def _tail_loss(records: list[dict], n: int = 3) -> float:
    """Mean train loss over the last ``n`` aggregations — buffered-async
    losses are thread-timing noisy aggregation to aggregation, so the
    gate compares smoothed tails, not single records."""
    import math as _math

    tail = [float(r["train_loss"]) for r in records
            if "train_loss" in r
            and _math.isfinite(float(r["train_loss"]))][-n:]
    return sum(tail) / len(tail) if tail else float("inf")


def run_async_soak(
    aggregations: int = 6,
    n_workers: int = 3,
    buffer_size: int = 2,
    workdir: Optional[str] = None,
    round_timeout: float = 120.0,
    enroll_timeout: float = 90.0,
    timeout_s: float = 600.0,
    kill: bool = True,
    seed: int = 0,
    loss_tol: float = 0.75,
    log_fn: Optional[Callable[[dict], None]] = None,
    lock_witness: bool = False,
) -> dict:
    """Buffered-async chaos gate: SIGKILL the async coordinator
    mid-aggregation, relaunch with ``--resume``, and hold the recovered
    run to the invariants a lost buffer must not break.

    Two full subprocess federations, identical config and seed:

    - **faulted** — the async coordinator is SIGKILLed the moment
      aggregation ``aggregations // 2 - 1``'s record streams (so the
      signal lands mid-aggregation with dispatcher pumps in flight,
      buffered updates unfolded, the version condition mid-notify), then
      relaunched with ``--resume``; a count-bounded client-site
      :class:`~.plan.FaultPlan` also rides on its dispatcher pumps;
    - **baseline** — the same federation, kill-free and fault-free.

    Gates (``colearn chaos --async``):

    - *version monotonicity* — within each coordinator incarnation the
      streamed ``model_version`` strictly increases; resume restarts
      from the checkpointed version and uncommitted aggregations are
      re-run, never replayed out of order;
    - *no RDP double-charge* — replaying each final aggregation record's
      ``dp_z_eff`` into a fresh accountant must land on the final
      record's ``dp_epsilon``: the resumed coordinator rebuilt its
      budget from the checkpointed history exactly once;
    - *loss parity* — the faulted run's tail train loss stays within
      ``loss_tol`` of the kill-free baseline's (async losses are
      thread-timing noisy; the tolerance covers scheduling, not
      divergence);
    - *attribution* — the SIGKILLed pid left a parseable flight dump
      whose postmortem names the coordinator role, the health ledgers
      survive the kill, and the injected pump faults show up as
      per-device retry counts in the ledger."""
    if aggregations < 4:
        raise ValueError(
            f"async soak needs >= 4 aggregations so the kill lands after "
            f"a committed checkpoint, got {aggregations}")
    workdir = workdir or tempfile.mkdtemp(prefix="colearn_asyncsoak_")
    os.makedirs(workdir, exist_ok=True)
    kills = ([KillSpec("async-coordinator",
                       after_round=max(1, aggregations // 2 - 1))]
             if kill else [])

    faulted = _run_async_fleet(
        aggregations=aggregations, n_workers=n_workers,
        buffer_size=buffer_size, kills=kills,
        workdir=os.path.join(workdir, "faulted"),
        round_timeout=round_timeout, enroll_timeout=enroll_timeout,
        timeout_s=timeout_s, seed=seed,
        fault_plan=_async_fault_plan() if kill else None, log_fn=log_fn,
        lock_witness=lock_witness)
    baseline = _run_async_fleet(
        aggregations=aggregations, n_workers=n_workers,
        buffer_size=buffer_size, kills=[],
        workdir=os.path.join(workdir, "baseline"),
        round_timeout=round_timeout, enroll_timeout=enroll_timeout,
        timeout_s=timeout_s, seed=seed, fault_plan=None, log_fn=log_fn,
        lock_witness=lock_witness)

    # RDP replay: the deduplicated record stream IS the final
    # coordinator's history (LAST record per aggregation wins, exactly
    # like the checkpointed history the resumed incarnation extended).
    # Re-deriving epsilon from the per-record realized multipliers must
    # land on the final record's figure — a double-charged resume (or a
    # restore that failed to reset) diverges here.
    from colearn_federated_learning_tpu.privacy.accountant import (
        RdpAccountant,
    )

    acct = RdpAccountant(noise_multiplier=_ASYNC_DP_NOISE,
                         sampling_rate=1.0, delta=_ASYNC_DP_DELTA)
    final_eps = None
    for rec in faulted["records"]:
        if "dp_z_eff" in rec:
            acct.step(1, sampling_rate=1.0,
                      noise_multiplier=float(rec["dp_z_eff"]))
        if "dp_epsilon" in rec:
            final_eps = float(rec["dp_epsilon"])
    replayed_eps = acct.epsilon()
    import math as _math

    dp_replay_ok = (final_eps is not None
                    and _math.isfinite(final_eps)
                    and _math.isfinite(replayed_eps)
                    and abs(replayed_eps - final_eps)
                    <= 1e-6 * max(1.0, abs(final_eps)))

    final_loss = _tail_loss(faulted["records"])
    baseline_loss = _tail_loss(baseline["records"])
    loss_gap = abs(final_loss - baseline_loss)
    loss_gap_ok = _math.isfinite(loss_gap) and loss_gap <= loss_tol

    # Postmortem attribution: the SIGKILLed async coordinator's black
    # box must parse and the merged report must name the coordinator
    # role for its pid.
    from colearn_federated_learning_tpu.telemetry import flight as _flight

    killed_pids = {k["pid"] for k in faulted["kills"] if "pid" in k}
    if killed_pids:
        dumps = _flight.load_flight_dumps(
            os.path.join(workdir, "faulted", "flight"))
        report = _flight.postmortem_report(dumps)
        attributed = any(
            p.get("pid") in killed_pids
            and str(p.get("role", "")) == "coordinator"
            for p in report.get("processes", []))
    else:
        attributed = not kill

    # Health-ledger durability + fault attribution: the ledgers must
    # survive the SIGKILL (parse, non-empty), and with the fault plan
    # armed at least one device must carry attributed retries — the
    # injected pump flaps landed in the per-device ledger, not just a
    # process-local counter that died with its incarnation.
    from colearn_federated_learning_tpu.telemetry import health as _health

    try:
        devices = _health.load_health(
            os.path.join(workdir, "faulted", "health"))
    except ValueError:
        devices = {}
    health_ok = bool(devices)
    fault_retries = sum(int(h.counts.get("retry", 0))
                        for h in devices.values())
    faults_attributed = (not kill) or fault_retries >= 1

    return {
        "exit_code": faulted["exit_code"],
        "baseline_exit_code": baseline["exit_code"],
        "aggregations_run": faulted["aggregations_run"],
        "baseline_aggregations_run": baseline["aggregations_run"],
        "version_monotonic": (faulted["version_monotonic"]
                              and baseline["version_monotonic"]),
        "resumed": faulted["resumed"],
        "coordinator_incarnations": faulted["coordinator_incarnations"],
        "dp_replay_ok": dp_replay_ok,
        "dp_epsilon": final_eps,
        "dp_epsilon_replayed": replayed_eps,
        "final_loss": final_loss,
        "baseline_final_loss": baseline_loss,
        "loss_gap": loss_gap,
        "loss_gap_ok": loss_gap_ok,
        "postmortem_attributed": attributed,
        "health_ledger_ok": health_ok,
        "health_devices": len(devices),
        "fault_retries": fault_retries,
        "faults_attributed": faults_attributed,
        "flight_missing": faulted["flight_missing"],
        "kills": faulted["kills"],
        "records": faulted["records"],
        "lock_witness": _merge_lockwitness(faulted["lock_witness"],
                                           baseline["lock_witness"]),
        "workdir": workdir,
    }


def _merge_lockwitness(*parts: dict) -> dict:
    """Fold the per-fleet witness summaries (faulted + baseline/oracle)
    into the one entry the chaos gate reads."""
    if not any(p.get("enabled") for p in parts):
        return {"enabled": False}
    merged = {"enabled": True, "reports": 0, "acquires": 0,
              "guarded_ops": 0, "inversions": 0, "unguarded": 0,
              "inversion_records": [], "unguarded_records": []}
    for p in parts:
        if not p.get("enabled"):
            continue
        for k in ("reports", "acquires", "guarded_ops",
                  "inversions", "unguarded"):
            merged[k] += int(p.get(k, 0))
        merged["inversion_records"] += list(p.get("inversion_records", []))
        merged["unguarded_records"] += list(p.get("unguarded_records", []))
    return merged


def run_tree_async_soak(
    aggregations: int = 6,
    n_workers: int = 3,
    buffer_size: int = 2,
    workdir: Optional[str] = None,
    round_timeout: float = 120.0,
    enroll_timeout: float = 90.0,
    timeout_s: float = 900.0,
    kill: bool = True,
    seed: int = 0,
    loss_tol: float = 0.75,
    log_fn: Optional[Callable[[dict], None]] = None,
    lock_witness: bool = False,
) -> dict:
    """Tree-async chaos gate: buffered-async THROUGH the aggregator
    tree, with an aggregator SIGKILLed mid-aggregation (and left dead)
    plus a broker kill-and-rebind one aggregation later.

    Two full subprocess federations, identical config and seed, both
    running buffered-async through 2 per-slice aggregator buffers:

    - **faulted** — aggregator 0 dies the moment aggregation
      ``aggregations // 2 - 1``'s record streams (mid-aggregation:
      dispatcher pumps in flight, its buffer part-staged) and STAYS
      dead — the root must sticky-dead its address and re-home the
      in-flight contributions of its slice onto aggregator 1 without
      folding any of them twice; one aggregation later the broker is
      SIGKILLed and rebinds its original port (worker re-enrollment
      watchdogs + the root's announcement re-subscribe must heal);
    - **oracle** — the same tree federation, kill-free.

    Gates (``colearn chaos --tree-async``):

    - *loss parity* — the faulted run's tail train loss stays within
      ``loss_tol`` of the kill-free tree oracle's;
    - *zero double-folds* — every dedup key in the record stream's
      ``folded_keys`` lists is globally unique across the run: a
      re-homed contribution folded exactly once, on exactly one
      aggregator (``double_folds`` must be 0);
    - *failover fired* — summed ``agg_failovers`` >= 1 with ``kill``;
    - *re-home attribution* — every device named in a record's
      ``rehomed_devices`` carries ``rehomed >= 1`` in the health
      ledger: the ledger tells the operator WHO rode through the
      failover, not just that one happened;
    - *version monotonicity*, flight-dump coverage of every SIGKILLed
      pid, postmortem attribution of the dead aggregator, and
      health-ledger durability, as in the flat async soak."""
    if aggregations < 4:
        raise ValueError(
            f"tree-async soak needs >= 4 aggregations so the kills land "
            f"inside the run, got {aggregations}")
    workdir = workdir or tempfile.mkdtemp(prefix="colearn_treeasync_")
    os.makedirs(workdir, exist_ok=True)
    # Kill EARLY (after ~a third of the run) so the post-kill runway is
    # long enough for bounded-deadline detection to fire, the in-flight
    # slice-0 contributions to re-home, and the re-homed partials to
    # fold into later records — all before the root hits its target.
    cut = max(1, aggregations // 3)
    kills = ([KillSpec("aggregator:0", after_round=cut, restart=False),
              KillSpec("broker", after_round=min(cut + 2,
                                                 aggregations - 1))]
             if kill else [])

    faulted = _run_async_fleet(
        aggregations=aggregations, n_workers=n_workers,
        buffer_size=buffer_size, kills=kills,
        workdir=os.path.join(workdir, "faulted"),
        round_timeout=round_timeout, enroll_timeout=enroll_timeout,
        timeout_s=timeout_s, seed=seed, n_aggregators=2,
        fault_plan=None, log_fn=log_fn, lock_witness=lock_witness)
    oracle = _run_async_fleet(
        aggregations=aggregations, n_workers=n_workers,
        buffer_size=buffer_size, kills=[],
        workdir=os.path.join(workdir, "oracle"),
        round_timeout=round_timeout, enroll_timeout=enroll_timeout,
        timeout_s=timeout_s, seed=seed, n_aggregators=2,
        fault_plan=None, log_fn=log_fn, lock_witness=lock_witness)

    import math as _math

    final_loss = _tail_loss(faulted["records"])
    oracle_loss = _tail_loss(oracle["records"])
    loss_gap = abs(final_loss - oracle_loss)
    loss_gap_ok = _math.isfinite(loss_gap) and loss_gap <= loss_tol

    # Double-fold audit: each aggregation record carries the dedup keys
    # (``version@device``) its folded partial was built from.  A key
    # appearing in two records means one contribution reached the model
    # twice — the exact failure mode re-home-with-ack-on-receipt
    # exists to prevent.  Records are deduplicated by aggregation index
    # (LAST wins), so a resumed re-run never false-positives here.
    seen_keys: set = set()
    double_folds = 0
    for rec in faulted["records"]:
        for key in rec.get("folded_keys", []):
            if key in seen_keys:
                double_folds += 1
            seen_keys.add(key)

    agg_failovers = sum(int(r.get("agg_failovers", 0))
                        for r in faulted["records"])
    failover_fired = (not kill) or agg_failovers >= 1
    rehomed_devices = sorted({str(d) for r in faulted["records"]
                              for d in r.get("rehomed_devices", [])})

    # Postmortem: the dead aggregator's black box must parse and the
    # merged report must name the aggregator role for its pid.
    from colearn_federated_learning_tpu.telemetry import flight as _flight

    killed_pids = {k["pid"] for k in faulted["kills"] if "pid" in k}
    if killed_pids:
        dumps = _flight.load_flight_dumps(
            os.path.join(workdir, "faulted", "flight"))
        report = _flight.postmortem_report(dumps)
        agg_attributed = any(
            p.get("pid") in killed_pids
            and str(p.get("role", "")).startswith("aggregator")
            for p in report.get("processes", []))
    else:
        agg_attributed = not kill

    # Health-ledger attribution of the re-home: durability first (the
    # ledgers must parse and be non-empty), then the re-home trail —
    # every device the record stream says was re-homed must carry a
    # ``rehomed`` count in the merged ledger.
    from colearn_federated_learning_tpu.telemetry import health as _health

    try:
        devices = _health.load_health(
            os.path.join(workdir, "faulted", "health"))
    except ValueError:
        devices = {}
    health_ok = bool(devices)
    ledger_rehomed = {d for d, h in devices.items()
                     if int(h.counts.get("rehomed", 0)) >= 1}
    rehomed_attributed = ((not kill) or
                          (bool(rehomed_devices)
                           and set(rehomed_devices) <= ledger_rehomed))

    return {
        "exit_code": faulted["exit_code"],
        "oracle_exit_code": oracle["exit_code"],
        "aggregations_run": faulted["aggregations_run"],
        "oracle_aggregations_run": oracle["aggregations_run"],
        "version_monotonic": (faulted["version_monotonic"]
                              and oracle["version_monotonic"]),
        "final_loss": final_loss,
        "oracle_final_loss": oracle_loss,
        "loss_gap": loss_gap,
        "loss_gap_ok": loss_gap_ok,
        "double_folds": double_folds,
        "folded_keys_total": len(seen_keys),
        "agg_failovers": agg_failovers,
        "failover_fired": failover_fired,
        "rehomed_devices": rehomed_devices,
        "rehomed_attributed": rehomed_attributed,
        "postmortem_attributed": agg_attributed,
        "health_ledger_ok": health_ok,
        "health_devices": len(devices),
        "flight_missing": faulted["flight_missing"],
        "kills": faulted["kills"],
        "records": faulted["records"],
        "lock_witness": _merge_lockwitness(faulted["lock_witness"],
                                           oracle["lock_witness"]),
        "workdir": workdir,
    }

# --------------------------------------------------- streaming-ckpt soak --

def _ckpt_fault_plan(slow_ms: int) -> dict:
    """``slow_io`` on every per-shard checkpoint write: each shard file
    costs an extra ``slow_ms`` before its bytes land, stretching the
    window between the first shard commit and the manifest commit so the
    save watcher's SIGKILL deterministically lands INSIDE a save."""
    return {"seed": 0, "faults": [
        {"kind": "slow_io", "device_id": "*", "round": -1, "op": "shard",
         "ms": slow_ms, "count": 0, "site": "server", "hop": "shard"},
    ]}


def _ckpt_gen_entries(ckpt_dir: str) -> list[str]:
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return []
    return sorted(os.path.join(ckpt_dir, n) for n in names
                  if n.startswith("gen_"))


def _ckpt_has_committed(ckpt_dir: str) -> bool:
    return any(os.path.exists(os.path.join(g, "manifest.json"))
               for g in _ckpt_gen_entries(ckpt_dir))


def _ckpt_in_progress(ckpt_dir: str) -> Optional[str]:
    """The newest generation directory that has shard files on disk but
    no manifest — a save in flight (or a dead one the next restore will
    fall through)."""
    for g in reversed(_ckpt_gen_entries(ckpt_dir)):
        if os.path.exists(os.path.join(g, "manifest.json")):
            continue
        try:
            names = os.listdir(g)
        except OSError:  # colearn: noqa(CL003): poll race — the dir the
            continue     # coordinator is pruning mid-scan simply isn't
                         # an in-progress save; the watcher re-polls.
        if any(n.startswith("shard_") and n.endswith(".npz")
               for n in names):
            return g
    return None


def _run_ckpt_fleet(
    rounds: int,
    n_workers: int,
    workdir: str,
    round_timeout: float,
    enroll_timeout: float,
    timeout_s: float,
    seed: int,
    tp_size: int,
    resume_tp_size: int,
    kill_during_save: bool,
    fault_plan: Optional[dict] = None,
    start_resumed: bool = False,
    ckpt_dir: Optional[str] = None,
    log_fn: Optional[Callable[[dict], None]] = None,
) -> dict:
    """One streaming-checkpoint fleet (broker + N workers + sync
    coordinator with ``--ckpt-stream``).  Unlike the round-keyed kill
    loop, the kill here is FILESYSTEM-keyed: with ``kill_during_save`` a
    watcher thread polls the checkpoint directory and SIGKILLs the
    coordinator the moment a generation has shard files on disk but no
    manifest — i.e. mid-save, after at least one earlier generation
    committed (so the resume has something to fall back to).  Right
    after the kill the watcher snapshots the last COMMITTED generation
    (step + content digest) via
    :func:`~..ckpt.streaming.load_generation_host`; the relaunched
    ``--resume`` coordinator (at ``resume_tp_size``) must restore
    exactly that.  ``start_resumed`` launches the FIRST coordinator with
    ``--resume`` against an existing ``ckpt_dir`` — the kill-free
    cross-tp smoke leg."""
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = ckpt_dir or os.path.join(workdir, "ckpt")
    flight_dir = os.path.join(workdir, "flight")

    env = dict(os.environ)
    env["PYTHONUNBUFFERED"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    # The coordinator's sharded-server placement needs >= tp_size XLA
    # host devices; match the test suite's 8-device CPU layout (workers
    # ignore the extra devices).
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()

    fleet = _Fleet(workdir, env)
    watchdog = threading.Timer(timeout_s, fleet.kill_all)
    watchdog.daemon = True

    records: dict[int, dict] = {}
    events: list[dict] = []
    per_client: dict = {}
    resumed = 0
    incarnations = 1
    resume_event: Optional[dict] = None
    rc: Optional[int] = None
    holder: dict = {"coord": None, "restart_pending": False, "stop": False}
    killed: dict = {}

    def watch() -> None:
        from colearn_federated_learning_tpu.ckpt.streaming import (
            load_generation_host,
        )

        # Arm only once a generation has COMMITTED: a kill during the
        # very first save would leave nothing to fall back to, and the
        # gate is "lose at most the uncommitted generation", not "lose
        # the run".
        while not holder["stop"] and not _ckpt_has_committed(ckpt_dir):
            time.sleep(0.02)
        prog = None
        while not holder["stop"]:
            prog = _ckpt_in_progress(ckpt_dir)
            if prog:
                break
            time.sleep(0.01)
        coord = holder["coord"]
        if holder["stop"] or coord is None or prog is None:
            return
        holder["restart_pending"] = True
        killed["pid"] = coord.pid
        killed["gen"] = os.path.basename(prog)
        coord.send_signal(signal.SIGKILL)
        coord.wait()
        # The process is dead and the resume incarnation is seconds
        # away, so the directory is frozen: record what the next
        # restore MUST come back with.
        killed["mid_save"] = not os.path.exists(
            os.path.join(prog, "manifest.json"))
        try:
            _, step, digest = load_generation_host(ckpt_dir)
            killed["committed_step"] = step
            killed["digest"] = digest
        except FileNotFoundError:
            killed["committed_step"] = None
            killed["digest"] = None

    watcher = (threading.Thread(target=watch, daemon=True)
               if kill_during_save else None)

    try:
        watchdog.start()
        flight_flags = ["--flight-dir", flight_dir,
                        "--flight-heartbeat", "0.5"]
        host, port = fleet.start_broker(timeout=30.0, extra=flight_flags)
        worker_cfg = _config_flags(rounds, n_workers, seed) + flight_flags
        for i in range(n_workers):
            fleet.start_worker(i, worker_cfg, host, port)
        coord_cfg = (_config_flags(rounds, n_workers, seed,
                                   checkpoint_dir=ckpt_dir)
                     + ["--ckpt-stream"] + flight_flags)
        if fault_plan is not None:
            plan_path = os.path.join(workdir, "fault_plan.json")
            with open(plan_path, "w") as f:
                json.dump(fault_plan, f)
            coord_cfg += ["--fault-plan", plan_path]

        def launch(resume: bool) -> subprocess.Popen:
            tp = resume_tp_size if resume else tp_size
            c = fleet.start_coordinator(
                coord_cfg + ["--tp-size", str(tp)], host, port, n_workers,
                round_timeout, enroll_timeout, resume=resume)
            holder["coord"] = c
            return c

        coord = launch(resume=start_resumed)
        if watcher is not None:
            watcher.start()
        err_log = fleet._log_file("coordinator.err")
        while True:
            line = coord.stderr.readline()
            if line:
                err_log.write(line.encode())
                err_log.flush()
            if not line:
                coord.wait()
                if holder["restart_pending"]:
                    holder["restart_pending"] = False
                    incarnations += 1
                    coord = launch(resume=True)
                    continue
                rc = coord.returncode
                break
            doc = _parse_json(line.strip())
            if doc is None:
                continue
            if "event" in doc:
                events.append(doc)
                if doc["event"] == "resumed":
                    resumed += 1
                    resume_event = doc
                continue
            if "num_clients_evaluated" in doc:
                per_client = doc
                continue
            if "round" not in doc:
                continue
            records[int(doc["round"])] = doc
            if log_fn is not None:
                log_fn(doc)
    finally:
        holder["stop"] = True
        watchdog.cancel()
        fleet.close()
        if watcher is not None and watcher.is_alive():
            watcher.join(timeout=5.0)

    if rc is None:
        raise RuntimeError(
            f"coordinator never exited cleanly within {timeout_s}s "
            f"(records for rounds {sorted(records)})")

    from colearn_federated_learning_tpu.telemetry import flight as _flight

    dumps = _flight.load_flight_dumps(flight_dir)
    dumped_pids = {d.get("pid") for d in dumps if "error" not in d}
    flight_missing = sorted(({killed["pid"]} if "pid" in killed else set())
                            - dumped_pids)

    recs = [records[r] for r in sorted(records)]
    return {
        "rounds_run": len(recs),
        "records": recs,
        "weighted_acc": per_client.get("weighted_acc"),
        "resumed": resumed,
        "resume_event": resume_event,
        "coordinator_incarnations": incarnations,
        "kill": killed,
        "flight_dumps": len(dumped_pids),
        "flight_missing": flight_missing,
        "events": events,
        "exit_code": rc,
        "ckpt_dir": ckpt_dir,
        "workdir": workdir,
    }


def run_ckpt_soak(
    rounds: int = 4,
    n_workers: int = 2,
    workdir: Optional[str] = None,
    round_timeout: float = 120.0,
    enroll_timeout: float = 90.0,
    timeout_s: float = 600.0,
    kill: bool = True,
    seed: int = 0,
    loss_tol: float = 0.75,
    tp_size: int = 2,
    resume_tp_size: int = 1,
    slow_ms: int = 300,
    log_fn: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Streaming-checkpoint chaos gate (``colearn chaos --ckpt``).

    **Kill leg** (``kill=True``): a tp=``tp_size`` federation saves a
    shard-native streaming checkpoint every round under an injected
    ``slow_io`` plan; a filesystem watcher SIGKILLs the coordinator the
    moment a save is mid-flight (shard files on disk, manifest not yet
    committed) AFTER at least one generation committed.  The relaunched
    ``--resume`` coordinator comes back at tp=``resume_tp_size`` — the
    cross-tp re-shard leg — and the gate holds:

    - *atomicity* — the kill landed mid-save (``killed_mid_save``) and
      the resume restored exactly the last COMMITTED generation: the
      resumed round equals the step the watcher snapshotted at kill
      time, i.e. at most the one uncommitted generation was lost;
    - *bitwise restore* — the resume event's ``ckpt_digest`` (sha256
      over the restored full-leaf bytes in flatten order) equals the
      digest :func:`~..ckpt.streaming.load_generation_host` computed
      from the on-disk generation at kill time, across the tp change
      (``resharded >= 1`` when ``resume_tp_size != tp_size``);
    - *loss parity* — tail train loss within ``loss_tol`` of a same-seed
      kill-free tp=``resume_tp_size`` oracle federation;
    - *attribution* — the SIGKILLed pid left a parseable flight dump
      whose postmortem names the coordinator role.

    **Smoke leg** (``kill=False``): a kill-free tp=``tp_size`` run to
    completion, then a fresh fleet resumes the SAME checkpoint directory
    at tp=``resume_tp_size`` with zero rounds left — the resume event's
    digest must match the harness's independent
    ``load_generation_host`` digest of the final generation, bitwise,
    across the re-shard."""
    if rounds < 3:
        raise ValueError(
            f"ckpt soak needs >= 3 rounds so the mid-save kill lands "
            f"after a committed generation, got {rounds}")
    workdir = workdir or tempfile.mkdtemp(prefix="colearn_ckptsoak_")
    os.makedirs(workdir, exist_ok=True)
    reshard = tp_size != resume_tp_size

    if not kill:
        first = _run_ckpt_fleet(
            rounds, n_workers, os.path.join(workdir, "save"),
            round_timeout, enroll_timeout, timeout_s, seed,
            tp_size=tp_size, resume_tp_size=tp_size,
            kill_during_save=False, log_fn=log_fn)
        from colearn_federated_learning_tpu.ckpt.streaming import (
            load_generation_host,
        )

        _, step, digest = load_generation_host(first["ckpt_dir"])
        second = _run_ckpt_fleet(
            rounds, n_workers, os.path.join(workdir, "resume"),
            round_timeout, enroll_timeout, timeout_s, seed,
            tp_size=resume_tp_size, resume_tp_size=resume_tp_size,
            kill_during_save=False, start_resumed=True,
            ckpt_dir=first["ckpt_dir"], log_fn=log_fn)
        ev = second["resume_event"] or {}
        return {
            "mode": "smoke",
            "exit_code": first["exit_code"],
            "resume_exit_code": second["exit_code"],
            "rounds_run": first["rounds_run"],
            "committed_step": step,
            "save_digest": digest,
            "resume_digest": ev.get("ckpt_digest"),
            "resume_round": ev.get("round"),
            "resume_round_ok": ev.get("round") == step,
            "digest_ok": (digest is not None
                          and ev.get("ckpt_digest") == digest),
            "resharded_resumes": int(ev.get("resharded", 0) or 0),
            "reshard_ok": ((not reshard)
                           or int(ev.get("resharded", 0) or 0) >= 1),
            "records": first["records"],
            "workdir": workdir,
        }

    faulted = _run_ckpt_fleet(
        rounds, n_workers, os.path.join(workdir, "faulted"),
        round_timeout, enroll_timeout, timeout_s, seed,
        tp_size=tp_size, resume_tp_size=resume_tp_size,
        kill_during_save=True, fault_plan=_ckpt_fault_plan(slow_ms),
        log_fn=log_fn)
    oracle = _run_ckpt_fleet(
        rounds, n_workers, os.path.join(workdir, "oracle"),
        round_timeout, enroll_timeout, timeout_s, seed,
        tp_size=resume_tp_size, resume_tp_size=resume_tp_size,
        kill_during_save=False, log_fn=log_fn)

    import math as _math

    ev = faulted["resume_event"] or {}
    killed = faulted["kill"]
    committed = killed.get("committed_step")
    final_loss = _tail_loss(faulted["records"])
    oracle_loss = _tail_loss(oracle["records"])
    loss_gap = abs(final_loss - oracle_loss)

    from colearn_federated_learning_tpu.telemetry import flight as _flight

    attributed = False
    if "pid" in killed:
        dumps = _flight.load_flight_dumps(
            os.path.join(workdir, "faulted", "flight"))
        report = _flight.postmortem_report(dumps)
        attributed = any(
            p.get("pid") == killed["pid"]
            and str(p.get("role", "")) == "coordinator"
            for p in report.get("processes", []))

    return {
        "mode": "kill",
        "exit_code": faulted["exit_code"],
        "oracle_exit_code": oracle["exit_code"],
        "rounds_run": faulted["rounds_run"],
        "oracle_rounds_run": oracle["rounds_run"],
        "killed_mid_save": bool(killed.get("mid_save")),
        "killed_gen": killed.get("gen"),
        "committed_step": committed,
        "kill_digest": killed.get("digest"),
        "resume_digest": ev.get("ckpt_digest"),
        "resume_round": ev.get("round"),
        "resume_round_ok": (committed is not None
                            and ev.get("round") == committed),
        "digest_ok": (killed.get("digest") is not None
                      and ev.get("ckpt_digest") == killed["digest"]),
        "resharded_resumes": int(ev.get("resharded", 0) or 0),
        "reshard_ok": ((not reshard)
                       or int(ev.get("resharded", 0) or 0) >= 1),
        "resumed": faulted["resumed"],
        "coordinator_incarnations": faulted["coordinator_incarnations"],
        "final_loss": final_loss,
        "oracle_final_loss": oracle_loss,
        "loss_gap": loss_gap,
        "loss_gap_ok": _math.isfinite(loss_gap) and loss_gap <= loss_tol,
        "postmortem_attributed": attributed,
        "flight_missing": faulted["flight_missing"],
        "kill": killed,
        "records": faulted["records"],
        "workdir": workdir,
    }
