"""Chaos soak: an in-process federation run under a fault plan.

One broker, ``n_workers`` DeviceWorkers and a FederatedCoordinator run in
this process; after a fault-free warmup round (the first train request
compiles each worker's jit program — a plan must perturb steady-state
rounds, not compile time) the plan is installed and the remaining rounds
run against injected drops, delays, corrupt frames and crashes.  The
returned summary carries every round record plus the telemetry counter
deltas, so a caller (scripts/chaos_soak.py, tests/test_chaos_soak.py) can
assert the robustness machinery held rather than eyeball a log.
"""

from __future__ import annotations

from typing import Callable, Optional

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.faults import inject
from colearn_federated_learning_tpu.faults.plan import FaultPlan, FaultSpec
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

# Counters whose soak-window delta the summary reports — declared once in
# the metric catalog so this gate and CL005 can never drift apart.
from colearn_federated_learning_tpu.analysis.metric_catalog import (
    SECURE_SOAK_DELTA_COUNTERS as _SECURE_COUNTERS,
    SOAK_DELTA_COUNTERS as _COUNTERS,
)


def default_soak_config(n_workers: int = 4, seed: int = 0,
                        min_cohort_fraction: float = 0.5,
                        evict_after: int = 2,
                        comm_retries: int = 2) -> ExperimentConfig:
    """Tiny CPU federation with the robustness features ON: quorum at
    half the cohort, eviction after 2 straight failures, 2 retries.

    Plain SGD (no momentum) at a calm lr: the verdict compares final
    accuracy between a faulted and a fault-free run, so the optimizer
    must converge monotonically — with momentum 0.9 at lr 0.1 the
    trajectory oscillates and "fewer updates" can land on a BETTER
    point, inverting the comparison."""
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=n_workers,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=2),
        fed=FedConfig(strategy="fedavg", rounds=10, cohort_size=0,
                      local_steps=4, batch_size=16, lr=0.05, momentum=0.0,
                      min_cohort_fraction=min_cohort_fraction),
        run=RunConfig(name="chaos_soak", backend="cpu", seed=seed,
                      evict_after=evict_after, comm_retries=comm_retries),
    )


def canned_plan(seed: int = 7) -> FaultPlan:
    """The acceptance-criteria plan against the default 4-worker soak
    (rounds are post-warmup: warmup is round 0, faults start at 1):

    - round 1: a delayed and a twice-flapped trainer — both recover
      within the round via the retry path;
    - round 2: three parallel request drops — only one survivor, below
      the 50% quorum, so the round must be an explicit no-op;
    - round 3: one corrupt reply frame — CRC failure, retried, recovered;
    - round 4: one mid-run worker crash — the device drops this round and
      every later one, and is evicted after ``evict_after`` failures.

    Rounds 5+ are fault-free: the surviving cohort gets a recovery tail
    long enough for final accuracy to re-converge toward the baseline's.
    """
    return FaultPlan([
        FaultSpec(kind="delay", device_id="0", round=1, op="train", ms=150),
        FaultSpec(kind="flap_reconnect", device_id="1", round=1, op="train",
                  count=2),
        FaultSpec(kind="drop_request", device_id="0", round=2, op="train"),
        FaultSpec(kind="drop_request", device_id="1", round=2, op="train"),
        FaultSpec(kind="drop_request", device_id="2", round=2, op="train"),
        FaultSpec(kind="corrupt_payload", device_id="1", round=3,
                  op="train"),
        FaultSpec(kind="crash_worker", device_id="3", round=4, op="train"),
    ], seed=seed)


def run_soak(rounds: int = 10, n_workers: int = 4,
             plan: Optional[FaultPlan] = None,
             round_timeout: float = 6.0,
             warmup_timeout: float = 120.0,
             config: Optional[ExperimentConfig] = None,
             log_fn: Optional[Callable[[dict], None]] = None) -> dict:
    """Run ``rounds`` federated rounds (1 fault-free warmup + the rest
    under ``plan``) and return a summary dict: ``records`` (every round
    record, in order), ``skipped_rounds``, ``evicted``, per-counter
    deltas under ``counters``, the plan's ``faults_fired`` ledger, and a
    fault-free final ``weighted_acc``/``weighted_loss`` over the
    surviving trainers' own shards."""
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker

    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    config = config or default_soak_config(n_workers)
    reg = telemetry.get_registry()
    # Iterates a catalog-declared tuple (SOAK_DELTA_COUNTERS): every name
    # is validated at declaration, so the non-literal lookup is safe.
    before = {name: reg.counter(name).value  # colearn: noqa(CL005): names from the catalog-declared counter tuple
              for name in _COUNTERS}
    _LABELED = "fault.injected_total{"
    labeled_before = {k: v for k, v in reg.snapshot().items()
                      if k.startswith(_LABELED)}

    broker = MessageBroker().start()
    workers = []
    coord = None
    installed = False
    try:
        workers = [
            DeviceWorker(config, i, broker.host, broker.port).start()
            for i in range(n_workers)
        ]
        coord = FederatedCoordinator(config, broker.host, broker.port,
                                     round_timeout=warmup_timeout,
                                     want_evaluator=False)
        coord.enroll(min_devices=n_workers, timeout=30.0)
        # Announcement arrival order is a thread race; aggregation folds
        # in trainer order, so sort for run-to-run byte-identical records.
        coord.trainers.sort(key=lambda d: int(d.device_id))
        for w in workers:
            w.await_role(timeout=10.0)

        rec = coord.run_round()                      # warmup (round 0)
        if log_fn is not None:
            log_fn(rec)
        coord.round_timeout = round_timeout
        if plan is not None:
            inject.install(plan)
            installed = True
        for _ in range(rounds - 1):
            rec = coord.run_round()
            if log_fn is not None:
                log_fn(rec)
        if installed:
            inject.uninstall()
            installed = False
        # Scored AFTER uninstall: the verdict metric must measure what
        # the faults did to the MODEL, not be corrupted by them.  Back on
        # the generous deadline — the first self_eval compiles.
        coord.round_timeout = warmup_timeout
        per_client = coord.evaluate_per_client()
    finally:
        if installed:
            inject.uninstall()
        for w in workers:
            w.stop()
        broker.stop()
        if coord is not None:
            coord.close()

    records = list(coord.history)
    return {
        "rounds_run": len(records),
        "records": records,
        "completed_rounds": [r["round"] for r in records
                             if r["completed"] > 0
                             and not r.get("skipped_quorum")],
        "skipped_rounds": [r["round"] for r in records
                           if r.get("skipped_quorum")],
        "evicted": sorted({d for r in records for d in r["evicted"]}),
        "weighted_acc": per_client.get("weighted_acc"),
        "weighted_loss": per_client.get("weighted_loss"),
        # device_id -> final own-shard accuracy.  Verdicts that compare a
        # faulted run against a baseline must intersect on the devices
        # BOTH runs still have (eviction shrinks the faulted eval set).
        "per_client_acc": per_client.get("per_client", {}),
        "counters": {
            # Same catalog-declared tuple as `before` above.
            name: reg.counter(name).value - before[name]  # colearn: noqa(CL005): names from the catalog-declared counter tuple
            for name in _COUNTERS
        },
        # Per-(device, kind) injection deltas, worst offender first — the
        # device/kind labels the injector attaches to fault.injected_total.
        "top_faults": sorted(
            ({"label": k[len(_LABELED) - 1:], "count": delta}
             for k, v in reg.snapshot().items()
             if k.startswith(_LABELED)
             and (delta := v - labeled_before.get(k, 0)) > 0),
            key=lambda t: (-t["count"], t["label"])),
        "faults_fired": dict(plan.fired) if plan is not None else {},
    }


# ------------------------------------------------------- secure flavor --
def secure_soak_config(n_workers: int = 5, seed: int = 0,
                       comm_retries: int = 2) -> ExperimentConfig:
    """Soak config with DH secure aggregation ON.

    Five workers is the floor for the combined-drop round of
    :func:`canned_secure_plan`: with one trainer dead and one masker
    silent during recovery, each origin's threshold t = ceil(0.5·4) = 2
    still has 3 reachable share-holders; at n=4 the same round leaves
    exactly t survivors with zero slack, and any retry hiccup flips the
    gate from "recovered exactly" to "correctly discarded" — a flake,
    not a verdict.  ``max_examples_per_client`` caps the per-round work
    so the lockstep twin-federation run stays CI-sized."""
    import dataclasses

    cfg = default_soak_config(n_workers, seed=seed,
                              comm_retries=comm_retries)
    return cfg.replace(
        data=dataclasses.replace(cfg.data, max_examples_per_client=64),
        fed=dataclasses.replace(cfg.fed, secure_agg=True,
                                secure_agg_key_exchange="dh",
                                secure_agg_threshold=0.5),
        run=dataclasses.replace(cfg.run, name="secure_soak"),
    )


def canned_secure_plan(seed: int = 11) -> FaultPlan:
    """Dropout matrix for the secure-agg gate (5 workers; warmup is
    round 0, faults start at 1).  ``count=3`` on every drop outruns the
    transport's 2 retries, so the drop sticks:

    - round 1: device 0's train request is swallowed — its masked update
      never folds, so recovery must reconstruct its SESSION SECRET and
      strip its orphaned pair-mask halves;
    - round 2: device 1 trains fine but goes silent during ``unmask`` —
      the after-fold/before-unmask window; its self-mask comes back via
      t-of-n shares from the other survivors;
    - round 3: both at once — device 2 never trains, device 3 goes
      silent in recovery;
    - round 4: device 0 is deaf to ``share_setup`` — pruned before
      training, which must NOT count as a mask recovery.
    """
    return FaultPlan([
        FaultSpec(kind="drop_request", device_id="0", round=1, op="train",
                  count=3),
        FaultSpec(kind="drop_request", device_id="1", round=2, op="unmask",
                  count=3),
        FaultSpec(kind="drop_request", device_id="2", round=3, op="train",
                  count=3),
        FaultSpec(kind="drop_request", device_id="3", round=3, op="unmask",
                  count=3),
        FaultSpec(kind="drop_request", device_id="0", round=4,
                  op="share_setup", count=3),
    ], seed=seed)


def oracle_plan(plan: FaultPlan) -> FaultPlan:
    """The PLAIN-federation mirror of a secure-agg fault plan.

    The exactness gate compares the secure run against plain FedAvg over
    the same survivors, so the oracle must lose exactly the trainers the
    secure run lost — and nothing else: ``share_setup`` drops become
    ``train`` drops (a pruned device contributes nothing either way),
    ``unmask`` drops vanish (the masked update already folded; plain has
    no recovery phase to go silent in), everything else carries over."""
    import dataclasses

    specs = []
    for f in plan.faults:
        if f.op == "unmask":
            continue
        if f.op == "share_setup":
            f = dataclasses.replace(f, op="train")
        specs.append(f)
    return FaultPlan(specs, seed=plan.seed)


def run_secure_soak(rounds: int = 6, n_workers: int = 5,
                    plan: Optional[FaultPlan] = None,
                    round_timeout: float = 8.0,
                    warmup_timeout: float = 120.0,
                    atol: float = 2e-4,
                    log_fn: Optional[Callable[[dict], None]] = None) -> dict:
    """Chaos-gated exactness: a DH secure-agg federation and a plain
    FedAvg oracle run LOCKSTEP in this process — same seed, same model
    init, same data — with ``plan`` (default :func:`canned_secure_plan`)
    hitting the secure run and :func:`oracle_plan` mirroring its trainer
    losses onto the oracle.  After every post-warmup round the two
    global models must agree to ``atol`` (float32 mask-cancellation
    roundoff): masks recovered, self-masks removed, nothing leaked into
    the sum.

    The two plans install ALTERNATELY around each run_round call — the
    injector seam is process-global and both fleets share device idents,
    so a plan may only be live while its own coordinator is talking.

    No ``evaluate_per_client`` here: per-client statistics are exactly
    what secure aggregation hides, and the coordinator refuses."""
    import dataclasses

    import jax
    import numpy as np

    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker

    if rounds < 2:
        raise ValueError(f"rounds must be >= 2 (warmup + faulted), "
                         f"got {rounds}")
    cfg_secure = secure_soak_config(n_workers)
    cfg_plain = cfg_secure.replace(
        fed=dataclasses.replace(cfg_secure.fed, secure_agg=False),
        run=dataclasses.replace(cfg_secure.run, name="secure_soak_oracle"),
    )
    plan = plan if plan is not None else canned_secure_plan()
    plan_plain = oracle_plan(plan)

    reg = telemetry.get_registry()
    before = {name: reg.counter(name).value  # colearn: noqa(CL005): names from the catalog-declared counter tuple
              for name in _SECURE_COUNTERS}

    def flat(coord) -> np.ndarray:
        return np.concatenate([
            np.ravel(np.asarray(a))
            for a in jax.tree.leaves(coord.server_state.params)
        ])

    fleets = []      # (broker, workers, coord) per federation
    installed = False
    try:
        for cfg in (cfg_secure, cfg_plain):
            broker = MessageBroker().start()
            workers = [
                DeviceWorker(cfg, i, broker.host, broker.port).start()
                for i in range(n_workers)
            ]
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=warmup_timeout,
                                         want_evaluator=False)
            coord.enroll(min_devices=n_workers, timeout=30.0)
            coord.trainers.sort(key=lambda d: int(d.device_id))
            for w in workers:
                w.await_role(timeout=10.0)
            fleets.append((broker, workers, coord))
        (_, _, coord_s), (_, _, coord_p) = fleets

        diffs = []
        for _ in range(rounds):
            faulted = bool(coord_s.history)      # round 0 is the warmup
            if faulted:
                inject.install(plan)
                installed = True
            rec_s = coord_s.run_round()
            if installed:
                inject.uninstall()
                installed = False
            if faulted:
                inject.install(plan_plain)
                installed = True
            rec_p = coord_p.run_round()
            if installed:
                inject.uninstall()
                installed = False
            if len(coord_s.history) == 1:
                # Warmup done on both: drop to the faulted-round deadline.
                coord_s.round_timeout = round_timeout
                coord_p.round_timeout = round_timeout
            diff = float(np.max(np.abs(flat(coord_s) - flat(coord_p))))
            diffs.append(diff)
            if log_fn is not None:
                log_fn({"round": rec_s["round"], "param_diff": diff,
                        "secure": strip_timing(rec_s),
                        "oracle": strip_timing(rec_p)})
    finally:
        if installed:
            inject.uninstall()
        for broker, workers, coord in fleets:
            for w in workers:
                w.stop()
            broker.stop()
            coord.close()

    records = list(coord_s.history)
    return {
        "rounds_run": len(records),
        "records": records,
        "oracle_records": list(coord_p.history),
        "param_diffs": diffs,
        "max_param_diff": max(diffs) if diffs else float("nan"),
        "oracle_ok": bool(diffs) and all(d <= atol for d in diffs),
        "skipped_rounds": [r["round"] for r in records
                           if r.get("skipped_quorum")],
        "counters": {
            # Catalog-declared tuple (SECURE_SOAK_DELTA_COUNTERS).
            name: reg.counter(name).value - before[name]  # colearn: noqa(CL005): names from the catalog-declared counter tuple
            for name in _SECURE_COUNTERS
        },
        "faults_fired": dict(plan.fired) if plan is not None else {},
        "oracle_faults_fired": dict(plan_plain.fired),
    }


# Timing keys vary run to run; everything else in a round record must be
# byte-identical between a no-plan run and an empty-plan run (the
# fault layer's zero-cost-when-disabled contract, tests/test_chaos_soak).
_TIMING_KEYS = ("round_time_s",)


def strip_timing(rec: dict) -> dict:
    """A round record minus wall-clock fields — the byte-comparison view."""
    return {k: v for k, v in rec.items()
            if k not in _TIMING_KEYS and not k.startswith("phase_")}
