"""Chaos soak: an in-process federation run under a fault plan.

One broker, ``n_workers`` DeviceWorkers and a FederatedCoordinator run in
this process; after a fault-free warmup round (the first train request
compiles each worker's jit program — a plan must perturb steady-state
rounds, not compile time) the plan is installed and the remaining rounds
run against injected drops, delays, corrupt frames and crashes.  The
returned summary carries every round record plus the telemetry counter
deltas, so a caller (scripts/chaos_soak.py, tests/test_chaos_soak.py) can
assert the robustness machinery held rather than eyeball a log.
"""

from __future__ import annotations

from typing import Callable, Optional

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.faults import inject
from colearn_federated_learning_tpu.faults.plan import FaultPlan, FaultSpec
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

# Counters whose soak-window delta the summary reports — declared once in
# the metric catalog so this gate and CL005 can never drift apart.
from colearn_federated_learning_tpu.analysis.metric_catalog import (
    SOAK_DELTA_COUNTERS as _COUNTERS,
)


def default_soak_config(n_workers: int = 4, seed: int = 0,
                        min_cohort_fraction: float = 0.5,
                        evict_after: int = 2,
                        comm_retries: int = 2) -> ExperimentConfig:
    """Tiny CPU federation with the robustness features ON: quorum at
    half the cohort, eviction after 2 straight failures, 2 retries.

    Plain SGD (no momentum) at a calm lr: the verdict compares final
    accuracy between a faulted and a fault-free run, so the optimizer
    must converge monotonically — with momentum 0.9 at lr 0.1 the
    trajectory oscillates and "fewer updates" can land on a BETTER
    point, inverting the comparison."""
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=n_workers,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=2),
        fed=FedConfig(strategy="fedavg", rounds=10, cohort_size=0,
                      local_steps=4, batch_size=16, lr=0.05, momentum=0.0,
                      min_cohort_fraction=min_cohort_fraction),
        run=RunConfig(name="chaos_soak", backend="cpu", seed=seed,
                      evict_after=evict_after, comm_retries=comm_retries),
    )


def canned_plan(seed: int = 7) -> FaultPlan:
    """The acceptance-criteria plan against the default 4-worker soak
    (rounds are post-warmup: warmup is round 0, faults start at 1):

    - round 1: a delayed and a twice-flapped trainer — both recover
      within the round via the retry path;
    - round 2: three parallel request drops — only one survivor, below
      the 50% quorum, so the round must be an explicit no-op;
    - round 3: one corrupt reply frame — CRC failure, retried, recovered;
    - round 4: one mid-run worker crash — the device drops this round and
      every later one, and is evicted after ``evict_after`` failures.

    Rounds 5+ are fault-free: the surviving cohort gets a recovery tail
    long enough for final accuracy to re-converge toward the baseline's.
    """
    return FaultPlan([
        FaultSpec(kind="delay", device_id="0", round=1, op="train", ms=150),
        FaultSpec(kind="flap_reconnect", device_id="1", round=1, op="train",
                  count=2),
        FaultSpec(kind="drop_request", device_id="0", round=2, op="train"),
        FaultSpec(kind="drop_request", device_id="1", round=2, op="train"),
        FaultSpec(kind="drop_request", device_id="2", round=2, op="train"),
        FaultSpec(kind="corrupt_payload", device_id="1", round=3,
                  op="train"),
        FaultSpec(kind="crash_worker", device_id="3", round=4, op="train"),
    ], seed=seed)


def run_soak(rounds: int = 10, n_workers: int = 4,
             plan: Optional[FaultPlan] = None,
             round_timeout: float = 6.0,
             warmup_timeout: float = 120.0,
             config: Optional[ExperimentConfig] = None,
             log_fn: Optional[Callable[[dict], None]] = None) -> dict:
    """Run ``rounds`` federated rounds (1 fault-free warmup + the rest
    under ``plan``) and return a summary dict: ``records`` (every round
    record, in order), ``skipped_rounds``, ``evicted``, per-counter
    deltas under ``counters``, the plan's ``faults_fired`` ledger, and a
    fault-free final ``weighted_acc``/``weighted_loss`` over the
    surviving trainers' own shards."""
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker

    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    config = config or default_soak_config(n_workers)
    reg = telemetry.get_registry()
    # Iterates a catalog-declared tuple (SOAK_DELTA_COUNTERS): every name
    # is validated at declaration, so the non-literal lookup is safe.
    before = {name: reg.counter(name).value  # colearn: noqa(CL005)
              for name in _COUNTERS}
    _LABELED = "fault.injected_total{"
    labeled_before = {k: v for k, v in reg.snapshot().items()
                      if k.startswith(_LABELED)}

    broker = MessageBroker().start()
    workers = []
    coord = None
    installed = False
    try:
        workers = [
            DeviceWorker(config, i, broker.host, broker.port).start()
            for i in range(n_workers)
        ]
        coord = FederatedCoordinator(config, broker.host, broker.port,
                                     round_timeout=warmup_timeout,
                                     want_evaluator=False)
        coord.enroll(min_devices=n_workers, timeout=30.0)
        # Announcement arrival order is a thread race; aggregation folds
        # in trainer order, so sort for run-to-run byte-identical records.
        coord.trainers.sort(key=lambda d: int(d.device_id))
        for w in workers:
            w.await_role(timeout=10.0)

        rec = coord.run_round()                      # warmup (round 0)
        if log_fn is not None:
            log_fn(rec)
        coord.round_timeout = round_timeout
        if plan is not None:
            inject.install(plan)
            installed = True
        for _ in range(rounds - 1):
            rec = coord.run_round()
            if log_fn is not None:
                log_fn(rec)
        if installed:
            inject.uninstall()
            installed = False
        # Scored AFTER uninstall: the verdict metric must measure what
        # the faults did to the MODEL, not be corrupted by them.  Back on
        # the generous deadline — the first self_eval compiles.
        coord.round_timeout = warmup_timeout
        per_client = coord.evaluate_per_client()
    finally:
        if installed:
            inject.uninstall()
        for w in workers:
            w.stop()
        broker.stop()
        if coord is not None:
            coord.close()

    records = list(coord.history)
    return {
        "rounds_run": len(records),
        "records": records,
        "completed_rounds": [r["round"] for r in records
                             if r["completed"] > 0
                             and not r.get("skipped_quorum")],
        "skipped_rounds": [r["round"] for r in records
                           if r.get("skipped_quorum")],
        "evicted": sorted({d for r in records for d in r["evicted"]}),
        "weighted_acc": per_client.get("weighted_acc"),
        "weighted_loss": per_client.get("weighted_loss"),
        # device_id -> final own-shard accuracy.  Verdicts that compare a
        # faulted run against a baseline must intersect on the devices
        # BOTH runs still have (eviction shrinks the faulted eval set).
        "per_client_acc": per_client.get("per_client", {}),
        "counters": {
            # Same catalog-declared tuple as `before` above.
            name: reg.counter(name).value - before[name]  # colearn: noqa(CL005)
            for name in _COUNTERS
        },
        # Per-(device, kind) injection deltas, worst offender first — the
        # device/kind labels the injector attaches to fault.injected_total.
        "top_faults": sorted(
            ({"label": k[len(_LABELED) - 1:], "count": delta}
             for k, v in reg.snapshot().items()
             if k.startswith(_LABELED)
             and (delta := v - labeled_before.get(k, 0)) > 0),
            key=lambda t: (-t["count"], t["label"])),
        "faults_fired": dict(plan.fired) if plan is not None else {},
    }


# Timing keys vary run to run; everything else in a round record must be
# byte-identical between a no-plan run and an empty-plan run (the
# fault layer's zero-cost-when-disabled contract, tests/test_chaos_soak).
_TIMING_KEYS = ("round_time_s",)


def strip_timing(rec: dict) -> dict:
    """A round record minus wall-clock fields — the byte-comparison view."""
    return {k: v for k, v in rec.items()
            if k not in _TIMING_KEYS and not k.startswith("phase_")}
