"""Checkpoint/resume for federated rounds (orbax/tensorstore).

The reference has nothing beyond ``torch.save`` (SURVEY.md §5
"Checkpoint/resume").  The rebuild checkpoints the global server state
(params + server-optimizer moments + round counter) with orbax — sharded
arrays stream to tensorstore without host gathering, so the same code path
works from one chip to a multi-host pod — plus the JSON round history, so a
killed experiment resumes exactly where it stopped.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

import orbax.checkpoint as ocp

from colearn_federated_learning_tpu.telemetry import registry as _metrics


class RoundCheckpointer:
    """Save/restore (server_state, history) keyed by round number."""

    @classmethod
    def for_run(cls, run_config) -> "RoundCheckpointer":
        """Checkpointer for a RunConfig — the one place the
        checkpoint-dir-required validation lives (engine + coordinator)."""
        if not run_config.checkpoint_dir:
            raise ValueError("config.run.checkpoint_dir is not set")
        return cls(run_config.checkpoint_dir)

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, server_state: Any, history: list[dict]) -> None:
        t0 = time.perf_counter()
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(server_state),
                history=ocp.args.JsonSave(history),
            ),
        )
        self._mgr.wait_until_finished()
        reg = _metrics.get_registry()
        reg.counter("ckpt.saves_total").inc()
        reg.histogram("ckpt.save_s").observe(time.perf_counter() - t0)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, target_state: Any, step: Optional[int] = None):
        """Restore into the structure of ``target_state`` (an existing
        ServerState provides sharding/dtype/treedef).  Returns
        ``(server_state, history, step)``."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        t0 = time.perf_counter()
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(target_state),
                history=ocp.args.JsonRestore(),
            ),
        )
        reg = _metrics.get_registry()
        reg.counter("ckpt.restores_total").inc()
        reg.histogram("ckpt.restore_s").observe(time.perf_counter() - t0)
        return restored["state"], list(restored["history"]), step

    def close(self) -> None:
        self._mgr.close()
