"""Crash-consistent shard-wise streaming checkpoint.

The orbax path (ckpt/manager.py) is correct but monolithic at the edges:
restore re-materializes the whole tree host-side before re-sharding, and
nothing in the format lets a kill mid-save be reasoned about shard by
shard.  This module is the shard-NATIVE durable plane:

- SAVE streams one shard file at a time: each distinct device shard of
  the (PR 9) sharded server state writes its own ``shard_<j>.npz`` —
  slice bytes read per-shard straight off the device
  (``partition.host_leaf`` semantics, counted in
  ``comm.gather_bytes_avoided_total``) — via the repo's atomic
  tmp + fsync + ``os.replace`` idiom.  The full tree is NEVER
  materialized on one host.
- A generation ``manifest.json`` (CRC32 + size of every file, per-leaf
  slice map) is written atomically and fsynced LAST — the commit marker,
  extending ``ckpt/wal.py``'s ordering discipline to heavyweight state.
  A kill at any byte leaves the previous complete generation restorable.
- RESTORE walks generations newest-first and falls BACK a generation on
  any torn/missing/CRC-bad file instead of crashing, counting each
  discard in ``ckpt.generations_discarded_total{reason}``.  Leaves are
  re-assembled one at a time (transient per-leaf host buffer, never the
  full tree) and re-cut onto the CURRENT mesh through the restore
  template's own sharding + ``make_array_from_single_device_arrays`` —
  so a tp=2 save resumes bitwise-correct on tp=1 and vice versa
  (``ckpt.resharded_resumes_total``).

The class is API-compatible with :class:`~.manager.RoundCheckpointer`
(``for_run`` / ``save`` / ``restore`` / ``latest_step`` / ``close``), so
both socket coordinators swap implementations on ``RunConfig
.ckpt_stream`` without touching the WAL-reconciliation logic around it.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import tempfile
import time
import zlib
from typing import Any, Optional

import numpy as np

from colearn_federated_learning_tpu.telemetry import registry as _metrics
from colearn_federated_learning_tpu.utils.serialization import (
    _dtype_entry,
    _resolve_dtype,
)

MANIFEST = "manifest.json"
HISTORY = "history.json"
_GEN_RE = re.compile(r"^gen_(\d{8})$")

# Recovery-matrix discard reasons (ckpt.generations_discarded_total labels).
R_MISSING_MANIFEST = "missing_manifest"
R_TORN_MANIFEST = "torn_manifest"
R_MISSING_SHARD = "missing_shard"
R_TORN_SHARD = "torn_shard"
R_CRC_MISMATCH = "crc_mismatch"


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _normalize_index(index, shape: tuple) -> tuple[list, list]:
    """A ``devices_indices_map``/``Shard.index`` slice tuple → explicit
    ``(start, stop)`` int lists (``slice(None)`` spans the dimension)."""
    index = tuple(index) if index is not None else (slice(None),) * len(shape)
    if len(index) < len(shape):
        index = index + (slice(None),) * (len(shape) - len(index))
    starts, stops = [], []
    for dim, s in zip(shape, index):
        starts.append(0 if s.start is None else int(s.start))
        stops.append(dim if s.stop is None else int(s.stop))
    return starts, stops


def _file_crc(path: str) -> tuple[int, int]:
    """(crc32, size) of a file, streamed in chunks."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return crc, size


def _atomic_write(path: str, write_fn) -> tuple[int, int]:
    """Atomic durable write via the repo idiom (same-dir temp file,
    fsync BEFORE ``os.replace``).  ``write_fn(fileobj)`` produces the
    bytes; returns the committed file's ``(crc32, size)``."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-", suffix=".part")
    try:
        with os.fdopen(fd, "w+b") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        crc, size = _file_crc(tmp)
        os.replace(tmp, path)
        return crc, size
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _leaf_shards(leaf: Any) -> tuple[tuple, Any, list]:
    """One state leaf → ``(shape, dtype, [(starts, stops, data_fn)])`` with
    duplicate (replicated) device shards collapsed to one entry.  The
    ``data_fn`` defers the D2H read until the owning shard FILE is being
    written, so at most one shard's bytes are resident at a time."""
    import jax

    if isinstance(leaf, jax.Array):
        shape = tuple(leaf.shape)
        dtype = np.dtype(leaf.dtype)
        out, seen = [], set()
        for sh in leaf.addressable_shards:
            starts, stops = _normalize_index(sh.index, shape)
            key = (tuple(starts), tuple(stops))
            if key in seen:          # replicated copies: write once
                continue
            seen.add(key)
            out.append((starts, stops,
                        lambda data=sh.data: np.asarray(data)))
        return shape, dtype, out
    arr = np.asarray(leaf)
    shape = tuple(arr.shape)
    starts, stops = _normalize_index(None, shape)
    return shape, arr.dtype, [(starts, stops, lambda a=arr: a)]


def _digest_update(h, dtype: np.dtype, shape: tuple, buf: np.ndarray) -> None:
    h.update(repr((dtype.name, shape)).encode())
    h.update(np.ascontiguousarray(buf).tobytes())


class StreamingCheckpointer:
    """Shard-wise crash-consistent checkpoint under ``directory``.

    Layout: one ``gen_<step>`` directory per generation holding
    ``shard_<j>.npz`` files (raw uint8 slice buffers keyed ``l<leaf>``),
    ``history.json``, and the commit-marker ``manifest.json`` written
    LAST.  A directory without a valid manifest is an uncommitted
    generation and is invisible to restore."""

    @classmethod
    def for_run(cls, run_config) -> "StreamingCheckpointer":
        if not run_config.checkpoint_dir:
            raise ValueError("config.run.checkpoint_dir is not set")
        return cls(run_config.checkpoint_dir)

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        # Populated by restore(): sha256 over the restored leaves
        # (full-leaf C-order bytes, flatten order) — the bitwise identity
        # the chaos harness compares against the on-disk generation,
        # independent of the tp the state was saved OR restored at.
        self.last_restore_digest: Optional[str] = None
        # reason -> count for THIS process (the resume event surfaces it;
        # the registry counter carries the labeled totals).
        self.generations_discarded: dict[str, int] = {}

    # ------------------------------------------------------------- save --
    def _gen_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"gen_{step:08d}")

    def _generations(self) -> list[tuple[int, str]]:
        """All ``gen_*`` dirs as ``(step, path)``, newest first."""
        out = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in entries:
            m = _GEN_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        out.sort(reverse=True)
        return out

    def save(self, step: int, server_state: Any, history: list[dict]) -> None:
        """Stream ``server_state`` shard-by-shard into generation ``step``;
        the manifest commit is the LAST durable write.  Aborts injected by
        the fault plane (``stale_manifest``) leave the generation
        uncommitted and are counted ``ckpt.save_aborted_total``."""
        import jax

        from colearn_federated_learning_tpu.faults import fileplane
        from colearn_federated_learning_tpu.parallel import partition

        t0 = time.perf_counter()
        reg = _metrics.get_registry()
        gen = self._gen_dir(step)
        if os.path.isdir(gen):       # re-save of a step: start clean
            shutil.rmtree(gen)
        os.makedirs(gen)

        flat, _ = jax.tree_util.tree_flatten_with_path(server_state)
        leaves: list[dict] = []
        plans: list[list] = []       # per leaf: shard-write plan
        n_shards = 1
        avoided = 0
        for path, leaf in flat:
            shape, dtype, shards = _leaf_shards(leaf)
            leaves.append({"path": _path_str(path), "shape": list(shape),
                           "dtype": _dtype_entry(dtype), "slices": []})
            plans.append(shards)
            n_shards = max(n_shards, len(shards))
            avoided += partition.leaf_gather_avoided(leaf)
        if avoided:
            reg.counter("comm.gather_bytes_avoided_total").inc(avoided)

        files: dict[str, dict] = {}
        for j in range(n_shards):
            fname = f"shard_{j:05d}.npz"
            fpath = os.path.join(gen, fname)
            fileplane.ckpt_slow_io(j, step, "shard")
            buffers: dict[str, np.ndarray] = {}
            for i, shards in enumerate(plans):
                if j >= len(shards):
                    continue
                starts, stops, data_fn = shards[j]
                arr = np.ascontiguousarray(data_fn())
                key = f"l{i:05d}"
                buffers[key] = arr.reshape(-1).view(np.uint8)
                leaves[i]["slices"].append(
                    {"file": fname, "key": key,
                     "start": starts, "stop": stops})
            crc, size = _atomic_write(
                fpath, lambda f, b=buffers: np.savez(f, **b))
            fileplane.ckpt_torn_shard(fpath, j, step)
            files[fname] = {"crc": crc, "size": size}
            reg.counter("ckpt.shards_written_total").inc()

        fileplane.ckpt_slow_io(-1, step, "history")
        hist_bytes = json.dumps(history).encode()
        crc, size = _atomic_write(
            os.path.join(gen, HISTORY), lambda f: f.write(hist_bytes))
        files[HISTORY] = {"crc": crc, "size": size}

        if fileplane.ckpt_stale_manifest(step):
            # Injected kill-before-commit: the shard files exist but the
            # generation never commits — exactly what a SIGKILL between
            # the last shard fsync and the manifest replace leaves.
            reg.counter("ckpt.save_aborted_total").inc()
            return
        fileplane.ckpt_slow_io(-1, step, "manifest")
        manifest = {"format": 1, "step": int(step),
                    "saved_shards": int(n_shards),
                    "leaves": leaves, "files": files}
        man_bytes = json.dumps(manifest, separators=(",", ":")).encode()
        _atomic_write(os.path.join(gen, MANIFEST),
                      lambda f: f.write(man_bytes))
        self._prune(step)
        reg.counter("ckpt.saves_total").inc()
        reg.histogram("ckpt.save_s").observe(time.perf_counter() - t0)

    def _prune(self, committed_step: int) -> None:
        """Keep the newest ``max_to_keep`` committed generations; drop
        everything else BELOW the fresh commit (an uncommitted dir above
        it would be a concurrent writer's — leave it alone)."""
        kept = 0
        for step, path in self._generations():
            if step > committed_step:
                continue
            committed = os.path.exists(os.path.join(path, MANIFEST))
            if committed and kept < self.max_to_keep:
                kept += 1
                continue
            shutil.rmtree(path, ignore_errors=True)

    # ---------------------------------------------------------- restore --
    def _validate(self, gen: str) -> tuple[Optional[dict], Optional[str]]:
        """(manifest, None) for a complete generation, else (None, reason)."""
        mpath = os.path.join(gen, MANIFEST)
        if not os.path.exists(mpath):
            return None, R_MISSING_MANIFEST
        try:
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
        except (json.JSONDecodeError, UnicodeDecodeError, OSError):
            return None, R_TORN_MANIFEST
        if not isinstance(manifest, dict) or "files" not in manifest:
            return None, R_TORN_MANIFEST
        for fname, rec in manifest["files"].items():
            fpath = os.path.join(gen, fname)
            if not os.path.exists(fpath):
                return None, R_MISSING_SHARD
            crc, size = _file_crc(fpath)
            if size != rec["size"]:
                return None, R_TORN_SHARD
            if crc != rec["crc"]:
                return None, R_CRC_MISMATCH
        return manifest, None

    def _latest_valid(self, step: Optional[int] = None
                      ) -> tuple[int, str, dict]:
        """Newest fully-committed generation (≤ ``step`` when given),
        discarding — with labeled counts — every torn one on the way."""
        reg = _metrics.get_registry()
        for gstep, gen in self._generations():
            if step is not None and gstep != step:
                continue
            manifest, reason = self._validate(gen)
            if manifest is not None:
                return gstep, gen, manifest
            reg.counter("ckpt.generations_discarded_total",
                        labels={"reason": reason}).inc()
            self.generations_discarded[reason] = (
                self.generations_discarded.get(reason, 0) + 1)
        raise FileNotFoundError(
            f"no restorable checkpoint generation under {self.directory}")

    def latest_step(self) -> Optional[int]:
        try:
            step, _, _ = self._latest_valid()
        except FileNotFoundError:
            return None
        return step

    def restore(self, target_state: Any, step: Optional[int] = None):
        """Restore into the structure/sharding of ``target_state`` —
        the template's OWN device layout is the re-shard target, so the
        same generation restores onto any current mesh.  Returns
        ``(server_state, history, step)``."""
        import jax

        t0 = time.perf_counter()
        reg = _metrics.get_registry()
        gstep, gen, manifest = self._latest_valid(step)

        with open(os.path.join(gen, HISTORY), encoding="utf-8") as f:
            history = json.load(f)

        flat, treedef = jax.tree_util.tree_flatten_with_path(target_state)
        if len(flat) != len(manifest["leaves"]):
            raise ValueError(
                f"checkpoint generation {gstep} holds "
                f"{len(manifest['leaves'])} leaves; restore template has "
                f"{len(flat)}")
        readers: dict[str, Any] = {}
        digest = hashlib.sha256()
        resharded = False
        out = []
        try:
            for (path, tmpl), rec in zip(flat, manifest["leaves"]):
                shape = tuple(rec["shape"])
                dtype = _resolve_dtype(rec["dtype"])
                if shape != tuple(np.shape(tmpl)):
                    raise ValueError(
                        f"leaf {rec['path']!r}: saved shape {shape} != "
                        f"template shape {tuple(np.shape(tmpl))}")
                # Transient FULL-LEAF host buffer (one leaf at a time —
                # never the whole tree): assembled from the saved slices,
                # hashed for the bitwise identity, then re-cut onto the
                # template's shard layout.
                buf = np.empty(shape, dtype)
                for sl in rec["slices"]:
                    if sl["file"] not in readers:
                        readers[sl["file"]] = np.load(
                            os.path.join(gen, sl["file"]))
                    raw = readers[sl["file"]][sl["key"]]
                    sub = tuple(slice(a, b)
                                for a, b in zip(sl["start"], sl["stop"]))
                    sub_shape = tuple(b - a for a, b
                                      in zip(sl["start"], sl["stop"]))
                    buf[sub] = raw.view(dtype).reshape(sub_shape)
                _digest_update(digest, dtype, shape, buf)
                out.append(self._place(tmpl, buf))
                tmpl_n = (self._n_distinct(tmpl)
                          if isinstance(tmpl, jax.Array) else 1)
                if (len(rec["slices"]) != tmpl_n
                        and (len(rec["slices"]) > 1 or tmpl_n > 1)):
                    resharded = True
        finally:
            for r in readers.values():
                r.close()
        if resharded:
            reg.counter("ckpt.resharded_resumes_total").inc()
        self.last_restore_digest = digest.hexdigest()
        reg.counter("ckpt.restores_total").inc()
        reg.histogram("ckpt.restore_s").observe(time.perf_counter() - t0)
        return jax.tree_util.tree_unflatten(treedef, out), history, gstep

    @staticmethod
    def _n_distinct(leaf) -> int:
        shape = tuple(leaf.shape)
        seen = set()
        for sh in leaf.addressable_shards:
            starts, stops = _normalize_index(sh.index, shape)
            seen.add((tuple(starts), tuple(stops)))
        return len(seen)

    @staticmethod
    def _place(tmpl: Any, buf: np.ndarray) -> Any:
        """One assembled host leaf → the template's placement: sharded
        leaves are cut per target shard and placed on each shard's OWN
        device (``make_array_from_single_device_arrays`` — no device ever
        receives more than its slice); host leaves pass through."""
        import jax

        if isinstance(tmpl, jax.Array):
            sharding = tmpl.sharding
            shards = tmpl.addressable_shards
            distinct = {tuple(_normalize_index(sh.index, buf.shape)[0])
                        for sh in shards}
            if len(shards) <= 1 or len(distinct) <= 1:
                return jax.device_put(buf, sharding)
            arrays = [
                jax.device_put(np.ascontiguousarray(buf[sh.index]),
                               sh.device)
                for sh in shards
            ]
            return jax.make_array_from_single_device_arrays(
                buf.shape, sharding, arrays)
        if isinstance(tmpl, np.ndarray):
            return buf
        if np.ndim(tmpl) == 0 and not isinstance(tmpl, np.generic):
            # Python scalar in the template (e.g. the accountant's rdp
            # float): hand back the same Python type.
            return type(tmpl)(buf.reshape(()).item())
        return buf

    def close(self) -> None:
        pass


# ----------------------------------------------------- harness-side loads --

def load_generation_host(directory: str, step: Optional[int] = None
                         ) -> tuple[dict, int, str]:
    """Template-free load of the newest committed generation: ``(leaf
    path -> full host array, step, digest)``.  The digest matches
    :attr:`StreamingCheckpointer.last_restore_digest` for the same
    generation — the chaos harness's bitwise-restore oracle."""
    ckpt = StreamingCheckpointer(directory)
    gstep, gen, manifest = ckpt._latest_valid(step)
    readers: dict[str, Any] = {}
    digest = hashlib.sha256()
    out: dict[str, np.ndarray] = {}
    try:
        for rec in manifest["leaves"]:
            shape = tuple(rec["shape"])
            dtype = _resolve_dtype(rec["dtype"])
            buf = np.empty(shape, dtype)
            for sl in rec["slices"]:
                if sl["file"] not in readers:
                    readers[sl["file"]] = np.load(
                        os.path.join(gen, sl["file"]))
                raw = readers[sl["file"]][sl["key"]]
                sub = tuple(slice(a, b)
                            for a, b in zip(sl["start"], sl["stop"]))
                sub_shape = tuple(b - a
                                  for a, b in zip(sl["start"], sl["stop"]))
                buf[sub] = raw.view(dtype).reshape(sub_shape)
            _digest_update(digest, dtype, shape, buf)
            out[rec["path"]] = buf
    finally:
        for r in readers.values():
            r.close()
    return out, gstep, digest.hexdigest()
