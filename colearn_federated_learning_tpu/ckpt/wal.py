"""JSON round write-ahead log for coordinator crash recovery.

The orbax checkpoint (ckpt/manager.py) carries the heavyweight server
state; this WAL carries the lightweight durable record of WHAT each
committed round did — the round counter, the accepted-update manifest,
and the round record — one fsynced JSON line per round.  Together they
let a restarted coordinator prove which rounds are committed: a WAL
entry past the latest checkpoint step is an uncommitted round whose
server-state delta died with the process, and resume discards it.

The format is deliberately boring: append-only JSONL, ``fsync`` after
every append, torn final line tolerated on load (the log itself must
survive the SIGKILLs it exists to describe).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from colearn_federated_learning_tpu.telemetry import registry as _metrics


class RoundWal:
    """Append-only fsynced JSONL round log under the checkpoint dir."""

    FILENAME = "round_wal.jsonl"

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, self.FILENAME)
        self._f = None

    # ----------------------------------------------------------- write --
    def _handle(self):
        if self._f is None:
            self._f = open(self.path, "a", encoding="utf-8")
        return self._f

    def append(self, entry: dict) -> None:
        """Durably append one round entry (fsync before returning)."""
        f = self._handle()
        f.write(json.dumps(entry, separators=(",", ":")) + "\n")
        f.flush()
        os.fsync(f.fileno())
        _metrics.get_registry().counter("ckpt.wal_appends_total").inc()

    # ------------------------------------------------------------ read --
    def load(self) -> list[dict]:
        """All decodable entries.  A torn final line — the append that was
        in flight when the process died — is dropped and counted
        (``ckpt.wal_torn_tail_total``); a torn line anywhere else is
        corruption and raises."""
        if not os.path.exists(self.path):
            return []
        with open(self.path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        out: list[dict] = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    _metrics.get_registry().counter(
                        "ckpt.wal_torn_tail_total").inc()
                    break
                raise ValueError(
                    f"corrupt WAL entry at {self.path}:{i + 1}")
        return out

    def rewind(self, num_entries: int) -> None:
        """Atomically truncate the log to its first ``num_entries``
        entries — how resume discards uncommitted-tail rounds."""
        entries = self.load()[:num_entries]
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for e in entries:
                f.write(json.dumps(e, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self.close()
        os.replace(tmp, self.path)

    # ----------------------------------------------------------- admin --
    def committed_rounds(self) -> Optional[int]:
        """Number of logged rounds, or None when the log doesn't exist."""
        if not os.path.exists(self.path):
            return None
        return len(self.load())

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class EnrollmentLedger(RoundWal):
    """Durable admission record: WHO the coordinator ever admitted, and
    under WHICH identity public key.

    Broker-retained announcements are soft state — they die with the
    broker, replay after it restarts, and anyone who can publish can
    forge one.  This ledger is the hard state a resumed coordinator
    trusts instead: one fsynced JSON line per admission (device_id,
    address, identity pubkey, wall time), latest line per device wins.
    ``coordinator.verify_resumed_devices`` readmits a device only when
    it is in this ledger AND answers a nonce challenge under the
    recorded key.  Reuses the RoundWal machinery wholesale — append-only
    JSONL, fsync per append, torn final line tolerated on load.
    """

    FILENAME = "enroll_ledger.jsonl"

    def append(self, entry: dict) -> None:
        f = self._handle()
        f.write(json.dumps(entry, separators=(",", ":")) + "\n")
        f.flush()
        os.fsync(f.fileno())
        _metrics.get_registry().counter(
            "comm.enroll_ledger_appends_total").inc()

    def admit(self, dev) -> None:
        """Record one admission (DeviceInfo or any object with
        device_id/host/port/pubkey attributes)."""
        import time

        self.append({
            "device_id": str(dev.device_id),
            "host": str(dev.host),
            "port": int(dev.port),
            "pubkey": str(getattr(dev, "pubkey", "") or ""),
            "ts": time.time(),
        })

    def revoke(self, device_id: str) -> None:
        """Durably retract a device's admission — the challenge-on-resume
        reject path.  Latest-line-wins turns the retraction into absence
        from :meth:`devices`, so an admission appended from a replayed or
        forged announcement (the resumed enrollment records devices
        before the challenge can vet them) cannot satisfy a LATER resume
        either.  A genuine re-admission after the revocation supersedes
        it — revocation is an append, not a ban."""
        import time

        self.append({"device_id": str(device_id), "revoked": True,
                     "ts": time.time()})

    def devices(self) -> dict:
        """``device_id -> latest admission record``.  Re-announcing with
        a fresh key supersedes the old binding (last line wins), so key
        rotation is an append, not an edit; a revocation line erases the
        device until its next admission."""
        out: dict[str, dict] = {}
        for entry in self.load():
            did = str(entry.get("device_id", ""))
            if not did:
                continue
            if entry.get("revoked"):
                out.pop(did, None)
            else:
                out[did] = entry
        return out
