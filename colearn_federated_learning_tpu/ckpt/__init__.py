from colearn_federated_learning_tpu.ckpt.manager import RoundCheckpointer
from colearn_federated_learning_tpu.ckpt.wal import EnrollmentLedger, RoundWal

__all__ = ["RoundCheckpointer", "RoundWal", "EnrollmentLedger"]
