from colearn_federated_learning_tpu.ckpt.manager import RoundCheckpointer
from colearn_federated_learning_tpu.ckpt.streaming import (
    StreamingCheckpointer,
    load_generation_host,
)
from colearn_federated_learning_tpu.ckpt.wal import EnrollmentLedger, RoundWal

__all__ = ["RoundCheckpointer", "StreamingCheckpointer",
           "load_generation_host", "RoundWal", "EnrollmentLedger"]
