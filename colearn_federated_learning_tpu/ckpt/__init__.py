from colearn_federated_learning_tpu.ckpt.manager import RoundCheckpointer

__all__ = ["RoundCheckpointer"]
