from colearn_federated_learning_tpu.ckpt.manager import RoundCheckpointer
from colearn_federated_learning_tpu.ckpt.wal import RoundWal

__all__ = ["RoundCheckpointer", "RoundWal"]
