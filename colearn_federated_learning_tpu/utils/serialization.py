"""Flat-file pytree serialization (npz) for the cross-silo file/wire plane.

The reference moves model state between processes as pickled PySyft tensors
over websockets (SURVEY.md §1 "Communication").  The rebuild's exchange
format is a plain ``.npz``: each leaf stored under its ``/``-joined tree
path, plus ``__meta__`` JSON for scalars (weights, round index).  It is
mmap-friendly, language-neutral, and the same payload is used by the offline
``colearn aggregate`` flow and the TCP federation transport (comm/).
"""

from __future__ import annotations

import io
import json
from typing import Any

import numpy as np

_META = "__meta__"


def _flatten(tree: Any, prefix: str = "") -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            if "/" in str(k):
                raise ValueError(f"key {k!r} contains the path separator '/'")
            out.update(_flatten(tree[k], f"{prefix}{k}/"))
        return out
    if isinstance(tree, (list, tuple)):
        # np.asarray would silently STACK a list of leaves into one array and
        # the round trip would change tree structure; refuse loudly instead.
        # (The wire format is dict-of-arrays; index lists/tuples by position.)
        raise TypeError(
            f"cannot serialize {type(tree).__name__} node at {prefix or '/'!r}: "
            "convert to a dict with string keys first"
        )
    out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> Any:
    tree: dict = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def save_pytree_npz(path_or_file, tree: Any, meta: dict | None = None) -> None:
    flat = _flatten(tree)
    flat[_META] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    ).copy()
    np.savez(path_or_file, **flat)


def load_pytree_npz(path_or_file) -> tuple[Any, dict]:
    z = np.load(path_or_file)
    meta = json.loads(bytes(z[_META]).decode()) if _META in z.files else {}
    flat = {k: z[k] for k in z.files if k != _META}
    return _unflatten(flat), meta


def pytree_to_bytes(tree: Any, meta: dict | None = None) -> bytes:
    buf = io.BytesIO()
    save_pytree_npz(buf, tree, meta)
    return buf.getvalue()


def bytes_to_pytree(data: bytes) -> tuple[Any, dict]:
    return load_pytree_npz(io.BytesIO(data))
